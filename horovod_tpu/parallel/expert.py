"""Expert parallelism: Switch-style Mixture-of-Experts with all-to-all.

The reference framework has no MoE (CNN-era, SURVEY §2.7), but its
``alltoall`` collective is exactly the EP dispatch primitive — this module
is the TPU-native layer built on it. Top-1 (Switch) routing with a fixed
per-expert capacity, compiled entirely into the XLA program:

1. route: ``softmax(x @ router)`` → argmax expert + gate probability;
2. dispatch: scatter tokens into a static ``[E, capacity, C]`` buffer
   (position = running count within the chosen expert; overflow tokens
   are dropped — they ride the residual connection, standard Switch
   behavior);
3. exchange: one tiled ``lax.all_to_all`` re-shards the buffer from
   expert-major [E, cap, C] to ``[E/n, n·cap, C]`` — each rank receives
   every rank's tokens for ITS experts (the reference's MPI_Alltoallv
   analogue, riding ICI);
4. expert FFN: batched einsum over the local experts' weights;
5. exchange back + combine: tokens return to their source rank and are
   scaled by the gate (straight-through for the router's gradient).

The load-balancing auxiliary loss (Switch eq. 4: E · Σ_e f_e · P_e) is
returned alongside; callers add ``aux_weight * aux`` to the task loss.

Everything is static-shaped; outside ``shard_map`` (or with a 1-sized
axis) the same code runs with all experts local and no collective.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .sequence import _axis_size


def _route(x, router_kernel, E):
    """Shared Switch top-1 routing: returns ``(expert, gate, aux,
    onehot)`` — argmax expert id [N], gate probability [N], the
    load-balancing aux loss (Switch eq. 4: E · Σ_e f_e · P_e), and the
    int32 [N, E] expert one-hot (built once; callers reuse it)."""
    probs = jax.nn.softmax(
        jnp.einsum("nc,ce->ne", x.astype(jnp.float32),
                   router_kernel.astype(jnp.float32)), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                    # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)    # [N, E]
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    aux = E * jnp.sum(frac * jnp.mean(probs, axis=0))
    return expert, gate, aux, onehot


def _check_experts(router_kernel, E_local, n):
    E = E_local * n
    if router_kernel.shape[-1] != E:
        raise ValueError(
            f"router has {router_kernel.shape[-1]} experts but "
            f"E_local {E_local} x axis size {n} = {E}")
    return E


def switch_moe(x, router_kernel, w1, b1, w2, b2, *,
               axis: Optional[str] = None,
               capacity_factor: float = 1.25):
    """Top-1 MoE on flattened tokens ``x`` [N, C].

    ``router_kernel``: [C, E_global]; expert weights carry the LOCAL
    expert dim: ``w1`` [E_local, C, F], ``b1`` [E_local, F], ``w2``
    [E_local, F, C], ``b2`` [E_local, C]. ``E_global = E_local · n``
    where n is the bound size of ``axis``. Returns ``(y [N, C], aux)``.
    """
    N, C = x.shape
    n = _axis_size(axis) if axis else 1
    E = _check_experts(router_kernel, w1.shape[0], n)
    # Per-expert capacity: every rank contributes N tokens to E experts.
    capacity = max(1, int(N * capacity_factor / E + 0.9999))

    expert, gate, aux, onehot = _route(x, router_kernel, E)

    # Position of each token within its expert's queue.
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1
    keep = pos < capacity                                  # overflow drop
    pos_c = jnp.minimum(pos, capacity - 1)

    dispatch = jnp.zeros((E, capacity, C), x.dtype).at[expert, pos_c].add(
        jnp.where(keep[:, None], x, 0))

    if n > 1:
        # [E, cap, C] → [E_local, n·cap, C]: rank r keeps/receives every
        # rank's buffer rows for ITS local experts.
        recv = lax.all_to_all(dispatch, axis, split_axis=0, concat_axis=1,
                              tiled=True)
    else:
        recv = dispatch                                    # all local

    h = jnp.einsum("ekc,ecf->ekf", recv, w1) + b1[:, None]
    h = nn.gelu(h)
    out = jnp.einsum("ekf,efc->ekc", h, w2) + b2[:, None]

    if n > 1:
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)                   # back home

    y = out[expert, pos_c]                                 # [N, C]
    y = jnp.where(keep[:, None], y, 0) * gate[:, None].astype(y.dtype)
    return y.astype(x.dtype), aux


def switch_moe_ragged(x, router_kernel, w1, b1, w2, b2, *,
                      axis: Optional[str] = None,
                      capacity_factor: float = 1.25,
                      pair_capacity_factor: float = 2.0):
    """Top-1 MoE with *ragged* all-to-all dispatch (uneven per-rank
    splits, reference: MPI_Alltoallv path, operations.cc:1031-1092).

    Same signature/returns as :func:`switch_moe`, different dispatch
    protocol.  Instead of a fixed ``[E, capacity, C]`` buffer where each
    (sender, expert) pair has a hard quota, tokens are sorted by
    destination *rank* and exchanged with
    :func:`~horovod_tpu.ops.collective_ops.alltoall_ragged`; the
    receiver then pools each local expert's capacity across ALL senders.
    Drops now happen only when

    * a single (sender → rank) pair exceeds
      ``pair_capacity_factor * N / n`` rows (gross rank-level skew), or
    * one expert *globally* exceeds ``capacity_factor * N * n / E``
      rows (the same total as :func:`switch_moe`, but pooled instead of
      per-sender),

    which is strictly laxer than the fixed path's per-(sender, expert)
    quota — the capacity-overflow cliff VERDICT r4 flagged.  Dropped
    tokens still emit zeros and ride the residual.
    """
    N, C = x.shape
    n = _axis_size(axis) if axis else 1
    E_local = w1.shape[0]
    E = _check_experts(router_kernel, E_local, n)
    # Pooled per-local-expert capacity: global token count over global
    # expert count, same total buffer bytes as the fixed path.
    local_cap = max(1, int(N * n * capacity_factor / E + 0.9999))

    expert, gate, aux, _ = _route(x, router_kernel, E)

    dest = (expert // E_local).astype(jnp.int32)           # owning rank
    e_loc = (expert % E_local).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)                 # dest-major
    xs, es, blk = x[order], e_loc[order], dest[order]
    splits = jnp.sum(jax.nn.one_hot(dest, n, dtype=jnp.int32), axis=0)

    if n > 1:
        pair_cap = max(1, min(N, int(N * pair_capacity_factor / n
                                     + 0.9999)))
        from ..ops.collective_ops import alltoall_ragged
        recv_x, recv_splits = alltoall_ragged(
            xs, splits, capacity=pair_cap, axes=axis)
        # Same splits as the x exchange: reuse its negotiated counts.
        recv_e, _ = alltoall_ragged(es, splits, capacity=pair_cap,
                                    axes=axis, recv_splits=recv_splits)
    else:
        pair_cap = N
        recv_x, recv_splits, recv_e = xs, splits, es

    R = recv_x.shape[0]                                    # n * pair_cap
    rvalid = jnp.arange(R) < jnp.sum(recv_splits)          # compacted
    re = jnp.where(rvalid, recv_e, 0)

    # Running position within each local expert's pooled queue.
    oh = jax.nn.one_hot(re, E_local, dtype=jnp.int32) * \
        rvalid[:, None].astype(jnp.int32)
    pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=-1) - 1
    keep = rvalid & (pos >= 0) & (pos < local_cap)
    pos_c = jnp.clip(pos, 0, local_cap - 1)

    buf = jnp.zeros((E_local, local_cap, C), x.dtype).at[re, pos_c].add(
        jnp.where(keep[:, None], recv_x, 0))

    h = jnp.einsum("ekc,ecf->ekf", buf, w1) + b1[:, None]
    h = nn.gelu(h)
    out = jnp.einsum("ekf,efc->ekc", h, w2) + b2[:, None]

    # Back to the received-row order (dropped rows -> zeros), then home.
    rows_out = out[re, pos_c] * keep[:, None].astype(out.dtype)
    sp_c = jnp.minimum(splits, pair_cap)
    if n > 1:
        # Return-trip recv counts are our own clamped sends — no
        # negotiation needed.
        back, _ = alltoall_ragged(rows_out, recv_splits, capacity=pair_cap,
                                  axes=axis, recv_splits=sp_c)
    else:
        back = rows_out

    # Sorted-token -> compact return position: block r of the return
    # buffer holds min(splits[r], pair_cap) rows in send order.
    boffs = jnp.cumsum(sp_c) - sp_c
    offs = jnp.cumsum(splits) - splits
    p = jnp.arange(N)
    p_in = p - offs[blk]
    sent = p_in < pair_cap
    cpos = jnp.where(sent, boffs[blk] + p_in, 0)
    y_sorted = jnp.where(sent[:, None], back[cpos], 0)
    inv = jnp.argsort(order)
    y = y_sorted[inv] * gate[:, None].astype(y_sorted.dtype)
    return y.astype(x.dtype), aux


class SwitchMoE(nn.Module):
    """Flax module: Switch-MoE FFN (drop-in for a dense MLP block).

    ``num_experts`` is GLOBAL; with ``ep_axis`` bound inside shard_map
    each rank creates only its ``num_experts / n`` experts' weights (the
    router is replicated). See ``ep_split_params`` for slicing a dense
    (world-1) checkpoint into per-rank shards.
    """

    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    dtype: jnp.dtype = jnp.float32
    kernel_init_std: float = 0.02
    # Ragged (uneven alltoall) dispatch: pools expert capacity across
    # senders, removing the per-(sender, expert) overflow cliff.
    # pair_capacity_factor bounds the (sender -> rank) block at
    # pair_capacity_factor * N / n rows (ragged path only).
    ragged: bool = False
    pair_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        n = _axis_size(self.ep_axis) if self.ep_axis else 1
        if self.num_experts % n:
            raise ValueError(
                f"num_experts {self.num_experts} not divisible by "
                f"ep axis size {n}")
        e_local = self.num_experts // n
        init = nn.initializers.normal(self.kernel_init_std)
        router = self.param("router", init, (C, self.num_experts),
                            jnp.float32)
        w1 = self.param("w1", init, (e_local, C, self.d_ff), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e_local, self.d_ff),
                        jnp.float32)
        w2 = self.param("w2", init, (e_local, self.d_ff, C), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e_local, C),
                        jnp.float32)
        kw = {}
        if self.ragged:
            moe_fn = switch_moe_ragged
            kw["pair_capacity_factor"] = self.pair_capacity_factor
        else:
            moe_fn = switch_moe
        y, aux = moe_fn(
            x.reshape(B * T, C),
            router, w1.astype(self.dtype), b1.astype(self.dtype),
            w2.astype(self.dtype), b2.astype(self.dtype),
            axis=self.ep_axis, capacity_factor=self.capacity_factor, **kw)
        self.sow("intermediates", "moe_aux_loss", aux)
        return y.reshape(B, T, C)


def _ep_rule(path: str):
    """Expert weights live under a SwitchMoE module ('moe' in GPT blocks)
    — anchor on the module name so unrelated params that happen to be
    called w1/b1/w2/b2 elsewhere are never mis-sharded."""
    mod, _, leaf = path.rpartition("/")
    if leaf in ("w1", "b1", "w2", "b2") and mod.split("/")[-1] == "moe":
        return lambda a, n, i: jnp.split(a, n, axis=0)[i]
    return None


def ep_split_params(params, n: int):
    """Dense (world-1) SwitchMoE params → (sharded, replicated) trees,
    same contract as :func:`horovod_tpu.parallel.tensor.tp_split_params`:
    expert weights (leading expert dim) are stacked per-rank shards, the
    router (and everything else) stays in the replicated tree."""
    from .tensor import split_params_by_rule

    return split_params_by_rule(params, n, _ep_rule)
