"""Ray integration (reference: horovod/ray/, SURVEY §2.5).

``RayExecutor`` runs a horovod_tpu world on Ray actors placed through a
**placement group** — one bundle per host, workers packed into their host's
bundle — the TPU-shaped equivalent of the reference's ``NodeColocator``
(ray/runner.py:48-175): chips on one host share ICI, so local ranks must be
colocated. The ``Coordinator`` (reference: ray/runner.py:178-248) collects
each worker's hostname, assigns ranks host-grouped, and builds the launcher
env contract. ``ElasticRayExecutor`` (reference: ray/elastic.py:61-300)
couples the elastic driver to Ray's cluster state through
``RayHostDiscovery``; elastic workers receive only their identity
(hostname, local_rank) plus the driver-service coordinates — rank/size
arrive via rendezvous, so they stay correct across resizes.

ray is not bundled: actor machinery is gated at call time, while the
Coordinator's assignment logic stays importable and unit-testable.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery


def _require_ray():
    try:
        import ray

        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires ray; install ray or use "
            "horovod_tpu.runner / horovod_tpu.spark") from e


def _pg_scheduling_strategy(pg, bundle_index: int):
    """PlacementGroupSchedulingStrategy for current ray; None if the API is
    unavailable (the caller then falls back to plain scheduling)."""
    try:
        from ray.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        return PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=bundle_index)
    except ImportError:  # pragma: no cover - very old ray
        return None


class Coordinator:
    """Rank assignment + env contract from worker hostnames (reference:
    ray/runner.py:178-248 — the part of RayExecutor that does not touch
    ray itself)."""

    def __init__(self):
        self.hostnames_by_rank: "OrderedDict[str, List[int]]" = OrderedDict()
        self._world_size = 0

    @property
    def world_size(self) -> int:
        return self._world_size

    def register(self, hostname: str, world_rank: int) -> None:
        self.hostnames_by_rank.setdefault(hostname, []).append(world_rank)
        self._world_size += 1

    def finalize_registration(self) -> Dict[int, Dict[str, str]]:
        """Env dict keyed by *registration index* (= actor index), with
        world ranks assigned **host-major** (reference: runner.py:218-248 —
        the NodeColocator groups workers per node before rank assignment).

        Ranks are renumbered rather than taken from registration order: a
        PACK-scheduled flat executor can interleave hosts, and a rank
        numbering that disagrees with the host grouping breaks the
        ``rank == cross_rank*local_size + local_rank`` invariant the
        hierarchical collectives (and the native core's fail-fast check)
        rely on.
        """
        envs: Dict[int, Dict[str, str]] = {}
        cross_size = len(self.hostnames_by_rank)
        world_rank = 0
        for cross_rank, (host, reg_ids) in enumerate(
                self.hostnames_by_rank.items()):
            for local_rank, reg_id in enumerate(sorted(reg_ids)):
                envs[reg_id] = {
                    "HOROVOD_RANK": str(world_rank),
                    "HOROVOD_SIZE": str(self._world_size),
                    "HOROVOD_LOCAL_RANK": str(local_rank),
                    "HOROVOD_LOCAL_SIZE": str(len(reg_ids)),
                    "HOROVOD_CROSS_RANK": str(cross_rank),
                    "HOROVOD_CROSS_SIZE": str(cross_size),
                    "HOROVOD_HOSTNAME": host,
                }
                world_rank += 1
        return envs

    def establish_rendezvous(self, controller_addr: str,
                             controller_port: int) -> Dict[str, str]:
        """Controller coordinates shared by every worker (reference:
        runner.py establishes the gloo rendezvous env the same way)."""
        return {
            "HOROVOD_CONTROLLER_ADDR": controller_addr,
            "HOROVOD_CONTROLLER_PORT": str(controller_port),
        }


class RayExecutor:
    """Run a horovod_tpu job on Ray actors (reference: ray/runner.py:250-482
    — start/run/run_remote/execute/shutdown, with NodeColocator's
    one-bundle-per-host placement, runner.py:48-175).

    Two topology modes, as in the reference:

    * ``num_hosts`` + ``num_workers_per_host``: one placement-group bundle
      per host (STRICT_SPREAD), all of a host's workers scheduled into its
      bundle — guarantees colocation *and* spread.
    * flat ``num_workers``: one bundle per worker, PACK strategy (fill
      nodes first), matching the reference's non-colocated fallback.
    """

    def __init__(self, num_workers: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: int = 1,
                 cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 gpus_per_worker: int = 0,
                 use_current_placement_group: bool = True):
        if num_workers is None and num_hosts is None:
            num_workers = 1
        self.num_workers = num_workers
        self.num_hosts = num_hosts
        self.num_workers_per_host = num_workers_per_host
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self.gpus_per_worker = gpus_per_worker if use_gpu else 0
        self.use_current_placement_group = use_current_placement_group
        self.workers: List[Any] = []
        self.placement_group = None
        self._owns_placement_group = False
        self._coordinator = Coordinator()

    # -- placement ---------------------------------------------------------

    def _bundles(self):
        """(bundles, strategy, workers_per_bundle) for the placement group
        (reference NodeColocator: one node-sized resource claim per host,
        runner.py:48-110)."""
        per_worker = {"CPU": self.cpus_per_worker}
        if self.gpus_per_worker:
            per_worker["GPU"] = self.gpus_per_worker
        if self.num_hosts is not None:
            bundle = {k: v * self.num_workers_per_host
                      for k, v in per_worker.items()}
            return ([dict(bundle) for _ in range(self.num_hosts)],
                    "STRICT_SPREAD", self.num_workers_per_host)
        return ([dict(per_worker) for _ in range(self.num_workers)],
                "PACK", 1)

    def _ensure_placement_group(self, ray):
        if self.use_current_placement_group:
            try:
                from ray.util import get_current_placement_group

                current = get_current_placement_group()
            except ImportError:  # pragma: no cover
                current = None
            if current is not None:
                self.placement_group = current
                return
        bundles, strategy, _ = self._bundles()
        from ray.util import placement_group as create_pg

        self.placement_group = create_pg(bundles, strategy=strategy)
        self._owns_placement_group = True
        ray.get(self.placement_group.ready())

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Create worker actors inside the placement group and wire the env
        contract (reference: runner.py:250-340)."""
        ray = _require_ray()

        @ray.remote
        class _Worker:
            def hostname(self):
                import socket

                return socket.gethostbyname(socket.gethostname())

            def find_free_port(self):
                # The controller binds on *this worker's* host; picking the
                # port here (not on the driver machine) avoids cross-host
                # port guessing (round-1 verdict weak #4).
                import socket

                s = socket.socket()
                s.bind(("0.0.0.0", 0))
                port = s.getsockname()[1]
                s.close()
                return port

            def set_env(self, env):
                import os

                os.environ.update(env)
                return True

            def execute(self, fn, args, kwargs):
                return fn(*(args or ()), **(kwargs or {}))

        self._ensure_placement_group(ray)
        _, _, per_bundle = self._bundles()
        n = (self.num_workers if self.num_hosts is None
             else self.num_hosts * self.num_workers_per_host)

        self.workers = []
        for i in range(n):
            # Explicit bundle indices only for a PG we created with the
            # matching shape; an inherited PG (e.g. from a Ray Tune trial)
            # may have any layout, so let Ray pick bundles (-1 = any).
            bundle_index = i // per_bundle if self._owns_placement_group \
                else -1
            strategy = _pg_scheduling_strategy(self.placement_group,
                                               bundle_index)
            opts = {"num_cpus": self.cpus_per_worker}
            if self.gpus_per_worker:
                opts["num_gpus"] = self.gpus_per_worker
            if strategy is not None:
                opts["scheduling_strategy"] = strategy
            self.workers.append(_Worker.options(**opts).remote())

        hostnames = ray.get([w.hostname.remote() for w in self.workers])
        for rank, host in enumerate(hostnames):
            self._coordinator.register(host, rank)
        envs = self._coordinator.finalize_registration()

        controller_port = ray.get(self.workers[0].find_free_port.remote())
        rendezvous = self._coordinator.establish_rendezvous(
            hostnames[0], controller_port)
        ray.get([
            w.set_env.remote({**envs[rank], **rendezvous})
            for rank, w in enumerate(self.workers)])

    def run(self, fn: Callable, args=None, kwargs=None) -> List[Any]:
        """Execute ``fn`` on every worker; rank-ordered results
        (reference: runner.py:380-420)."""
        ray = _require_ray()
        return ray.get([w.execute.remote(fn, args, kwargs)
                        for w in self.workers])

    def run_remote(self, fn: Callable, args=None, kwargs=None) -> List[Any]:
        """Non-blocking variant: returns the object refs (reference:
        runner.py run_remote)."""
        return [w.execute.remote(fn, args, kwargs) for w in self.workers]

    def execute(self, fn: Callable) -> List[Any]:
        """Reference: runner.py execute(fn) — fn receives the worker."""
        return self.run(lambda: fn(None))

    def execute_single(self, fn: Callable) -> Any:
        """Run ``fn`` on the rank-0 worker only (reference:
        runner.py execute_single)."""
        ray = _require_ray()
        return ray.get(self.workers[0].execute.remote(lambda: fn(None),
                                                      None, None))

    def shutdown(self) -> None:
        ray = _require_ray()
        for w in self.workers:
            ray.kill(w)
        self.workers = []
        if self._owns_placement_group and self.placement_group is not None:
            try:
                from ray.util import remove_placement_group

                remove_placement_group(self.placement_group)
            except Exception:  # pragma: no cover - best effort
                pass
        self.placement_group = None
        self._owns_placement_group = False


class RayHostDiscovery(HostDiscovery):
    """Elastic host discovery from Ray cluster state (reference:
    ray/elastic.py:36-60)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = _require_ray()
        hosts: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {})
            if self.use_gpu:
                slots = int(res.get("GPU", 0)) // self.gpus_per_slot
            else:
                slots = int(res.get("CPU", 0)) // self.cpus_per_slot
            if slots > 0:
                hosts[node["NodeManagerAddress"]] = slots
        return hosts


def _driver_service_env(driver) -> Dict[str, str]:
    """Elastic driver-service coordinates every actor needs to rendezvous
    (mirrors elastic/launcher.py:_worker_env; round-1 verdict fix: without
    these the actor's ``hvd.elastic.run`` KeyErrors immediately)."""
    import socket

    try:
        from ray.util import get_node_ip_address

        addr = get_node_ip_address()
    except Exception:
        addr = socket.gethostbyname(socket.gethostname())
    return {
        "HOROVOD_ELASTIC_DRIVER_ADDR": addr,
        "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
        "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
    }


class ElasticRayExecutor:
    """Elastic executor over Ray actors (reference: ray/elastic.py:61-300):
    couples the ElasticDriver + RayHostDiscovery, spawning one Ray task per
    slot through the driver's create_worker_fn. Each task is pinned to its
    slot's node via the ``node:<ip>`` resource and receives *only* identity
    env (hostname, local_rank) plus the driver-service coordinates —
    rank/size come from rendezvous so they survive resizes."""

    def __init__(self, min_np: int = 1, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1,
                 override_discovery: Optional[HostDiscovery] = None,
                 controller_addr_override: Optional[str] = None):
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot if use_gpu else 0
        self.discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_slot,
            gpus_per_slot=gpus_per_slot)
        self.controller_addr_override = controller_addr_override
        self.driver = None

    def start(self) -> None:
        _require_ray()
        from ..elastic.driver import ElasticDriver

        self.driver = ElasticDriver(
            self.discovery, min_np=self.min_np, max_np=self.max_np,
            reset_limit=self.reset_limit,
            controller_addr_override=self.controller_addr_override)

    def run(self, worker_fn: Callable) -> bool:
        """Launch ``worker_fn`` per slot as Ray tasks under the elastic
        driver; returns True when the job ends with a successful worker
        (reference: elastic.py:200-300)."""
        ray = _require_ray()
        if self.driver is None:
            self.start()
        driver = self.driver
        service_env = _driver_service_env(driver)

        @ray.remote(max_calls=1)
        def _slot_main(env, fn):
            import os

            os.environ.update(env)
            return fn()

        cpus = self.cpus_per_slot
        gpus = self.gpus_per_slot

        def create_worker(slot, world_id):
            env = {
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_ELASTIC": "1",
                **service_env,
            }
            opts: Dict[str, Any] = {"num_cpus": cpus}
            if gpus:
                opts["num_gpus"] = gpus
            # Pin to the discovered host so the slot actually lands on the
            # node whose ICI domain it was assigned (reference colocation).
            opts["resources"] = {f"node:{slot.hostname}": 0.001}
            try:
                ref = _slot_main.options(**opts).remote(env, worker_fn)
                ray.get(ref)
                return 0
            except Exception:
                return 1

        try:
            driver.start(create_worker)
            return driver.join()
        finally:
            driver.stop()
            driver.shutdown_service()
