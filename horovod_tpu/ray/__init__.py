"""Ray integration (reference: horovod/ray/, SURVEY §2.5).

``RayExecutor`` runs a horovod_tpu world on Ray actors; the
``Coordinator`` (reference: ray/runner.py:178-248) collects each worker's
hostname, assigns ranks host-grouped (so local ranks share ICI), and
builds the launcher env contract. ``ElasticRayExecutor`` (reference:
ray/elastic.py:61) couples the elastic driver to Ray's cluster state
through ``RayHostDiscovery``.

ray is not bundled: actor machinery is gated at call time, while the
Coordinator's assignment logic stays importable and unit-testable.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery


def _require_ray():
    try:
        import ray

        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires ray; install ray or use "
            "horovod_tpu.runner / horovod_tpu.spark") from e


class Coordinator:
    """Rank assignment + env contract from worker hostnames (reference:
    ray/runner.py:178-248 — the part of RayExecutor that does not touch
    ray itself)."""

    def __init__(self):
        self.hostnames_by_rank: "OrderedDict[str, List[int]]" = OrderedDict()
        self._world_size = 0

    @property
    def world_size(self) -> int:
        return self._world_size

    def register(self, hostname: str, world_rank: int) -> None:
        self.hostnames_by_rank.setdefault(hostname, []).append(world_rank)
        self._world_size += 1

    def finalize_registration(self) -> Dict[int, Dict[str, str]]:
        """Env dict per world rank (reference: runner.py:218-248 —
        HOROVOD_RANK/SIZE/LOCAL/CROSS per worker, host-grouped so chips on
        one node get consecutive local ranks)."""
        envs: Dict[int, Dict[str, str]] = {}
        cross_size = len(self.hostnames_by_rank)
        for cross_rank, (host, ranks) in enumerate(
                self.hostnames_by_rank.items()):
            for local_rank, world_rank in enumerate(sorted(ranks)):
                envs[world_rank] = {
                    "HOROVOD_RANK": str(world_rank),
                    "HOROVOD_SIZE": str(self._world_size),
                    "HOROVOD_LOCAL_RANK": str(local_rank),
                    "HOROVOD_LOCAL_SIZE": str(len(ranks)),
                    "HOROVOD_CROSS_RANK": str(cross_rank),
                    "HOROVOD_CROSS_SIZE": str(cross_size),
                    "HOROVOD_HOSTNAME": host,
                }
        return envs

    def establish_rendezvous(self, controller_addr: str,
                             controller_port: int) -> Dict[str, str]:
        """Controller coordinates shared by every worker (reference:
        runner.py establishes the gloo rendezvous env the same way)."""
        return {
            "HOROVOD_CONTROLLER_ADDR": controller_addr,
            "HOROVOD_CONTROLLER_PORT": str(controller_port),
        }


class RayExecutor:
    """Run a horovod_tpu job on Ray actors (reference: ray/runner.py:250-482
    — start/run/run_remote/execute/shutdown)."""

    def __init__(self, num_workers: int = 1, cpus_per_worker: int = 1,
                 use_current_placement_group: bool = True):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.use_current_placement_group = use_current_placement_group
        self.workers: List[Any] = []
        self._coordinator = Coordinator()

    def start(self) -> None:
        """Create worker actors and wire the env contract (reference:
        runner.py:250-340)."""
        ray = _require_ray()

        @ray.remote(num_cpus=self.cpus_per_worker)
        class _Worker:
            def hostname(self):
                import socket

                return socket.gethostbyname(socket.gethostname())

            def set_env(self, env):
                import os

                os.environ.update(env)
                return True

            def execute(self, fn, args, kwargs):
                return fn(*(args or ()), **(kwargs or {}))

        self.workers = [_Worker.remote() for _ in range(self.num_workers)]
        hostnames = ray.get([w.hostname.remote() for w in self.workers])
        for rank, host in enumerate(hostnames):
            self._coordinator.register(host, rank)
        envs = self._coordinator.finalize_registration()

        from ..runner.network import find_free_port

        rendezvous = self._coordinator.establish_rendezvous(
            hostnames[0], find_free_port())
        ray.get([
            w.set_env.remote({**envs[rank], **rendezvous})
            for rank, w in enumerate(self.workers)])

    def run(self, fn: Callable, args=None, kwargs=None) -> List[Any]:
        """Execute ``fn`` on every worker; rank-ordered results
        (reference: runner.py:380-420)."""
        ray = _require_ray()
        return ray.get([w.execute.remote(fn, args, kwargs)
                        for w in self.workers])

    def execute(self, fn: Callable) -> List[Any]:
        """Reference: runner.py execute(fn) — fn receives the worker."""
        return self.run(lambda: fn(None))

    def shutdown(self) -> None:
        ray = _require_ray()
        for w in self.workers:
            ray.kill(w)
        self.workers = []


class RayHostDiscovery(HostDiscovery):
    """Elastic host discovery from Ray cluster state (reference:
    ray/elastic.py:36-60)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = _require_ray()
        hosts: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {})
            if self.use_gpu:
                slots = int(res.get("GPU", 0)) // self.gpus_per_slot
            else:
                slots = int(res.get("CPU", 0)) // self.cpus_per_slot
            if slots > 0:
                hosts[node["NodeManagerAddress"]] = slots
        return hosts


class ElasticRayExecutor:
    """Elastic executor over Ray actors (reference: ray/elastic.py:61-300):
    couples the ElasticDriver + RayHostDiscovery, spawning a worker actor
    per slot through the driver's create_worker_fn."""

    def __init__(self, min_np: int = 1, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 use_gpu: bool = False, cpus_per_slot: int = 1):
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.discovery = RayHostDiscovery(use_gpu=use_gpu,
                                          cpus_per_slot=cpus_per_slot)
        self.driver = None

    def start(self) -> None:
        _require_ray()
        from ..elastic.driver import ElasticDriver

        self.driver = ElasticDriver(
            self.discovery, min_np=self.min_np, max_np=self.max_np,
            reset_limit=self.reset_limit)

    def run(self, worker_fn: Callable) -> None:
        """Launch `worker_fn` per slot as Ray actors under the elastic
        driver (reference: elastic.py:200-300)."""
        ray = _require_ray()
        if self.driver is None:
            self.start()

        @ray.remote
        def _slot_main(env, fn):
            import os

            os.environ.update(env)
            return fn()

        def create_worker(slot, world_id):
            envs = {
                "HOROVOD_RANK": str(slot.rank),
                "HOROVOD_SIZE": str(slot.world_size),
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_LOCAL_SIZE": str(slot.local_size),
                "HOROVOD_CROSS_RANK": str(slot.cross_rank),
                "HOROVOD_CROSS_SIZE": str(slot.cross_size),
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_ELASTIC": "1",
            }
            try:
                ray.get(_slot_main.remote(envs, worker_fn))
                return 0
            except Exception:
                return 1

        self.driver.start(create_worker)
        self.driver.join()
