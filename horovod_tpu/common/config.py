"""Environment-variable configuration knobs.

The reference converges three config layers (env vars, CLI flags, YAML) onto
environment variables consumed by the native core at init time
(operations.cc:416-518, knob names common.h:64-90, config_parser.py). We keep
the same knob names with a ``HOROVOD_`` prefix so reference users can carry
their tuning over, and read them once at :func:`horovod_tpu.init` into a
typed, immutable :class:`Config`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a float, got {v!r}")


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_str(name: str, default: Optional[str]) -> Optional[str]:
    v = os.environ.get(name)
    return default if v in (None, "") else v


@dataclasses.dataclass(frozen=True)
class Config:
    """Runtime knobs, mirroring the reference's env contract.

    Defaults match the reference where a reference default exists
    (fusion threshold 64 MiB and cycle time 5 ms: operations.cc:437,445;
    cache capacity 1024: operations.cc:452-461; stall warning 60 s:
    stall_inspector.h:36-66).
    """

    # --- tensor fusion (operations.cc:437; controller.cc:360-378) ---
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 5.0

    # --- response cache (operations.cc:452-461) ---
    cache_capacity: int = 1024

    # --- hierarchical collectives (operations.cc:463-487) ---
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False

    # --- quantized allreduce (no reference analogue; EQuARX-style int8
    #     wire on the DCN hop of the hierarchical decomposition) ---
    quantized_allreduce: bool = False
    quant_block: int = 256  # elements per int8 scale block

    # --- ZeRO sharded optimizer (no reference analogue; reduce-scatter
    #     data parallelism with per-rank optax updates, docs/zero.md).
    #     zero_stage 0-3 wins; the PR-4 boolean maps to stage 2. ---
    zero_sharding: bool = False
    zero_stage: int = 0

    # --- overlapped gradient reduction (docs/overlap.md): stream fused
    #     buckets into collectives while backward compute still runs ---
    overlap: bool = False
    num_comm_streams: int = 1  # bucket collectives in flight (pow2 1-4)

    # --- fused compute-collective Pallas kernels (docs/fused-kernels.md):
    #     kernel-eligible wire-plan legs (int8 quantize/dequant, matmul
    #     prologue/epilogue) lower through the Pallas backend ---
    fused_kernels: bool = False
    # 3-level tree plans: ride the pod hop as the blockwise-int8 rs+ag
    # pair instead of the exact psum (docs/wire-plan.md)
    quantized_pod: bool = False

    # --- pipeline parallelism (docs/pipeline.md): a dedicated hvd_pp
    #     mesh axis of pp_stages stages; the training schedule pumps
    #     pp_microbatches microbatches through it (gpipe | 1f1b |
    #     interleaved_1f1b with pp_interleave virtual stages per rank).
    #     pp_quantized rides the inter-stage activation sends as
    #     blockwise-int8 wire-plan legs with error feedback (DCN/pod
    #     hops only — the send leg inherits the EQuARX placement rule).
    pp_stages: int = 0          # 0/1 = pipeline off
    pp_microbatches: int = 0    # 0 = schedule default (max(stages, 2))
    pp_schedule: str = "interleaved_1f1b"
    pp_interleave: int = 1      # virtual stages per rank (>=1)
    pp_quantized: bool = False

    # --- expert parallelism / MoE (docs/moe.md): a dedicated hvd_ep
    #     mesh axis of ep_size expert groups; the MoE layer's
    #     dispatch/combine all-to-alls lower as wire-plan ``a2a`` legs.
    #     moe_quantized rides them blockwise-int8 with error feedback
    #     (DCN/pod hops only — the a2a leg inherits the EQuARX
    #     placement rule, exactly like the pipeline send leg).
    ep_size: int = 0            # 0/1 = expert parallelism off
    moe_experts: int = 0        # global expert count (0 = MoE off)
    moe_topk: int = 2           # experts per token (top-k gating)
    moe_capacity_factor: float = 1.25
    moe_quantized: bool = False

    # --- autotune (common.h:68-73) ---
    autotune: bool = False
    autotune_log: Optional[str] = None
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    # Cost-model warm start (docs/cost-model.md): seed the GP with the
    # top-K analytically priced plans (0 = cold search).
    autotune_warm_start: int = 0

    # --- link-class calibration store (docs/cost-model.md): the
    #     microbenchmark-fitted (bandwidth, latency, quant-rate) triples,
    #     kept beside the autotune cache by default ---
    calibration_cache: Optional[str] = None

    # --- compile-once runtime (docs/compile.md): JAX persistent
    #     compilation cache + serialized-executable registry, armed from
    #     init so warm reruns / restarted workers skip lower+compile.
    #     Dir defaults beside the autotune cache
    #     (~/.cache/horovod_tpu/compile). ---
    compile_cache: bool = True
    compile_cache_dir: Optional[str] = None

    # --- timeline (operations.cc:420-434) ---
    timeline: Optional[str] = None
    timeline_mark_cycles: bool = False

    # --- metrics registry / sinks (docs/observability.md) ---
    metrics_jsonl: Optional[str] = None  # snapshot JSONL sink path
    metrics_port: Optional[int] = None   # Prometheus endpoint (0 = any port)
    metrics_interval: float = 0.0        # reporter period secs (0 = off)
    metrics_aggregate: bool = False      # cross-rank aggregate per interval

    # --- flight recorder (docs/observability.md): always-on forensic
    #     ring of recent events, dumped to the dir on crash paths ---
    flight_recorder_dir: Optional[str] = None
    flight_recorder_events: int = 4096  # ring capacity (0 disables)

    # --- stall inspector (stall_inspector.h:36-66) ---
    stall_check_disable: bool = False
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0

    # --- resilience supervisor (docs/robustness.md): preemption-notice
    #     priority-snapshot deadline and the restart-from-last-commit
    #     budget of the failure-policy supervisor ---
    preempt_snapshot_deadline_secs: float = 5.0
    resilience_restart_budget: int = 3

    # --- logging ---
    log_level: str = "warning"
    log_hide_timestamp: bool = False

    # --- elastic (launcher-injected; gloo_run.py:65-76) ---
    elastic: bool = False

    # --- launcher-injected world description (gloo_run.py:65-76) ---
    rank: Optional[int] = None
    size: Optional[int] = None
    local_rank: Optional[int] = None
    local_size: Optional[int] = None
    cross_rank: Optional[int] = None
    cross_size: Optional[int] = None
    rendezvous_addr: Optional[str] = None
    rendezvous_port: Optional[int] = None

    # --- controller transport (env_parser.h:26-32 analogue) ---
    controller: str = "tcp"  # "tcp" (rank-0 coordinator over sockets) | "none"
    cpu_operations: str = "ring"  # CPU eager data plane: "ring" | "naive"

    # --- number of independent collective streams (HOROVOD_NUM_NCCL_STREAMS) ---
    num_streams: int = 1


def from_env() -> Config:
    """Read all knobs from the environment (reference: operations.cc:416-518)."""
    return Config(
        fusion_threshold_bytes=_env_int("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024),
        cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", 5.0),
        cache_capacity=_env_int("HOROVOD_CACHE_CAPACITY", 1024),
        hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE", False),
        hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER", False),
        quantized_allreduce=_env_bool("HOROVOD_QUANTIZED_ALLREDUCE", False),
        quant_block=_env_int("HOROVOD_QUANT_BLOCK", 256),
        zero_sharding=_env_bool("HOROVOD_ZERO_SHARDING", False),
        zero_stage=_env_int("HOROVOD_ZERO_STAGE", 0),
        overlap=_env_bool("HOROVOD_OVERLAP", False),
        num_comm_streams=_env_int("HOROVOD_NUM_COMM_STREAMS", 1),
        fused_kernels=_env_bool("HOROVOD_FUSED_KERNELS", False),
        quantized_pod=_env_bool("HOROVOD_QUANTIZED_POD", False),
        pp_stages=_env_int("HOROVOD_PP_STAGES", 0),
        pp_microbatches=_env_int("HOROVOD_PP_MICROBATCHES", 0),
        pp_schedule=_env_str("HOROVOD_PP_SCHEDULE", "interleaved_1f1b")
        or "interleaved_1f1b",
        pp_interleave=_env_int("HOROVOD_PP_INTERLEAVE", 1),
        pp_quantized=_env_bool("HOROVOD_PP_QUANTIZED", False),
        ep_size=_env_int("HOROVOD_EP_SIZE", 0),
        moe_experts=_env_int("HOROVOD_MOE_EXPERTS", 0),
        moe_topk=_env_int("HOROVOD_MOE_TOPK", 2),
        moe_capacity_factor=_env_float("HOROVOD_MOE_CAPACITY_FACTOR",
                                       1.25),
        moe_quantized=_env_bool("HOROVOD_MOE_QUANTIZED", False),
        autotune=_env_bool("HOROVOD_AUTOTUNE", False),
        autotune_log=_env_str("HOROVOD_AUTOTUNE_LOG", None),
        autotune_warmup_samples=_env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3),
        autotune_steps_per_sample=_env_int("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10),
        autotune_bayes_opt_max_samples=_env_int(
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20
        ),
        autotune_gaussian_process_noise=_env_float(
            "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8
        ),
        autotune_warm_start=_env_int("HOROVOD_AUTOTUNE_WARM_START", 0),
        calibration_cache=_env_str("HOROVOD_CALIBRATION_CACHE", None),
        compile_cache=_env_bool("HOROVOD_COMPILE_CACHE", True),
        compile_cache_dir=_env_str("HOROVOD_COMPILE_CACHE_DIR", None),
        timeline=_env_str("HOROVOD_TIMELINE", None),
        timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES", False),
        metrics_jsonl=_env_str("HOROVOD_METRICS_JSONL", None),
        metrics_port=_opt_int("HOROVOD_METRICS_PORT"),
        metrics_interval=_env_float("HOROVOD_METRICS_INTERVAL", 0.0),
        metrics_aggregate=_env_bool("HOROVOD_METRICS_AGGREGATE", False),
        flight_recorder_dir=_env_str("HOROVOD_FLIGHT_RECORDER_DIR", None),
        flight_recorder_events=_env_int("HOROVOD_FLIGHT_RECORDER_EVENTS",
                                        4096),
        stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE", False),
        stall_warning_time_seconds=_env_float("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0),
        stall_shutdown_time_seconds=_env_float(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0
        ),
        preempt_snapshot_deadline_secs=_env_float(
            "HOROVOD_PREEMPT_SNAPSHOT_DEADLINE_SECS", 5.0
        ),
        resilience_restart_budget=_env_int(
            "HOROVOD_RESILIENCE_RESTART_BUDGET", 3
        ),
        log_level=_env_str("HOROVOD_LOG_LEVEL", "warning") or "warning",
        log_hide_timestamp=_env_bool("HOROVOD_LOG_HIDE_TIME", False),
        elastic=_env_bool("HOROVOD_ELASTIC", False),
        rank=_opt_int("HOROVOD_RANK"),
        size=_opt_int("HOROVOD_SIZE"),
        local_rank=_opt_int("HOROVOD_LOCAL_RANK"),
        local_size=_opt_int("HOROVOD_LOCAL_SIZE"),
        cross_rank=_opt_int("HOROVOD_CROSS_RANK"),
        cross_size=_opt_int("HOROVOD_CROSS_SIZE"),
        rendezvous_addr=_env_str("HOROVOD_GLOO_RENDEZVOUS_ADDR", None),
        rendezvous_port=_opt_int("HOROVOD_GLOO_RENDEZVOUS_PORT"),
        controller=_env_str("HOROVOD_CONTROLLER", "tcp") or "tcp",
        cpu_operations=_env_str("HOROVOD_CPU_OPERATIONS", "ring") or "ring",
        num_streams=_env_int("HOROVOD_NUM_STREAMS", 1),
    )


def _opt_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return None if v in (None, "") else int(v)
