"""Byte-payload transport over the eager/native collectives.

One wire protocol shared by every host-framework binding's
``broadcast_object`` / ``allgather_object`` (torch, mxnet; reference:
horovod/torch/functions.py:122-160, mxnet/functions.py): payloads ride as
numpy uint8 buffers — a size broadcast first, then the data — so each
binding only supplies its serializer (torch.save vs pickle) and never
re-implements the framing.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..ops import collective_ops as C


def broadcast_bytes(data: Optional[bytes], root_rank: int,
                    name: str) -> bytes:
    """Broadcast ``data`` from ``root_rank``; non-root ranks pass anything
    (ignored) and receive the root's bytes. World-1 returns ``data``."""
    ctrl, world = C._eager_ctx()
    if world == 1:
        return data if data is not None else b""
    is_root = ctrl.rank() == root_rank
    payload = np.frombuffer(data, dtype=np.uint8).copy() \
        if is_root and data is not None else np.empty(0, np.uint8)
    sz = ctrl.broadcast_async(np.array([len(payload)], np.int64),
                              f"{name}.sz", root=root_rank).wait()
    buf = payload if is_root else np.empty(int(sz[0]), np.uint8)
    out = ctrl.broadcast_async(buf, f"{name}.data", root=root_rank).wait()
    return out.tobytes()


def allgather_bytes(data: bytes, name: str) -> List[bytes]:
    """Gather every rank's bytes; returns them rank-ordered. World-1
    returns ``[data]``."""
    ctrl, world = C._eager_ctx()
    if world == 1:
        return [data]
    payload = np.frombuffer(data, dtype=np.uint8).copy()
    gathered = ctrl.allgather_async(payload, f"{name}.data").wait()
    sizes = ctrl.allgather_async(np.array([len(payload)], np.int64),
                                 f"{name}.sz").wait()
    out, offset = [], 0
    for s in sizes.tolist():
        out.append(gathered[offset:offset + int(s)].tobytes())
        offset += int(s)
    return out
