"""Core world state: init/shutdown, device mesh, rank/size queries.

Reference surface: ``HorovodBasics`` (horovod/common/basics.py:22-258) backed
by the C ABI ``horovod_init/rank/size/local_rank/...`` (operations.cc:685-889).

TPU-native redesign
-------------------
The reference runs **one process per GPU**; a rank is a process. On TPU the
idiomatic unit is **one process per host, one rank per chip**, with all chips
of a job joined in a single :class:`jax.sharding.Mesh` (single-controller
SPMD). We therefore keep Horovod's three-level world vocabulary but map it
onto the mesh:

====================  =============================================
Horovod concept        horovod_tpu mapping
====================  =============================================
rank                  global chip index (``hvd_cross * local_size + hvd_local``)
local_rank            chip index within this host (mesh axis ``hvd_local``)
cross_rank            host/process index (mesh axis ``hvd_cross``)
size                  total chips in the mesh
local_size            chips per host
cross_size            number of hosts
====================  =============================================

The mesh is always 2-D ``(hvd_cross, hvd_local)`` so hierarchical collectives
(intra-host over ICI, cross-host over DCN) fall out of the axis structure the
same way the reference splits ``local_comm``/``cross_comm``
(mpi_context.h:78-84, nccl_operations.cc:190-380).

``rank()``/``local_rank()``/``cross_rank()`` are **context sensitive**: inside
a ``jax.shard_map`` over the Horovod mesh they return the traced per-chip
index (so model code like ``if hvd.rank() == 0`` compiles to a per-device
predicate, matching the per-process value a reference user would see); in
eager host code they return the index of this process's *leader chip*.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import config as _config
from . import counters as _counters
from .exceptions import NotInitializedError

# Mesh axis names. The pair mirrors the reference's local/cross communicator
# split (mpi_context.h:78-84). ``HVD_AXES`` is the flat "world" axis tuple —
# psum over it is the reference's flat ring allreduce. ``POD_AXIS`` is the
# optional third hierarchy level (multi-pod topologies, ``mesh_shape=
# (cross, local, pods)`` with pods > 1): when present the mesh is 3-D
# ``(hvd_pod, hvd_cross, hvd_local)`` and ``ALL_AXES`` in that order is the
# full world tuple (rank-major lex order matches the mesh layout).
CROSS_AXIS = "hvd_cross"
LOCAL_AXIS = "hvd_local"
POD_AXIS = "hvd_pod"
HVD_AXES: Tuple[str, str] = (CROSS_AXIS, LOCAL_AXIS)
ALL_AXES: Tuple[str, str, str] = (POD_AXIS, CROSS_AXIS, LOCAL_AXIS)

# Pipeline-parallel mesh axis (docs/pipeline.md). Deliberately NOT part of
# ALL_AXES: the pp axis carries pipeline *stages*, not data replicas — a
# gradient collective over the "world" must never sum across ranks that
# hold different model layers, so every axes=None collective resolves to
# the data axes only and the pp axis is reached explicitly (the
# ``send``-leg ppermutes of parallel/pipeline.py).
PP_AXIS = "hvd_pp"

# Expert-parallel mesh axis (docs/moe.md). The same dedicated-axis
# pattern as PP_AXIS: the ep axis carries expert *groups*, not data
# replicas — expert parameters differ per ep rank, so a gradient
# collective over the "world" must never sum across expert groups. Every
# axes=None collective resolves to the data axes only; the ep axis is
# reached explicitly by the MoE dispatch/combine ``a2a`` wire-plan legs
# (horovod_tpu/moe/layer.py).
EP_AXIS = "hvd_ep"

# ``jax.shard_map`` graduated from jax.experimental in jax 0.6; on the
# pinned 0.4.x line only the experimental spelling exists. This resolver is
# the single home every horovod_tpu caller (and the test suite, via
# ``hvd.shard_map``) goes through, so either jax works unmodified.
if getattr(jax, "shard_map", None) is not None:
    shard_map = jax.shard_map
else:  # jax < 0.6
    from jax.experimental.shard_map import shard_map


class _State:
    """Process-global framework state (reference: HorovodGlobalState,
    global_state.h:42-122 — minus the background-thread machinery, which on
    TPU lives in the native controller, see horovod_tpu/cc/)."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.initialized = False
        self.config: Optional[_config.Config] = None
        self.mesh: Optional[Mesh] = None
        self.process_index: int = 0
        self.process_count: int = 1
        self.local_device_count: int = 0
        self.timeline = None  # utils.timeline.Timeline, attached lazily
        self.controller = None  # runtime controller client (eager path)
        self.joined = False


_state = _State()


def _build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, ...]] = None,
    pp_stages: Optional[int] = None,
    ep_size: Optional[int] = None,
) -> Mesh:
    """Arrange all job devices into the 2-D (cross, local) Horovod mesh.

    Devices are ordered host-major so that chips on the same host are
    contiguous along ``hvd_local`` — the layout that keeps ``hvd_local``
    collectives on ICI and only ``hvd_cross`` traffic on DCN (the analogue of
    the reference packing ranks host-by-host, hosts.py:100-150).

    ``mesh_shape=(cross, local)`` overrides the inferred host/chip split —
    used to emulate a multi-host topology on a single host (tests, dryruns)
    or to re-slice a multi-slice pod. ``mesh_shape=(cross, local, pods)``
    with pods > 1 builds the 3-level ``(hvd_pod, hvd_cross, hvd_local)``
    mesh — the topology the wire-plan compiler's 3-level tree plans
    target (docs/wire-plan.md); pods == 1 collapses to the 2-D mesh.
    """
    if devices is None:
        from .backend import acquire_devices

        devices = acquire_devices()
    devices = list(devices)
    if (ep_size is not None and ep_size > 1
            and pp_stages is not None and pp_stages > 1):
        # 4-D composed mesh (docs/parallelism.md): (hvd_pp, hvd_ep,
        # hvd_cross, hvd_local). The pp axis leads so consecutive
        # stages sit a full (ep x data)-mesh apart — the inter-stage
        # send still crosses the slowest link class present — and the
        # ep axis nests inside a stage so expert dispatch/combine
        # all-to-alls stay STAGE-LOCAL (an a2a must never mix tokens
        # that belong to different pipeline stages). Data shards and
        # gradient collectives stay on (cross, local) per (stage,
        # expert-group) cell.
        if mesh_shape is not None and len(mesh_shape) == 3:
            raise ValueError(
                "pp_stages x ep_size does not compose with a 3-level "
                "(cross, local, pods) mesh_shape — the pp/ep axes take "
                "the leading mesh dimensions the pod axis would use")
        if mesh_shape is not None:
            cross, local = mesh_shape
        else:
            if len(devices) % (pp_stages * ep_size):
                raise ValueError(
                    f"pp_stages {pp_stages} x ep_size {ep_size} does "
                    f"not divide {len(devices)} devices")
            cross, local = 1, len(devices) // (pp_stages * ep_size)
        if pp_stages * ep_size * cross * local != len(devices):
            raise ValueError(
                f"pp_stages {pp_stages} x ep_size {ep_size} x "
                f"mesh_shape ({cross}, {local}) does not cover "
                f"{len(devices)} devices")
        grid = np.array(devices, dtype=object).reshape(
            pp_stages, ep_size, cross, local)
        return Mesh(grid, (PP_AXIS, EP_AXIS, CROSS_AXIS, LOCAL_AXIS))
    if ep_size is not None and ep_size > 1:
        # Expert-parallel mesh (docs/moe.md): a leading hvd_ep axis of
        # expert groups over the (cross, local) data mesh — the same
        # leading-axis layout as the pipeline mesh, so consecutive ep
        # groups sit a full data-mesh apart and the dispatch/combine
        # all-to-all crosses the slowest link class present.
        if mesh_shape is not None and len(mesh_shape) == 3:
            raise ValueError(
                "ep_size does not compose with a 3-level "
                "(cross, local, pods) mesh_shape yet — the ep axis takes "
                "the leading mesh dimension the pod axis would use")
        if mesh_shape is not None:
            cross, local = mesh_shape
        else:
            if len(devices) % ep_size:
                raise ValueError(
                    f"ep_size {ep_size} does not divide "
                    f"{len(devices)} devices")
            cross, local = 1, len(devices) // ep_size
        if ep_size * cross * local != len(devices):
            raise ValueError(
                f"ep_size {ep_size} x mesh_shape ({cross}, {local}) "
                f"does not cover {len(devices)} devices")
        grid = np.array(devices, dtype=object).reshape(
            ep_size, cross, local)
        return Mesh(grid, (EP_AXIS, CROSS_AXIS, LOCAL_AXIS))
    if pp_stages is not None and pp_stages > 1:
        # Pipeline mesh: a leading hvd_pp axis of pipeline stages over
        # the (cross, local) data mesh. Consecutive stages sit a full
        # data-mesh apart in the device order, so the inter-stage hop
        # crosses the slowest link class present (docs/pipeline.md).
        if mesh_shape is not None and len(mesh_shape) == 3:
            raise ValueError(
                "pp_stages does not compose with a 3-level "
                "(cross, local, pods) mesh_shape yet — the pp axis takes "
                "the leading mesh dimension the pod axis would use")
        if mesh_shape is not None:
            cross, local = mesh_shape
        else:
            if len(devices) % pp_stages:
                raise ValueError(
                    f"pp_stages {pp_stages} does not divide "
                    f"{len(devices)} devices")
            cross, local = 1, len(devices) // pp_stages
        if pp_stages * cross * local != len(devices):
            raise ValueError(
                f"pp_stages {pp_stages} x mesh_shape ({cross}, {local}) "
                f"does not cover {len(devices)} devices")
        grid = np.array(devices, dtype=object).reshape(
            pp_stages, cross, local)
        return Mesh(grid, (PP_AXIS, CROSS_AXIS, LOCAL_AXIS))
    if mesh_shape is not None:
        if len(mesh_shape) == 3:
            cross, local, pods = mesh_shape
        elif len(mesh_shape) == 2:
            (cross, local), pods = mesh_shape, 1
        else:
            raise ValueError(
                f"mesh_shape must be (cross, local) or "
                f"(cross, local, pods), got {mesh_shape}")
        if cross * local * pods != len(devices):
            raise ValueError(
                f"mesh_shape {mesh_shape} does not cover {len(devices)} devices")
        if pods > 1:
            grid = np.array(devices, dtype=object).reshape(
                pods, cross, local)
            return Mesh(grid, ALL_AXES)
        grid = np.array(devices, dtype=object).reshape(cross, local)
        return Mesh(grid, HVD_AXES)
    n_proc = max(1, jax.process_count())
    per_proc = len(devices) // n_proc if n_proc > 1 else len(devices)
    if n_proc > 1 and per_proc * n_proc == len(devices):
        # Host-major ordering: sort by (process_index, id).
        devices.sort(key=lambda d: (d.process_index, d.id))
        grid = np.array(devices, dtype=object).reshape(n_proc, per_proc)
    else:
        grid = np.array(devices, dtype=object).reshape(1, len(devices))
    return Mesh(grid, HVD_AXES)


# Optional hook invoked (from a watcher thread) with the rank-0 controller's
# actually-bound port once its listener is up, while world formation is
# still in progress. Set by the elastic rendezvous before init() so the
# OS-assigned port (HOROVOD_CONTROLLER_PORT=0) can be reported to the
# elastic driver — port allocation happens on the rank-0 host, never as a
# driver-side free-port guess.
_controller_port_callback = [None]


def set_controller_port_callback(fn) -> None:
    _controller_port_callback[0] = fn


def _bridge_jsm_env() -> None:
    """Map jsrun's JSM_NAMESPACE_* identity vars onto the HOROVOD_* env
    contract when the latter is absent (jsrun launch path,
    runner/js_run.py: jsrun is the process placer; rank identity comes
    from the job-step manager, reference js_run.py + launch.py:463)."""
    bridge = {
        "HOROVOD_RANK": "JSM_NAMESPACE_RANK",
        "HOROVOD_SIZE": "JSM_NAMESPACE_SIZE",
        "HOROVOD_LOCAL_RANK": "JSM_NAMESPACE_LOCAL_RANK",
        "HOROVOD_LOCAL_SIZE": "JSM_NAMESPACE_LOCAL_SIZE",
    }
    for hvd_key, jsm_key in bridge.items():
        if hvd_key not in os.environ and jsm_key in os.environ:
            os.environ[hvd_key] = os.environ[jsm_key]


def _bridge_mpi_env() -> None:
    """Map mpirun's rank-identity vars onto the HOROVOD_* env contract
    when the latter is absent (mpirun launch path, runner/mpi_run.py:
    mpirun is the process placer; OpenMPI/Spectrum export
    ``OMPI_COMM_WORLD_*``, MPICH/Hydra export ``PMI_*``)."""
    bridges = (
        {  # OpenMPI / IBM Spectrum MPI
            "HOROVOD_RANK": "OMPI_COMM_WORLD_RANK",
            "HOROVOD_SIZE": "OMPI_COMM_WORLD_SIZE",
            "HOROVOD_LOCAL_RANK": "OMPI_COMM_WORLD_LOCAL_RANK",
            "HOROVOD_LOCAL_SIZE": "OMPI_COMM_WORLD_LOCAL_SIZE",
        },
        {  # MPICH (Hydra PMI; local identity rides MPI_LOCALRANKID)
            "HOROVOD_RANK": "PMI_RANK",
            "HOROVOD_SIZE": "PMI_SIZE",
            "HOROVOD_LOCAL_RANK": "?MPI_LOCALRANKID",
            "HOROVOD_LOCAL_SIZE": "?MPI_LOCALNRANKS",
        },
    )
    for bridge in bridges:
        # "?"-prefixed sources are optional; the rest gate the bridge.
        required = {k: v for k, v in bridge.items()
                    if not v.startswith("?")}
        if all(v in os.environ for v in required.values()):
            for hvd_key, mpi_key in bridge.items():
                mpi_key = mpi_key.lstrip("?")
                if mpi_key in os.environ:
                    os.environ.setdefault(hvd_key, os.environ[mpi_key])
            return


def init(
    comm=None,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[Tuple[int, int]] = None,
    pp_stages: Optional[int] = None,
    ep_size: Optional[int] = None,
) -> None:
    """Initialize the framework (reference: hvd.init(), basics.py:33 →
    InitializeHorovodOnce, operations.cc:628-674).

    Unlike the reference there is no background communication thread to spawn
    for the compiled path: collectives are compiled *into* the XLA program
    over the ICI mesh. What init does:

    1. read env knobs into an immutable :class:`Config`;
    2. build the global 2-D device mesh;
    3. (multi-host) assume ``jax.distributed.initialize`` was already called
       by the launcher (runner/), mirroring the launcher-injected
       ``HOROVOD_RANK/SIZE`` env contract (gloo_run.py:65-76);
    4. start the timeline if ``HOROVOD_TIMELINE`` is set.

    ``comm`` is accepted for API parity with the reference (an MPI
    communicator there) and must be ``None`` or a device list.
    """
    with _state.lock:
        if _state.initialized:
            return
        if comm is not None and devices is None:
            devices = comm  # parity: allow init(devices)
        _bridge_jsm_env()
        _bridge_mpi_env()
        _state.config = _config.from_env()
        if _state.config.overlap:
            # Before the mesh (= before PJRT client creation): the async-
            # collective/LHS flags only apply to a fresh backend. Graceful
            # no-op off-TPU (docs/overlap.md).
            from .backend import enable_overlap_scheduling

            enable_overlap_scheduling()
        # Compile-once runtime (docs/compile.md): arm JAX's persistent
        # compilation cache BEFORE the mesh exists — the knob only
        # covers compiles issued after arming, and the first collective
        # compile can happen as soon as the mesh does.
        from ..compile import cache as _compile_cache

        _compile_cache.arm_persistent_cache(_state.config)
        if pp_stages is None:
            pp_stages = _state.config.pp_stages or None
        if ep_size is None:
            ep_size = _state.config.ep_size or None
        _state.mesh = _build_mesh(devices, mesh_shape, pp_stages, ep_size)
        _state.process_index = jax.process_index()
        _state.process_count = jax.process_count()
        _state.local_device_count = int(_state.mesh.devices.shape[-1])
        # Launcher-injected env contract (HOROVOD_RANK/SIZE +
        # HOROVOD_CONTROLLER_ADDR, gloo_run.py:65-76): start the native
        # control-plane core. It owns the rank-0 coordinator loop and the
        # TCP data plane for eager (host) collectives between worker
        # processes — the role MPI/Gloo play in the reference.
        cfg = _state.config
        if (cfg.size is not None and cfg.size > 1
                and cfg.controller != "none"):
            from .. import cc

            port_cb = _controller_port_callback[0]
            # Env check BEFORE importing runner/: non-bootstrap inits
            # (elastic, jax.distributed) must not pay the launcher-package
            # import on this path.
            if os.environ.get("HOROVOD_CONTROLLER_BOOTSTRAP") == "kv":
                # Static-launch KV protocol (runner/bootstrap.py): rank 0
                # binds port 0 and publishes; other ranks resolve the
                # controller address from the KV before native init.
                from ..runner import bootstrap

                rank = int(os.environ.get("HOROVOD_RANK", "0"))
                cb = bootstrap.apply(rank)
                if cb is not None:
                    port_cb = cb
            _state.controller = cc.CoreContext(
                bound_port_callback=port_cb)
            if _state.process_count == 1:
                # Process-world mode (no jax.distributed): each worker
                # process is one Horovod rank, exactly the reference's
                # process model. The local mesh serves in-process
                # compiled collectives only.
                _state.process_index = _state.controller.rank()
                _state.process_count = _state.controller.size()
        if _state.config.timeline:
            from ..utils.timeline import Timeline

            _state.timeline = Timeline(_state.config.timeline,
                                       mark_cycles=_state.config.timeline_mark_cycles)
        _state.initialized = True
        # Observability layer: metric sinks (JSONL / Prometheus / timeline
        # mirrors) and the live StallInspector watchdog. The registry
        # itself is process-global and survives shutdown→init cycles
        # (docs/observability.md).
        from .. import monitor

        monitor.start_from_env(_state.config)
    # Outside the lock (uses eager collectives): multi-host runs verify
    # that every host loaded an identical kernel-autotune cache before
    # any cached block choice may shape a compiled program.
    if _state.process_count > 1:
        from ..ops import kernel_autotune

        kernel_autotune.verify_multihost_cache()


# One warning per process: HOROVOD_AUTOTUNE=1 that never reached a
# tuning session is a silent no-op on the compiled path (bucket plans are
# trace-time; the knob activates hvd.autotune_session, docs/autotune.md).
_autotune_unused_warned = [False]


def _warn_autotune_unused(cfg: Optional[_config.Config]) -> None:
    if cfg is None or not cfg.autotune or _autotune_unused_warned[0]:
        return
    from ..autotune import driver as _autotune_driver

    if _autotune_driver.sessions_run() > 0:
        return
    _autotune_unused_warned[0] = True
    import logging

    logging.getLogger("horovod_tpu.autotune").warning(
        "HOROVOD_AUTOTUNE=1 but no tuning session ran: on the compiled "
        "(XLA) path the collective tunables are fixed at trace time, so "
        "autotuning requires an explicit session — wrap your step in "
        "hvd.autotune_session(make_step, cache_key=params) and build the "
        "step with the returned TunedParams (tuned_params= on "
        "DistributedOptimizer / allreduce_pytree). Without it the knob "
        "changes nothing. See docs/autotune.md.")


def shutdown() -> None:
    """Tear down framework state (reference: horovod_shutdown,
    operations.cc:676-683). Safe to call multiple times; init() can be called
    again afterwards (the elastic reset path relies on this,
    common/elastic.py:147-168)."""
    _warn_autotune_unused(_state.config)
    if _state.initialized:
        # Before the timeline closes: final metric flush (the timeline
        # mirror rides it), stop the stall watchdog / reporter / endpoint.
        # Registry values persist into the next incarnation.
        from .. import monitor

        monitor.on_shutdown()
    with _state.lock:
        if _state.timeline is not None:
            _state.timeline.close()
            _state.timeline = None
        if _state.controller is not None:
            _state.controller.close()
            _state.controller = None
        _state.initialized = False
        _state.mesh = None
        _state.config = None
        _state.joined = False
        # Re-align auto-generated collective names for the elastic
        # shutdown→init cycle (survivors and respawned workers must both
        # count from 0).
        from ..ops import collective_ops

        collective_ops._reset_eager_state()
        # New incarnation, fresh fault/retry counters (totals persist).
        _counters.reset_incarnation()


atexit.register(shutdown)


def fault_counters(total: bool = False) -> dict:
    """Snapshot of the fault/retry counters (RPC retries, injected chaos
    faults, blacklist transitions, stall-watchdog firings). Scope is the
    current world incarnation by default — counters clear on
    ``shutdown()``, so an elastic job reads per-incarnation numbers;
    ``total=True`` returns process-lifetime cumulative values. Does not
    require ``init()``: the runner/driver processes record too."""
    return _counters.counters(total=total)


def is_initialized() -> bool:
    """Reference: horovod_is_initialized (operations.cc:759)."""
    return _state.initialized


def _require_init() -> _State:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def mesh() -> Mesh:
    """The global 2-D ``(hvd_cross, hvd_local)`` device mesh."""
    return _require_init().mesh


def config() -> _config.Config:
    return _require_init().config


def timeline():
    return _require_init().timeline


def _bound_axes() -> frozenset:
    """Names of mesh axes bound in the current trace (inside shard_map)."""
    try:
        from jax._src.core import get_axis_env

        return frozenset(get_axis_env().axis_sizes)
    except Exception:  # pragma: no cover - private-API drift fallback
        bound = set()
        for name in ALL_AXES:
            try:
                jax.lax.axis_index(name)
                bound.add(name)
            except NameError:
                pass
        return frozenset(bound)


def _axis_size(name) -> int:
    """Size of a bound mesh axis. ``lax.axis_size`` appeared alongside the
    graduated ``jax.shard_map``; on jax 0.4.x the size comes from the axis
    env directly (the same source :func:`_bound_axes` reads)."""
    try:
        return jax.lax.axis_size(name)
    except AttributeError:  # jax < 0.6
        from jax._src.core import get_axis_env

        try:
            return get_axis_env().axis_sizes[name]
        except KeyError:
            raise _unbound_axis_error(name) from None
    except NameError:
        raise _unbound_axis_error(name) from None


def _unbound_axis_error(name) -> Exception:
    """A collective asked for a mesh axis that is not bound in the current
    trace. Uninitialized backend → the reference-style "call hvd.init()
    first" error instead of the raw KeyError/NameError; initialized →
    explain the shard_map requirement."""
    if not is_initialized():
        return NotInitializedError(
            f"Horovod-TPU (required by a collective over mesh axis "
            f"{name!r})")
    return ValueError(
        f"mesh axis {name!r} is not bound in the current trace: compiled "
        f"collectives must run inside hvd.shard_map over the Horovod "
        f"mesh (hvd.mesh()); omit axes= in eager host code to use the "
        f"process-world path")


def _trace_world_axes() -> Tuple[str, ...]:
    """Horovod mesh axes bound in the current trace, in rank-major
    ``(pod, cross, local)`` order — the 3-level-aware source for
    per-trace rank computation and axis resolution."""
    bound = _bound_axes()
    return tuple(a for a in ALL_AXES if a in bound)


def world_axes() -> Tuple[str, ...]:
    """Axis tuple of the full world mesh: ``(hvd_pod, hvd_cross,
    hvd_local)`` on a 3-level mesh, ``HVD_AXES`` otherwise (including
    before init — the 2-level names are the back-compat default)."""
    s = _state
    if (s.initialized and s.mesh is not None
            and s.mesh.devices.ndim == 3
            and s.mesh.axis_names[0] == POD_AXIS):
        return ALL_AXES
    # A pipeline mesh's hvd_pp axis (and an expert-parallel mesh's
    # hvd_ep axis) is NOT a world/data axis: data shards and gradient
    # collectives stay on (cross, local) per stage / per expert group.
    return HVD_AXES


def in_hvd_context() -> bool:
    """True when tracing under shard_map over the Horovod mesh axes."""
    bound = _bound_axes()
    return (CROSS_AXIS in bound or LOCAL_AXIS in bound
            or POD_AXIS in bound)


def _process_world() -> bool:
    """True in process-world mode: the native controller defines the world
    (one rank per worker process, the reference's process model) because
    jax.distributed is not gluing the devices into one global mesh."""
    s = _state
    return s.controller is not None and jax.process_count() == 1


def size() -> int:
    """Total number of ranks. Mesh chips under single-controller SPMD;
    worker processes in process-world mode. Reference: horovod_size
    (operations.cc:795)."""
    s = _require_init()
    if _process_world():
        return s.controller.size()
    return int(s.mesh.devices.size)


def local_size() -> int:
    """Ranks on this host. Reference: horovod_local_size (operations.cc:787)."""
    s = _require_init()
    if _process_world():
        return s.controller.local_size()
    return s.local_device_count


def cross_size() -> int:
    """Number of hosts. Reference: horovod_cross_size (operations.cc:817)."""
    s = _require_init()
    if _process_world():
        return s.controller.cross_size()
    return int(s.mesh.devices.shape[-2])


def pod_size() -> int:
    """Number of pods (the third hierarchy level): the leading mesh dim
    of a 3-level ``(pod, cross, local)`` mesh, else 1."""
    s = _require_init()
    if (s.mesh is not None and s.mesh.devices.ndim == 3
            and s.mesh.axis_names[0] == POD_AXIS):
        return int(s.mesh.devices.shape[0])
    return 1


def pp_size() -> int:
    """Number of pipeline stages: the leading ``hvd_pp`` mesh dim of a
    pipeline mesh (``init(pp_stages=...)`` / ``HOROVOD_PP_STAGES``),
    else 1 (docs/pipeline.md). On the 4-D composed ``(pp, ep, cross,
    local)`` mesh the pp axis still leads."""
    s = _require_init()
    if (s.mesh is not None and s.mesh.devices.ndim in (3, 4)
            and s.mesh.axis_names[0] == PP_AXIS):
        return int(s.mesh.devices.shape[0])
    return 1


def ep_size() -> int:
    """Number of expert-parallel groups: the leading ``hvd_ep`` mesh dim
    of an expert-parallel mesh (``init(ep_size=...)`` /
    ``HOROVOD_EP_SIZE``), else 1 (docs/moe.md). On the 4-D composed
    ``(pp, ep, cross, local)`` mesh the ep axis sits second, inside a
    stage."""
    s = _require_init()
    if (s.mesh is not None and s.mesh.devices.ndim == 3
            and s.mesh.axis_names[0] == EP_AXIS):
        return int(s.mesh.devices.shape[0])
    if (s.mesh is not None and s.mesh.devices.ndim == 4
            and s.mesh.axis_names[1] == EP_AXIS):
        return int(s.mesh.devices.shape[1])
    return 1


def data_mesh_shape() -> Tuple[int, ...]:
    """The DATA mesh shape ``(cross, local[, pods])`` — the shape every
    plan derivation prices. On a pipeline or expert-parallel mesh the
    leading ``hvd_pp``/``hvd_ep`` dim is excluded: gradient collectives
    run per-stage / per-expert-group over the data axes only."""
    s = _require_init()
    shp = s.mesh.devices.shape
    if len(shp) == 2:
        return (int(shp[0]), int(shp[1]))
    if len(shp) == 4:
        # 4-D composed (pp, ep, cross, local) mesh: the data mesh is
        # the trailing pair — one (stage, expert-group) cell.
        return (int(shp[2]), int(shp[3]))
    if s.mesh.axis_names[0] in (PP_AXIS, EP_AXIS):
        return (int(shp[1]), int(shp[2]))
    return (int(shp[1]), int(shp[2]), int(shp[0]))


def mesh_geometry(mesh_shape=None, mesh=None) -> str:
    """Geometry fingerprint ``mesh<CxL[xP]>|world<N>|<device-kind>``.

    Keys every geometry-bound persisted artifact — the autotune
    warm-start cache entries and the link-calibration store
    (docs/cost-model.md): a tuned winner or a calibrated (bandwidth,
    latency, quant-rate) triple only transfers to an identical topology
    on the same chip kind. ``mesh_shape`` is ``(cross, local[, pods])``;
    with neither argument the live mesh is used (``nomesh`` before
    init)."""
    if mesh is None and mesh_shape is None and is_initialized():
        mesh = _state.mesh
    pp = ""
    if mesh is not None and mesh_shape is None:
        shp = mesh.devices.shape
        if len(shp) == 2:
            mesh_shape = tuple(int(v) for v in shp)
        elif len(shp) == 4:
            # 4-D composed mesh: the fingerprint is the per-cell DATA
            # mesh plus the combined pp/ep marker — a winner tuned at
            # one (stage, expert-group) geometry never warm-starts
            # another (docs/parallelism.md).
            mesh_shape = (int(shp[2]), int(shp[3]))
            pp = f"pp{int(shp[0])}.ep{int(shp[1])}"
        elif mesh.axis_names[0] == PP_AXIS:
            # Pipeline mesh: the fingerprint is the DATA mesh plus an
            # explicit pp marker — a winner tuned at one stage count
            # never warm-starts another (docs/pipeline.md).
            mesh_shape = (int(shp[1]), int(shp[2]))
            pp = f"pp{int(shp[0])}"
        elif mesh.axis_names[0] == EP_AXIS:
            # Expert-parallel mesh: same discipline — a winner tuned at
            # one expert-group count never warm-starts another
            # (docs/moe.md).
            mesh_shape = (int(shp[1]), int(shp[2]))
            pp = f"ep{int(shp[0])}"
        else:
            mesh_shape = (int(shp[1]), int(shp[2]), int(shp[0]))
    if mesh_shape:
        shape = "x".join(str(int(v)) for v in mesh_shape) + pp
        world = 1
        for v in mesh_shape:
            world *= int(v)
    else:
        shape = "nomesh"
        world = size() if is_initialized() else 1
    try:
        devs = (list(mesh.devices.ravel()) if mesh is not None
                else jax.devices())
        kind = getattr(devs[0], "device_kind", "unknown") if devs \
            else "unknown"
    except Exception:  # pragma: no cover - backendless processes
        kind = "unknown"
    kind = str(kind or "unknown").strip().lower().replace(" ", "-")
    return f"mesh{shape}|world{world}|{kind}"


def rank():
    """Global rank. Traced per-chip inside shard_map; process rank in eager
    code. Reference: horovod_rank (operations.cc:771)."""
    s = _require_init()
    if in_hvd_context():
        return jax.lax.axis_index(_trace_world_axes() or HVD_AXES)
    if _process_world():
        return s.controller.rank()
    return s.process_index * s.local_device_count


def local_rank():
    """Rank within the host. Reference: horovod_local_rank
    (operations.cc:779)."""
    s = _require_init()
    if in_hvd_context():
        return jax.lax.axis_index(LOCAL_AXIS)
    if _process_world():
        return s.controller.local_rank()
    return 0


def cross_rank():
    """Host index. Reference: horovod_cross_rank (operations.cc:809)."""
    s = _require_init()
    if in_hvd_context():
        return jax.lax.axis_index(CROSS_AXIS)
    if _process_world():
        return s.controller.cross_rank()
    return s.process_index


def is_homogeneous() -> bool:
    """True when every host has the same number of chips (always true for a
    well-formed mesh). Reference: horovod_is_homogeneous (operations.cc:825)."""
    _require_init()
    return True


def mpi_threads_supported() -> bool:
    """Parity stub (reference: horovod_mpi_threads_supported,
    operations.cc:833). The compiled-collective path has no MPI; the eager
    control plane is thread-safe, so report True."""
    _require_init()
    return True


# --- convenience sharding helpers -----------------------------------------


def data_sharding(extra: Sequence[Optional[str]] = ()) -> NamedSharding:
    """NamedSharding that splits the leading (batch) dim over all ranks."""
    return NamedSharding(mesh(), PartitionSpec(world_axes(), *extra))


def replicated_sharding() -> NamedSharding:
    """NamedSharding that replicates a value on every rank."""
    return NamedSharding(mesh(), PartitionSpec())


def local_batch_size(global_batch: int) -> int:
    n = size()
    if global_batch % n != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by world size {n}"
        )
    return global_batch // n
