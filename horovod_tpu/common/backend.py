"""Robust accelerator-backend acquisition with retry/backoff.

The reference assumes NCCL/MPI initialization either succeeds or the job
dies (operations.cc:628-674 busy-waits ``initialization_done``). On TPU the
failure mode is different: the PJRT client can come up slowly or report
transient ``UNAVAILABLE`` while another (stale) client holds the chip, the
tunnel is warming, or libtpu is still initializing. A framework whose
``init()`` dies with a raw traceback on the first such hiccup is unusable on
real pods, and it is exactly what killed the round-1 benchmark.

This module owns the retry policy:

- :func:`acquire_devices` — ``jax.devices()`` with bounded retry/backoff,
  resetting JAX's cached (possibly half-initialized) backend between
  attempts so each retry re-creates the PJRT client from scratch.
- transient-error classification: ``UNAVAILABLE`` / ``DEADLINE_EXCEEDED`` /
  ``ALREADY_EXISTS`` (stale chip lock) / connection failures retry;
  programming errors surface immediately.
- on exhaustion, raise :class:`BackendInitError` carrying an actionable
  diagnostic (platform asked for, attempts made, the usual causes and their
  fixes) instead of a bare PJRT traceback.

Knobs (env):

``HOROVOD_BACKEND_INIT_RETRIES``  max attempts (default 5)
``HOROVOD_BACKEND_INIT_BACKOFF``  initial sleep seconds, doubles per attempt,
                                  capped at 30 (default 2.0)
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

import jax

from .exceptions import HorovodTpuError

# Substrings identifying transient PJRT/plugin failures worth retrying.
# UNAVAILABLE: backend setup/compile error while the client warms up;
# ALREADY_EXISTS / "in use": a stale client still holds the chip lock;
# DEADLINE/connect/reset: tunnel or coordinator hiccups.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ALREADY_EXISTS",
    "RESOURCE_EXHAUSTED",
    "already in use",
    "failed to connect",
    "connection reset",
    "connection refused",
    "socket closed",
    "unable to initialize backend",
)


class BackendInitError(HorovodTpuError):
    """The accelerator backend could not be initialized after retries."""


def _is_transient(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}"
    low = msg.lower()
    return any(m.lower() in low for m in _TRANSIENT_MARKERS)


def _reset_backends() -> None:
    """Drop JAX's cached backend so the next ``jax.devices()`` re-creates the
    PJRT client. Private-API use is deliberate and guarded: a failed client
    is cached by jax and would otherwise poison every subsequent attempt."""
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
        try:
            xla_bridge.get_backend.cache_clear()
        except AttributeError:
            pass
    except Exception:  # pragma: no cover - private-API drift
        pass


def _log(msg: str) -> None:
    print(f"[horovod_tpu] {msg}", file=sys.stderr, flush=True)


def probe_backend(timeout: float = 120.0) -> bool:
    """Check from a *subprocess* (with a hard timeout) that the accelerator
    backend can be brought up.

    ``jax.devices()`` can hang indefinitely inside PJRT client creation when
    the TPU runtime/tunnel is wedged — a state no in-process retry loop can
    escape. Probing in a child process turns a hang into a timeout the
    parent survives. A successful probe also warms the runtime, so the
    in-process :func:`acquire_devices` that follows is fast.
    """
    import subprocess

    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        _log(f"backend probe timed out after {timeout:.0f}s "
             "(PJRT client creation hung)")
        return False
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        _log(f"backend probe failed (rc={r.returncode}): "
             f"{tail[-1][:200] if tail else '<no stderr>'}")
        return False
    _log(f"backend probe ok: {r.stdout.strip()}")
    return True


def acquire_devices(
    retries: Optional[int] = None,
    backoff: Optional[float] = None,
) -> List[jax.Device]:
    """``jax.devices()`` that survives transient backend-init failures.

    Returns the device list on success. Raises :class:`BackendInitError`
    with a diagnostic message (never a raw PJRT traceback) once the retry
    budget is exhausted or on a non-transient error.
    """
    if retries is None:
        retries = int(os.environ.get("HOROVOD_BACKEND_INIT_RETRIES", "5"))
    if backoff is None:
        backoff = float(os.environ.get("HOROVOD_BACKEND_INIT_BACKOFF", "2.0"))
    retries = max(1, retries)

    last_exc: Optional[BaseException] = None
    for attempt in range(1, retries + 1):
        try:
            t0 = time.perf_counter()
            devices = jax.devices()
            if attempt > 1:
                _log(f"backend up after {attempt} attempts "
                     f"({time.perf_counter() - t0:.1f}s last attempt)")
            return devices
        except Exception as exc:  # PJRT raises RuntimeError/JaxRuntimeError
            last_exc = exc
            if not _is_transient(exc):
                raise BackendInitError(
                    f"backend init failed with a non-transient error: "
                    f"{type(exc).__name__}: {exc}") from exc
            if attempt < retries:
                sleep = min(backoff * (2 ** (attempt - 1)), 30.0)
                _log(f"backend init attempt {attempt}/{retries} failed "
                     f"({type(exc).__name__}: {str(exc).splitlines()[0][:160]}); "
                     f"resetting client, retrying in {sleep:.0f}s")
                _reset_backends()
                time.sleep(sleep)

    platforms = os.environ.get("JAX_PLATFORMS", "<unset>")
    raise BackendInitError(
        "could not initialize the accelerator backend after "
        f"{retries} attempts (JAX_PLATFORMS={platforms}).\n"
        f"Last error: {type(last_exc).__name__}: {last_exc}\n"
        "Common causes:\n"
        "  - a stale process still holds the TPU chip (check for other "
        "python processes using libtpu; remove /tmp/libtpu_lockfile)\n"
        "  - the TPU runtime/tunnel is still warming up (raise "
        "HOROVOD_BACKEND_INIT_RETRIES / HOROVOD_BACKEND_INIT_BACKOFF)\n"
        "  - wrong platform requested (set JAX_PLATFORMS=tpu, or '' to "
        "auto-select)")
