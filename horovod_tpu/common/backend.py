"""Robust accelerator-backend acquisition with retry/backoff.

The reference assumes NCCL/MPI initialization either succeeds or the job
dies (operations.cc:628-674 busy-waits ``initialization_done``). On TPU the
failure mode is different: the PJRT client can come up slowly or report
transient ``UNAVAILABLE`` while another (stale) client holds the chip, the
tunnel is warming, or libtpu is still initializing. A framework whose
``init()`` dies with a raw traceback on the first such hiccup is unusable on
real pods, and it is exactly what killed the round-1 benchmark.

This module owns the retry policy:

- :func:`acquire_devices` — ``jax.devices()`` with bounded retry/backoff,
  resetting JAX's cached (possibly half-initialized) backend between
  attempts so each retry re-creates the PJRT client from scratch.
- transient-error classification: ``UNAVAILABLE`` / ``DEADLINE_EXCEEDED`` /
  ``ALREADY_EXISTS`` (stale chip lock) / connection failures retry;
  programming errors surface immediately.
- on exhaustion, raise :class:`BackendInitError` carrying an actionable
  diagnostic (platform asked for, attempts made, the usual causes and their
  fixes) instead of a bare PJRT traceback.

Knobs (env):

``HOROVOD_BACKEND_INIT_RETRIES``  max attempts (default 5)
``HOROVOD_BACKEND_INIT_BACKOFF``  initial sleep seconds, doubles per attempt,
                                  capped at 30 (default 2.0)
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

import jax

from .exceptions import HorovodTpuError

# Substrings identifying transient PJRT/plugin failures worth retrying.
# UNAVAILABLE: backend setup/compile error while the client warms up;
# ALREADY_EXISTS / "in use": a stale client still holds the chip lock;
# DEADLINE/connect/reset: tunnel or coordinator hiccups.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ALREADY_EXISTS",
    "RESOURCE_EXHAUSTED",
    "already in use",
    "failed to connect",
    "connection reset",
    "connection refused",
    "socket closed",
    "unable to initialize backend",
)


class BackendInitError(HorovodTpuError):
    """The accelerator backend could not be initialized after retries."""


# ---------------------------------------------------------------------------
# Overlapped-collective scheduling flags (docs/overlap.md).
#
# XLA hides collectives under compute only when (a) the collective lowers
# to an async start/done pair and (b) the latency-hiding scheduler is
# allowed to stretch the start→done window across independent compute.
# Both are TPU compiler flags; on CPU/GPU backends the TPU spellings are
# unknown flags that would crash XLA option parsing, so enabling is
# platform-gated with a graceful no-op fallback.
# ---------------------------------------------------------------------------

# The canonical TPU async-collective + LHS flag set (the same knobs the
# public MaxText/T5X configs ship with).
_OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)


def _requested_platform() -> str:
    """The platform the process is headed for, WITHOUT creating a backend
    (jax.devices() here would freeze XLA_FLAGS before we can edit them):
    jax.config's jax_platforms if set, else the JAX_PLATFORMS env, else
    'auto'."""
    try:
        p = jax.config.jax_platforms  # set by jax.config.update
    except AttributeError:
        p = None
    if not p:
        p = os.environ.get("JAX_PLATFORMS") or ""
    p = p.split(",")[0].strip().lower()
    return p or "auto"


def _backend_already_created() -> bool:
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # pragma: no cover - private-API drift
        return False


def enable_overlap_scheduling(platform: Optional[str] = None) -> bool:
    """Arm XLA's async-collective + latency-hiding-scheduler flags for the
    overlapped gradient reduction (``HOROVOD_OVERLAP=1``, docs/overlap.md).

    Appends :data:`_OVERLAP_XLA_FLAGS` to ``XLA_FLAGS`` so the NEXT PJRT
    client creation compiles collectives as async start/done pairs the
    scheduler can stretch over independent backward compute. Returns True
    when the flags were (or already are) armed for a TPU backend.

    Graceful fallback everywhere else: on cpu/gpu platforms the TPU flag
    spellings don't exist, so this is a logged no-op — the overlap
    *schedule* (stream-ordered buckets, double-buffered microbatches,
    ops/fusion.py) still traces identically; only the compiler-level
    hiding is absent. Call before the first ``jax.devices()``; if a
    backend already exists the flags cannot take effect in this process
    and we say so instead of silently lying.
    """
    platform = (platform or _requested_platform()).lower()
    if platform in ("auto", ""):
        # Only commit to the TPU flag set when a TPU is actually in
        # reach: XLA aborts on unknown flags, so guessing wrong on a
        # CPU-only box would turn the graceful fallback into a crash.
        import glob

        has_tpu = bool(glob.glob("/dev/accel*")) or bool(
            os.environ.get("PALLAS_AXON_POOL_IPS"))
        platform = "tpu" if has_tpu else "cpu"
    if platform != "tpu":
        _log(f"overlap: platform {platform!r} has no async-collective "
             "flag support; running the overlap schedule without "
             "compiler-level latency hiding")
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    missing = [f for f in _OVERLAP_XLA_FLAGS if f not in flags]
    if not missing:
        return True
    if _backend_already_created():
        _log("overlap: the XLA backend is already initialized; async-"
             "collective flags cannot apply to this process (set "
             "HOROVOD_OVERLAP=1 before the first jax.devices() call, or "
             "export XLA_FLAGS yourself)")
        return False
    os.environ["XLA_FLAGS"] = (flags + " " + " ".join(missing)).strip()
    _log("overlap: armed async-collective/latency-hiding XLA flags "
         f"({len(missing)} added)")
    return True


def _is_transient(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}"
    low = msg.lower()
    return any(m.lower() in low for m in _TRANSIENT_MARKERS)


def _reset_backends() -> None:
    """Drop JAX's cached backend so the next ``jax.devices()`` re-creates the
    PJRT client. Private-API use is deliberate and guarded: a failed client
    is cached by jax and would otherwise poison every subsequent attempt."""
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
        try:
            xla_bridge.get_backend.cache_clear()
        except AttributeError:
            pass
    except Exception:  # pragma: no cover - private-API drift
        pass


def _log(msg: str) -> None:
    print(f"[horovod_tpu] {msg}", file=sys.stderr, flush=True)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _device_holders() -> Tuple[List[str], int]:
    """Processes holding ``/dev/accel*`` / ``/dev/vfio*`` open, via a
    /proc fd scan (``fuser`` is not always installed on TPU VMs).
    Returns ``(holders, uninspectable)`` — the second count is pids
    whose fd table we could not read (EACCES as non-root), so "no
    holders found" can be distinguished from "could not look"."""
    import glob

    targets = set(glob.glob("/dev/accel*")) | set(glob.glob("/dev/vfio/*"))
    if not targets:
        return [], 0
    holders: List[str] = []
    uninspectable = 0
    for pdir in glob.glob("/proc/[0-9]*"):
        try:
            fds = os.listdir(os.path.join(pdir, "fd"))
        except PermissionError:
            uninspectable += 1
            continue
        except OSError:
            continue  # process exited mid-scan
        for fd in fds:
            try:
                if os.readlink(os.path.join(pdir, "fd", fd)) in targets:
                    pid = pdir.rsplit("/", 1)[1]
                    with open(os.path.join(pdir, "cmdline"), "rb") as f:
                        cmd = f.read().replace(b"\0", b" ")[:160]
                    holders.append(
                        f"pid {pid}: {cmd.decode(errors='replace')}")
                    break
            except OSError:
                continue
    return holders, uninspectable


def clear_stale_tpu_locks() -> None:
    """Remove libtpu lockfiles whose owning process is dead.

    libtpu serializes chip access through ``/tmp/libtpu_lockfile``; a
    process killed mid-run can leave it behind, and the next PJRT client
    then blocks forever waiting for a holder that no longer exists — the
    exact bring-up hang that cost round 4 its TPU measurement. Lockfiles
    with a live holder are left alone (and logged)."""
    import glob

    for path in glob.glob("/tmp/libtpu_lockfile*"):
        # Liveness via non-blocking flock — the mechanism libtpu itself
        # uses (it does NOT write a pid into the file, so content is no
        # evidence). EWOULDBLOCK => a live process holds the flock;
        # acquiring it proves the lock is orphaned (flocks die with
        # their holder) and we unlink while still holding it.
        try:
            fd = os.open(path, os.O_RDWR)
        except OSError:
            continue  # vanished or unreadable: nothing to clear
        try:
            import fcntl

            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                _log(f"libtpu lockfile {path} is flock-held by a live "
                     "process; not removing (another process owns the "
                     "chip)")
                continue
            # Unlink race guard: if the path no longer names the inode
            # we flocked (someone re-created the file since our open),
            # removing it would delete THEIR lockfile — skip.
            try:
                if os.fstat(fd).st_ino != os.stat(path).st_ino:
                    _log(f"libtpu lockfile {path} was re-created "
                         "concurrently; leaving it alone")
                    continue
            except OSError:
                continue  # already gone: nothing to do
            # Secondary pid heuristic for lockfiles that DO carry one
            # (some runtimes write it): a live pid means keep.
            try:
                txt = os.read(fd, 64).decode(errors="replace").strip()
            except OSError:
                txt = ""
            if txt.isdigit() and _pid_alive(int(txt)):
                _log(f"libtpu lockfile {path} records LIVE pid {txt}; "
                     "not removing")
                continue
            try:
                os.unlink(path)
                _log(f"removed stale libtpu lockfile {path} (no live "
                     "flock holder)")
            except OSError as e:
                _log(f"could not remove libtpu lockfile {path}: {e}")
        finally:
            os.close(fd)


def diagnose_backend() -> None:
    """Log *why* backend bring-up is failing: relay/tunnel reachability,
    device-file holders, lockfiles, and the backend-relevant env — so a
    hung probe leaves an actionable trail instead of a bare timeout
    (VERDICT r4: three silent 150 s timeouts cost the round its TPU
    measurement). Diagnostics must never turn a recoverable probe
    failure into a crash, so every section is exception-guarded."""
    import glob
    import socket

    # 1. Remote-relay runtimes (axon tunnel): is anything listening?
    relay_ips = os.environ.get("PALLAS_AXON_POOL_IPS")
    try:
        if relay_ips:
            port = int(os.environ.get("HOROVOD_AXON_RELAY_PORT",
                                      "8083").strip() or "8083")
            for ip in relay_ips.split(","):
                try:
                    with socket.create_connection((ip.strip(), port),
                                                  timeout=3):
                        _log(f"relay {ip}:{port}: TCP reachable (tunnel "
                             "up; hang is past the transport — likely "
                             "chip-side)")
                except OSError as e:
                    _log(f"relay {ip}:{port}: NOT reachable ({e}) — the "
                         "tunnel/relay process is down; nothing in this "
                         "process can bring the chip back")
    except Exception as e:
        _log(f"relay diagnostics failed: {e}")
    # 2. Local chips: device files + who holds them.
    try:
        accels = sorted(glob.glob("/dev/accel*"))
        if accels:
            _log(f"local TPU device files: {accels}")
            holders, blind = _device_holders()
            if holders:
                _log("device holders (a leftover process wedges PJRT "
                     "creation):\n  " + "\n  ".join(holders))
            elif blind:
                _log(f"no holder found among inspectable processes, but "
                     f"{blind} pids were uninspectable (EACCES — run as "
                     f"root for a definitive answer)")
            else:
                _log("no process holds the device files")
        elif not relay_ips:
            _log("no /dev/accel* files and no relay configured: this "
                 "host has no TPU attached")
    except Exception as e:
        _log(f"device-holder diagnostics failed: {e}")
    # 3. Lockfiles (report only; clear_stale_tpu_locks removes dead ones).
    try:
        locks = glob.glob("/tmp/libtpu_lockfile*")
        if locks:
            _log(f"libtpu lockfiles present: {locks}")
    except Exception:
        pass
    # 4. Backend-relevant env at failure time.
    try:
        keys = sorted(k for k in os.environ
                      if k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_",
                                       "PALLAS_", "AXON_", "PJRT_")))
        env = ", ".join(f"{k}={os.environ[k][:60]}" for k in keys)
        _log(f"backend env: {env or '<none>'}")
    except Exception:
        pass


def probe_backend(timeout: float = 120.0) -> bool:
    """Check from a *subprocess* (with a hard timeout) that the accelerator
    backend can be brought up.

    ``jax.devices()`` can hang indefinitely inside PJRT client creation when
    the TPU runtime/tunnel is wedged — a state no in-process retry loop can
    escape. Probing in a child process turns a hang into a timeout the
    parent survives. A successful probe also warms the runtime, so the
    in-process :func:`acquire_devices` that follows is fast.
    """
    import subprocess

    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        _log(f"backend probe timed out after {timeout:.0f}s "
             "(PJRT client creation hung)")
        diagnose_backend()
        return False
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        _log(f"backend probe failed (rc={r.returncode}): "
             f"{tail[-1][:200] if tail else '<no stderr>'}")
        return False
    _log(f"backend probe ok: {r.stdout.strip()}")
    return True


def acquire_devices(
    retries: Optional[int] = None,
    backoff: Optional[float] = None,
) -> List[jax.Device]:
    """``jax.devices()`` that survives transient backend-init failures.

    Returns the device list on success. Raises :class:`BackendInitError`
    with a diagnostic message (never a raw PJRT traceback) once the retry
    budget is exhausted or on a non-transient error.
    """
    if retries is None:
        retries = int(os.environ.get("HOROVOD_BACKEND_INIT_RETRIES", "5"))
    if backoff is None:
        backoff = float(os.environ.get("HOROVOD_BACKEND_INIT_BACKOFF", "2.0"))
    retries = max(1, retries)

    last_exc: Optional[BaseException] = None
    for attempt in range(1, retries + 1):
        try:
            t0 = time.perf_counter()
            devices = jax.devices()
            if attempt > 1:
                _log(f"backend up after {attempt} attempts "
                     f"({time.perf_counter() - t0:.1f}s last attempt)")
            return devices
        except Exception as exc:  # PJRT raises RuntimeError/JaxRuntimeError
            last_exc = exc
            if not _is_transient(exc):
                raise BackendInitError(
                    f"backend init failed with a non-transient error: "
                    f"{type(exc).__name__}: {exc}") from exc
            if attempt < retries:
                sleep = min(backoff * (2 ** (attempt - 1)), 30.0)
                _log(f"backend init attempt {attempt}/{retries} failed "
                     f"({type(exc).__name__}: {str(exc).splitlines()[0][:160]}); "
                     f"resetting client, retrying in {sleep:.0f}s")
                _reset_backends()
                time.sleep(sleep)

    platforms = os.environ.get("JAX_PLATFORMS", "<unset>")
    raise BackendInitError(
        "could not initialize the accelerator backend after "
        f"{retries} attempts (JAX_PLATFORMS={platforms}).\n"
        f"Last error: {type(last_exc).__name__}: {last_exc}\n"
        "Common causes:\n"
        "  - a stale process still holds the TPU chip (check for other "
        "python processes using libtpu; remove /tmp/libtpu_lockfile)\n"
        "  - the TPU runtime/tunnel is still warming up (raise "
        "HOROVOD_BACKEND_INIT_RETRIES / HOROVOD_BACKEND_INIT_BACKOFF)\n"
        "  - wrong platform requested (set JAX_PLATFORMS=tpu, or '' to "
        "auto-select)")
