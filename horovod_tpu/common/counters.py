"""Fault / retry counters, exported through ``horovod_tpu.common.basics``.

Robustness events (RPC retries, injected faults, blacklist transitions,
stall-watchdog firings) are recorded here so tests and operators can assert
on *how* a job survived, not just that it did. Two scopes:

* **incarnation** — cleared by :func:`reset_incarnation`, which
  ``basics.shutdown()`` calls; in an elastic job this makes the counters
  per world incarnation (the shutdown→init cycle between worlds).
* **total** — cumulative across the life of the process.

Every increment is also emitted as an instant event on the active
:class:`horovod_tpu.utils.timeline.Timeline` (when one is attached), so a
``chrome://tracing`` view of a chaotic run shows exactly when each fault
or retry happened relative to the collectives around it.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_lock = threading.Lock()
_incarnation: Dict[str, int] = {}
_total: Dict[str, int] = {}


def increment(name: str, n: int = 1,
              attrs: Optional[dict] = None) -> None:
    """Bump counter ``name`` by ``n`` and mirror it onto the timeline.

    ``name`` is dot-separated (``rpc.client.retry``, ``chaos.drop``,
    ``elastic.stall.warning``); ``attrs`` ride into the timeline event's
    ``args`` for context (service name, host, attempt number, ...).
    """
    with _lock:
        _incarnation[name] = _incarnation.get(name, 0) + n
        _total[name] = _total.get(name, 0) + n
    _emit_timeline(name, attrs)
    _emit_registry(name, n)


def _emit_timeline(name: str, attrs: Optional[dict]) -> None:
    # Lazy import: counters must stay importable from the launcher/runner
    # processes without dragging framework state along.
    tl = None
    try:
        from . import basics

        tl = basics._state.timeline
    except Exception:  # pragma: no cover - partial interpreter teardown
        return
    if tl is not None:
        tl.instant(f"FAULT:{name}", tid="faults", args=attrs)
        return
    # No timeline attached: fault events still reach the flight
    # recorder's ring directly (a timeline emit would have been tapped),
    # so a dump from an un-traced process carries its fault trail.
    try:
        from ..monitor import flight as _flight

        _flight.instant(f"FAULT:{name}", tid="faults", args=attrs)
    except Exception:  # pragma: no cover - partial interpreter teardown
        return


def _emit_registry(name: str, n: int) -> None:
    """Mirror into the unified metrics registry (monitor/), which keeps
    the process-lifetime monotone view and feeds the metric sinks. Stays
    lazy + guarded for the same launcher-importability reason as the
    timeline mirror (monitor.registry itself is stdlib-only)."""
    try:
        from ..monitor import registry as _mon

        _mon.counter(name).inc(n)
    except Exception:  # pragma: no cover - partial interpreter teardown
        return


def get(name: str, total: bool = False) -> int:
    with _lock:
        return (_total if total else _incarnation).get(name, 0)


def counters(total: bool = False) -> Dict[str, int]:
    """Snapshot of all counters (incarnation scope by default)."""
    with _lock:
        return dict(_total if total else _incarnation)


def reset_incarnation() -> None:
    """Clear the per-incarnation scope (called by ``basics.shutdown()``)."""
    with _lock:
        _incarnation.clear()


def reset_all() -> None:
    """Clear both scopes (tests)."""
    with _lock:
        _incarnation.clear()
        _total.clear()
