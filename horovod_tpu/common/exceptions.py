"""Exception hierarchy for horovod_tpu.

Parity with the reference's ``horovod/common/exceptions.py``:
``HorovodInternalError`` aborts the current step and (under elastic) rolls
back to the last committed state; ``HostsUpdatedInterrupt`` signals a world
change without failure (reference: horovod/common/exceptions.py:19-33,
horovod/common/elastic.py:147-168).
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error in a collective or the runtime.

    Under :func:`horovod_tpu.elastic.run` this triggers state restore and a
    re-initialization with the current world.
    """


class HostsUpdatedInterrupt(Exception):
    """Raised between steps when the host set changed (elastic mode).

    ``skip_sync`` mirrors the reference (common/exceptions.py:28-33): when the
    update was caused by a failure the new state must be restored, not synced.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    """An API requiring ``hvd.init()`` was called before initialization."""

    def __init__(self, what: str = "Horovod-TPU"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first."
        )


class DuplicateTensorNameError(HorovodTpuError):
    """Two in-flight collectives used the same tensor name.

    Reference: DUPLICATE_NAME_ERROR, horovod/common/common.h:163.
    """


class TensorShapeMismatchError(HorovodTpuError):
    """Ranks disagreed on shape/dtype/op for a named collective.

    Reference: the coordinator's cross-rank consistency checks in
    ``Controller::ConstructResponse`` (controller.cc:380-657).
    """
