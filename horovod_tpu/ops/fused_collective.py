"""Fused compute-collective Pallas TPU kernels (docs/fused-kernels.md).

Scheduling-level overlap (docs/overlap.md) hides communication *between*
XLA ops; the remaining exposed cost is the HBM round-trip at the
compute/collective boundary itself — the full matmul product written out
just to be reduce-scattered, the gathered weight buffer written out just
to be matmul'd, the int8 payload + scales written out between the
quantize op and the wire. Following "Fused Computation-Collective
Operations" (arXiv:2305.06942) and T3 (arXiv:2401.16677), this module
fuses the three hot pairs into Pallas kernels so the boundary tensor
never materializes:

* :func:`fused_matmul_reduce_scatter` — **matmul → reduce-scatter
  epilogue** (ZeRO stage-2/3 gradient shards, TP row-parallel outputs):
  a ring of ``world`` steps where each step's Pallas kernel computes the
  output tile destined for one owner and accumulates it INTO the
  traveling partial-sum buffer; only a ``[M/world, N]`` tile ever exists
  per rank instead of the full ``[M, N]`` product. The ring hop
  (``lax.ppermute`` riding ICI/DCN neighbours) overlaps the next tile's
  MXU work under XLA's async collective scheduling — the same
  composition idiom as ``flash_ring_attention`` (ops/flash_attention.py).
* :func:`fused_all_gather_matmul` — **all-gather → matmul prologue**
  (ZeRO-3 JIT param gather, TP column-parallel inputs): weight shards
  rotate around the ring and each arriving shard feeds the next partial
  matmul while the previous one computes; the full ``[K, N]`` gathered
  weight never exists in HBM.
* :func:`quantize_blockwise` / :func:`dequantize_accumulate` —
  **in-kernel blockwise int8 quantize / dequant-accumulate** for the DCN
  legs of the quantized wire plans (EQuARX, arXiv:2506.17615: the
  quantization rides inside the collective): absmax, scales, rounding,
  and the error-feedback residual are produced in ONE VMEM pass, and the
  receiver's dequant-multiply-accumulate never expands the int8 payload
  to fp32 in HBM. The plan compiler invokes these when a leg carries
  ``backend="pallas"`` (``Leg(..., backend="pallas")``, plan/ir.py).

Wire bytes are IDENTICAL to the unfused lowerings (the ring moves the
same ``(n-1)/n`` payload the XLA collective would); the win is the
avoided HBM round-trip, which every kernel call credits to the trace-time
accounting (:func:`horovod_tpu.plan.accounting.fused_span` →
``FUSED:*`` timeline spans, ``comm.fused.*`` metrics,
``WireStats.fused_hbm_saved_bytes``).

Off-TPU every kernel runs in Pallas interpreter mode
(``pallas_call(interpret=True)``), so the CPU tier-1 suite exercises the
identical code path on the 8-device emulated mesh; the fused-vs-unfused
parity matrix lives in tests/test_fused_collective.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

# flash_attention installs the jax<0.6 shard_map replication rule for
# pallas_call and the CompilerParams alias — import for the side effects.
from . import flash_attention as _flash
from ..plan.accounting import _acct, _acct_enabled, fused_span

_interpret = _flash._interpret
_out_struct = _flash._out_struct


def _block_k_knob() -> int:
    from ..common.config import _env_int

    v = _env_int("HOROVOD_FUSED_BLOCK_K", 512)
    if v < 128:
        raise ValueError(
            f"HOROVOD_FUSED_BLOCK_K={v}: Pallas kernel blocks must be "
            f">= 128 (MXU/lane tile)")
    return v


def _resolve_axes(axes) -> Tuple[str, ...]:
    from .collective_ops import _resolve_axes as _ra

    return _ra(axes)


def _vary(x, axes_t, *others):
    from .collective_ops import _vma, pvary_missing

    union = set(axes_t) | frozenset().union(*[_vma(t) for t in others])
    return pvary_missing(x, tuple(sorted(union)))


# ---------------------------------------------------------------------------
# HBM-traffic model: bytes the fusion avoids round-tripping vs the
# separate-op lowering. ONE definition shared by the kernels' trace-time
# accounting, the planner's --dump-plan delta line, and the tests/bench
# assertions (docs/fused-kernels.md, "HBM model").
# ---------------------------------------------------------------------------


def matmul_rs_hbm_saved(m: int, n: int, world: int, itemsize: int) -> float:
    """Unfused: the full [m, n] partial product writes to HBM and the
    reduce-scatter reads it back; fused keeps all but this rank's final
    [m/world, n] tile in VMEM → 2 * (1 - 1/world) * m*n*itemsize."""
    return 2.0 * (m - m // max(1, world)) * n * float(itemsize)


def ag_matmul_hbm_saved(k: int, n: int, world: int, itemsize: int) -> float:
    """Unfused: the gathered [k, n] weight writes to HBM (all-gather) and
    the matmul reads it back; fused streams each arriving shard straight
    into the MXU → 2 * (1 - 1/world) * k*n*itemsize (this rank's own
    shard lives in HBM either way)."""
    return 2.0 * (k - k // max(1, world)) * n * float(itemsize)


def quant_hbm_saved(rows: int, nb: int, blk: int) -> float:
    """Unfused: the int8 payload and fp32 scales materialize in HBM
    between the quantize op and the wire (write + read); fused produces
    them in the VMEM pass that already holds the blocks →
    2 * (rows*nb*blk * 1B + rows*nb * 4B)."""
    return 2.0 * (rows * nb * blk * 1.0 + rows * nb * 4.0)


def dequant_hbm_saved(rows: int, nb: int, blk: int) -> float:
    """Unfused: the dequantized fp32 expansion [rows, nb, blk]
    materializes before the sum; fused multiply-accumulates in VMEM →
    2 * rows*nb*blk * 4B."""
    return 2.0 * rows * nb * blk * 4.0


# ---------------------------------------------------------------------------
# Kernel bodies.
# ---------------------------------------------------------------------------


def _mm_acc_kernel(x_ref, w_ref, acc_ref, o_ref, acc_scr, *, nk):
    """o = acc + x @ w, K-blocked: grid axis 0 walks the contraction in
    ``bk`` slabs with the fp32 accumulator resident in VMEM scratch — the
    ring-step tile matmul of both fusion pairs."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = acc_ref[...].astype(jnp.float32)

    acc_scr[:] += lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        o_ref[...] = acc_scr[:].astype(o_ref.dtype)


def _matmul_accumulate(x, w, acc, *, block_k: Optional[int] = None):
    """acc + x @ w through the Pallas tile kernel (fp32 accumulate).

    x [m, K], w [K, N], acc [m, N] → [m, N] in acc.dtype. The contraction
    is ``block_k``-blocked (HOROVOD_FUSED_BLOCK_K, default 512, snapped
    to a 128-aligned divisor of K like the flash kernels; whole-K when
    nothing divides)."""
    m, K = x.shape
    N = w.shape[1]
    bk = _flash._pick_block(K, block_k or _block_k_knob()) or K
    nk = K // bk
    return pl.pallas_call(
        functools.partial(_mm_acc_kernel, nk=nk),
        grid=(nk,),
        in_specs=[
            pl.BlockSpec((m, bk), lambda j: (0, j)),
            pl.BlockSpec((bk, N), lambda j: (j, 0)),
            pl.BlockSpec((m, N), lambda j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((m, N), lambda j: (0, 0)),
        out_shape=_out_struct((m, N), acc.dtype, x, w, acc),
        scratch_shapes=[pltpu.VMEM((m, N), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(x, w, acc)


def _quant_kernel(b_ref, q_ref, s_ref, e_ref):
    """Blockwise int8 quantize, one VMEM pass: absmax → scales → rounded
    payload → error residual. The math is byte-for-byte the
    ``_block_scales`` + clip/round composition of ops/compression.py, so
    the wire FORMAT is identical to the XLA lowering (values agree to
    the last ulp of the scale division; tests ulp-bound it)."""
    blocks = b_ref[...]
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    scales = jnp.where(absmax > 0, absmax / 127.0, jnp.ones_like(absmax))
    q = jnp.clip(jnp.round(blocks / scales[..., None]), -127, 127)
    qi = q.astype(jnp.int8)
    e_ref[...] = blocks - qi.astype(jnp.float32) * scales[..., None]
    q_ref[...] = qi
    s_ref[...] = scales


def quantize_blockwise(blocks):
    """Fused blockwise int8 quantization of fp32 ``blocks``
    ``[rows, nb, blk]`` → ``(q int8 [rows, nb, blk], scales fp32
    [rows, nb], err fp32 [rows, nb, blk])`` — the kernel behind
    ``backend="pallas"`` on an int8 reduce-scatter/all-gather leg."""
    rows, nb, blk = blocks.shape
    with fused_span("QUANT", quant_hbm_saved(rows, nb, blk)):
        return pl.pallas_call(
            _quant_kernel,
            out_shape=[
                _out_struct((rows, nb, blk), jnp.int8, blocks),
                _out_struct((rows, nb), jnp.float32, blocks),
                _out_struct((rows, nb, blk), jnp.float32, blocks),
            ],
            interpret=_interpret(),
        )(blocks)


def _dequant_acc_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = jnp.sum(
        q_ref[...].astype(jnp.float32) * s_ref[...][..., None], axis=0)


def dequantize_accumulate(qT, sT):
    """Fused dequant-multiply-accumulate: ``sum_r qT[r] * sT[r]`` over
    the leading (contributor) axis without expanding the int8 payload to
    fp32 in HBM. qT ``[rows, nb, blk]`` int8, sT ``[rows, nb]`` fp32 →
    ``[nb, blk]`` fp32."""
    rows, nb, blk = qT.shape
    with fused_span("DEQUANT", dequant_hbm_saved(rows, nb, blk)):
        return pl.pallas_call(
            _dequant_acc_kernel,
            out_shape=_out_struct((nb, blk), jnp.float32, qT, sT),
            interpret=_interpret(),
        )(qT, sT)


# ---------------------------------------------------------------------------
# Ring wire accounting: the fused rings move exactly the bytes the
# unfused collective would — (n-1) hops of the tile/shard — charged with
# the same per-device model as plan/accounting.py. Rank-major over the
# (pod, cross, local) axis tuple, nc of every n ring sends cross a host
# boundary, so that fraction is DCN-class.
# ---------------------------------------------------------------------------


def _acct_ring(axes_t, hop_bytes: float, hops: int) -> None:
    if not _acct_enabled():
        return
    from ..common import basics
    from .collective_ops import _axis_size

    sizes = {a: _axis_size(a) for a in axes_t}
    total = hop_bytes * hops
    if set(axes_t) == {basics.LOCAL_AXIS}:
        _acct("ici", total)
        return
    if basics.LOCAL_AXIS not in sizes:
        _acct("dcn", total)  # cross/pod-only ring: every hop is slow wire
        return
    # Of the n directed ring links (rank-major order), n/nl cross a host
    # boundary (the wrap from local index nl-1 to 0 of the next host).
    nl = max(1, sizes[basics.LOCAL_AXIS])
    _acct("dcn", total / nl)
    _acct("ici", total * (1.0 - 1.0 / nl))


# ---------------------------------------------------------------------------
# Fusion pair (a): matmul → reduce-scatter epilogue.
# ---------------------------------------------------------------------------


def fused_matmul_reduce_scatter(x, w, *, axes=None,
                                block_k: Optional[int] = None):
    """Reduce-scattered matmul: rank-major ``[M/world, N]`` shard of
    ``sum_r x_r @ w_r`` without materializing any rank's full ``[M, N]``
    partial product.

    The TP row-parallel / ZeRO gradient epilogue: each rank holds a
    per-rank ``x [M, K]`` and ``w [K, N]`` (e.g. activations × local
    weight rows, or ``h^T × dh`` for a data-parallel weight gradient
    whose reduce-scattered rows are exactly the ZeRO stage-2/3 gradient
    shard). A ``world``-step ring runs: at step ``i`` the Pallas tile
    kernel (:func:`_matmul_accumulate`) computes the row tile destined
    for rank ``(my + world - 1 - i) % world`` and accumulates it into
    the traveling partial-sum buffer, which then hops to the next rank
    (``lax.ppermute``); after the last step each rank holds its own
    fully-summed tile. Wire bytes equal the unfused reduce-scatter's
    ``(n-1)/n * M*N``; the saved HBM round-trip is
    :func:`matmul_rs_hbm_saved`.

    Must run inside ``hvd.shard_map``; ``M`` must divide by the world
    size (pad like ``plan_buckets(shard_multiple=world)``)."""
    axes_t = _resolve_axes(axes)
    M, K = x.shape
    N = w.shape[1]
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    if not axes_t:
        # Eager/world-of-one: the epilogue degenerates to the local tile.
        return jnp.dot(x, w).astype(out_dtype)
    from .collective_ops import _world_size

    n = _world_size(axes_t)
    if M % n:
        raise ValueError(
            f"fused_matmul_reduce_scatter: M={M} does not divide into "
            f"{n} row tiles — pad the leading dim to a world multiple "
            f"(plan_buckets(shard_multiple=world) idiom)")
    seg = M // n
    isz = jnp.dtype(out_dtype).itemsize
    _acct_ring(axes_t, float(seg) * N * isz, n - 1)
    my = lax.axis_index(axes_t)
    perm = [(r, (r + 1) % n) for r in range(n)]
    with fused_span("MATMUL_RS", matmul_rs_hbm_saved(M, N, n, isz)):
        x = _vary(x, axes_t, w)
        w = _vary(w, axes_t, x)
        acc = _vary(jnp.zeros((seg, N), out_dtype), axes_t, x, w)
        for i in range(n):
            dst = (my + n - 1 - i) % n
            xt = lax.dynamic_slice_in_dim(x, dst * seg, seg, 0)
            acc = _matmul_accumulate(xt, w, acc, block_k=block_k)
            if i < n - 1:
                acc = lax.ppermute(acc, axes_t, perm)
    return acc


# ---------------------------------------------------------------------------
# Fusion pair (b): all-gather → matmul prologue.
# ---------------------------------------------------------------------------


def fused_all_gather_matmul(x, w_shard, *, axes=None,
                            block_k: Optional[int] = None):
    """``x @ W`` where ``W`` lives as rank-major row shards
    (``w_shard [K/world, N]`` — the ZeRO-3 parameter layout), without
    materializing the gathered ``[K, N]`` weight.

    The ring all-gather is fused into the contraction: after ``i`` hops
    this rank holds shard ``(my - i) % world``, the Pallas tile kernel
    contracts it against the matching ``K``-column slab of ``x`` and
    accumulates into the local output while the shard hops onward — the
    arriving weight rows feed the next tile's matmul under the current
    tile's compute (T3's fine-grained prologue overlap). Wire bytes
    equal the unfused all-gather's ``(n-1)/n * K*N``; the saved HBM
    round-trip is :func:`ag_matmul_hbm_saved`.

    Returns ``[M, N]`` in the promoted dtype — device-varying (it feeds
    this rank's forward compute, like ``zero3_gather_params`` output).
    Must run inside ``hvd.shard_map`` with ``x.shape[1] ==
    w_shard.shape[0] * world``."""
    axes_t = _resolve_axes(axes)
    M, K = x.shape
    kseg, N = w_shard.shape
    out_dtype = jnp.promote_types(x.dtype, w_shard.dtype)
    if not axes_t:
        return jnp.dot(x, w_shard).astype(out_dtype)
    from .collective_ops import _world_size

    n = _world_size(axes_t)
    if K != kseg * n:
        raise ValueError(
            f"fused_all_gather_matmul: x has K={K} columns but the "
            f"shard ring gathers {kseg} x {n} = {kseg * n} weight rows "
            f"— w_shard must be the rank-major [K/world, N] row shard")
    isz = jnp.dtype(out_dtype).itemsize
    _acct_ring(axes_t, float(kseg) * N * isz, n - 1)
    my = lax.axis_index(axes_t)
    perm = [(r, (r + 1) % n) for r in range(n)]
    with fused_span("AG_MATMUL", ag_matmul_hbm_saved(K, N, n, isz)):
        x = _vary(x, axes_t, w_shard)
        w = _vary(w_shard, axes_t, x)
        acc = _vary(jnp.zeros((M, N), out_dtype), axes_t, x, w)
        for i in range(n):
            src = (my - i) % n  # whose rows we hold after i hops
            xt = lax.dynamic_slice_in_dim(x, src * kseg, kseg, 1)
            acc = _matmul_accumulate(xt, w, acc, block_k=block_k)
            if i < n - 1:
                w = lax.ppermute(w, axes_t, perm)
    return acc
