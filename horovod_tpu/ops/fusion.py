"""Tensor fusion: pack many small tensors into few large collectives.

Reference: the 64 MiB fusion buffer (fusion_buffer_manager.{h,cc},
operations.cc:437) plus ``Controller::FuseResponses`` which bins ready
tensors under the threshold with look-ahead across mixed dtypes
(controller.cc:686-809). Fusion is Horovod's single most important
performance feature: it amortizes per-collective launch latency over many
gradients.

TPU-native redesign
-------------------
Under XLA, shapes are static at trace time, so fusion needs no runtime
negotiation at all: we pack the gradient pytree into flat per-dtype buckets
**once, during tracing**, and every compiled step reduces whole buckets. The
response-cache "learned schedule" of the reference (response_cache.cc — the
steady-state fast path) becomes simply the XLA compilation cache: the first
trace fixes the fused schedule, subsequent steps replay it at zero
negotiation cost.

Bucketing mirrors the reference policy: greedy first-fit in tree order,
per-dtype buffers (mixed dtypes can't share one XLA collective), capped at
``HOROVOD_FUSION_THRESHOLD`` bytes, and bucket lengths rounded up to a
multiple of 64 elements so hierarchical reduce-scatter shards evenly
(reference: FUSION_BUFFER_ATOMIC_UNIT, common.h:97; controller.cc:360-378).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..common.config import _env_bool
from . import collective_ops as C
from .compression import Compression

# Reference: FUSION_BUFFER_ATOMIC_UNIT = 64 (common.h:97) — keeps fused
# buffers divisible for hierarchical/Adasum sharding.
ATOMIC_UNIT = 64


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fused buffer: which flat leaves it holds and how to unpack them."""

    dtype: Any
    leaf_indices: Tuple[int, ...]
    sizes: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    padded_size: int  # total elements, rounded up to ATOMIC_UNIT


def plan_buckets(
    leaves: Sequence[jax.Array],
    threshold_bytes: Optional[int] = None,
    *,
    shard_multiple: int = 1,
) -> List[Bucket]:
    """Greedy first-fit bucketing in leaf order, one buffer per dtype run.

    Matches the reference's FuseResponses policy (controller.cc:686-809):
    walk tensors in order, open a new buffer when the current one would
    exceed the threshold or the dtype changes (the reference's look-ahead
    skips over mixed dtypes; leaf order here is pytree order, which is
    deterministic, so we simply group by dtype).

    Guarantees the autotuner's warm-start cache key relies on: the plan
    is a pure, deterministic function of (leaf order, shapes, dtypes,
    threshold) — identical pytrees always produce identical plans; a
    single leaf larger than the threshold becomes its own bucket (never
    an error, and never shared — a following small leaf must not ride a
    bucket that already blew past the cap); 0-d and zero-size leaves
    count as one element (the reference's min-1 slot).

    ``shard_multiple`` (the ZeRO-sharding hook) rounds every bucket's
    padded size up to a multiple of ``lcm(ATOMIC_UNIT, shard_multiple)``
    instead of plain ``ATOMIC_UNIT``, so the flat buffer reduce-scatters
    evenly into ``shard_multiple`` per-rank shards (pass the world size).
    It never changes WHICH leaves share a bucket — only the tail padding —
    so plans for different world sizes unpack identically (the elastic
    reshard path relies on this)."""
    if threshold_bytes is None:
        threshold_bytes = (
            basics.config().fusion_threshold_bytes
            if basics.is_initialized()
            else 64 * 1024 * 1024
        )
    unit = int(np.lcm(ATOMIC_UNIT, max(1, int(shard_multiple))))
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(_leaf_dtype(leaf), []).append(i)

    buckets: List[Bucket] = []
    for dtype, idxs in by_dtype.items():
        itemsize = jnp.dtype(dtype).itemsize
        cur_idx: List[int] = []
        cur_elems = 0
        max_elems = max(ATOMIC_UNIT, threshold_bytes // itemsize)
        for i in idxs:
            n = int(np.prod(_leaf_shape(leaves[i]), dtype=np.int64)) or 1
            if cur_idx and cur_elems + n > max_elems:
                buckets.append(_close_bucket(dtype, cur_idx, leaves, unit))
                cur_idx, cur_elems = [], 0
            cur_idx.append(i)
            cur_elems += n
            if n > max_elems:
                # Oversized leaf: its own bucket, closed immediately.
                buckets.append(_close_bucket(dtype, cur_idx, leaves, unit))
                cur_idx, cur_elems = [], 0
        if cur_idx:
            buckets.append(_close_bucket(dtype, cur_idx, leaves, unit))
    return buckets


def _leaf_dtype(leaf):
    """Leaf dtype without materializing the value — abstract leaves
    (``jax.ShapeDtypeStruct`` templates, the ZeRO-3 gather path) plan
    identically to concrete arrays."""
    dt = getattr(leaf, "dtype", None)
    return jnp.dtype(dt) if dt is not None else jnp.asarray(leaf).dtype


def _leaf_shape(leaf) -> Tuple[int, ...]:
    s = getattr(leaf, "shape", None)
    return tuple(s) if s is not None else tuple(jnp.shape(leaf))


def _close_bucket(dtype, idxs: List[int], leaves,
                  unit: int = ATOMIC_UNIT) -> Bucket:
    shapes = tuple(_leaf_shape(leaves[i]) for i in idxs)
    sizes = tuple(int(np.prod(s, dtype=np.int64)) or 1 for s in shapes)
    total = sum(sizes)
    padded = ((total + unit - 1) // unit) * unit
    return Bucket(dtype=dtype, leaf_indices=tuple(idxs), sizes=sizes,
                  shapes=shapes, padded_size=padded)


def stream_order(buckets: Sequence[Bucket]) -> Tuple[int, ...]:
    """Reverse-layer bucket issue schedule (docs/overlap.md).

    Backprop produces gradients output-side first: for a forward-ordered
    parameter pytree that means the HIGHEST leaf indices become ready
    earliest. Issuing the bucket holding the highest leaf index first
    aligns collective program order with data readiness, so a streamed
    bucket can launch while the backward of earlier (input-side) layers
    is still running — the compiled-path analogue of Horovod's background
    coordinator starting reductions mid-backprop.

    Only the ISSUE order changes; leaf→bucket assignment comes unchanged
    from :func:`plan_buckets`, so every bucket carries identical contents
    (and, on the quantized wire, identical scale-block boundaries) to the
    in-order schedule — any collective sequence issued this way computes
    bit-identical values. Ties (impossible within one dtype group, since
    leaf indices are unique) break by bucket index for determinism."""
    return tuple(sorted(range(len(buckets)),
                        key=lambda j: (-max(buckets[j].leaf_indices), j)))


def gather_order(buckets: Sequence[Bucket]) -> Tuple[int, ...]:
    """Forward-order bucket issue schedule — :func:`stream_order`'s
    mirror for the ZeRO-3 just-in-time parameter gather (docs/zero.md).

    The forward pass consumes parameters input-side first: for a
    forward-ordered pytree the LOWEST leaf indices are needed earliest.
    Issuing the bucket holding the lowest leaf index first lets the
    latency-hiding scheduler run the gathers of deeper layers' buckets
    under the compute of the layers already gathered — T3's fine-grained
    prologue overlap at bucket granularity. Contents are untouched
    (leaf→bucket assignment comes from :func:`plan_buckets`), so any
    issue order computes bit-identical values; ties break by bucket
    index for determinism."""
    return tuple(sorted(range(len(buckets)),
                        key=lambda j: (min(buckets[j].leaf_indices), j)))


def _resolve_overlap(overlap, num_comm_streams, tuned_params):
    """(overlap_on, streams): explicit args > TunedParams override >
    HOROVOD_OVERLAP / HOROVOD_NUM_COMM_STREAMS config."""
    if tuned_params is not None:
        if overlap is None:
            overlap = tuned_params.overlap
        if num_comm_streams is None:
            num_comm_streams = tuned_params.num_comm_streams
    if overlap is None:
        overlap = (basics.config().overlap if basics.is_initialized()
                   else _env_bool("HOROVOD_OVERLAP", False))
    if num_comm_streams is None:
        num_comm_streams = (basics.config().num_comm_streams
                            if basics.is_initialized() else 1)
    return bool(overlap), max(1, int(num_comm_streams))


def pack(bucket: Bucket, leaves: Sequence[jax.Array]) -> jax.Array:
    """Concatenate the bucket's leaves into one flat padded buffer (the
    MemcpyInFusionBuffer analogue, collective_operations.cc:34-59 — here a
    traced concatenate that XLA fuses). A zero-size leaf still owns its
    min-1 slot in the plan (plan_buckets), so it packs as slot padding."""
    flat = []
    for i, size in zip(bucket.leaf_indices, bucket.sizes):
        v = jnp.ravel(jnp.asarray(leaves[i]))
        if v.shape[0] < size:  # zero-size leaf: fill its min-1 slot
            v = jnp.zeros((size,), dtype=v.dtype)
        flat.append(v)
    buf = jnp.concatenate(flat) if len(flat) > 1 else flat[0]
    pad = bucket.padded_size - buf.shape[0]
    if pad:
        buf = jnp.concatenate([buf, jnp.zeros((pad,), dtype=buf.dtype)])
    return buf


def unpack(bucket: Bucket, buf: jax.Array) -> List[jax.Array]:
    """Split a fused buffer back into leaves (MemcpyOutFusionBuffer)."""
    out = []
    off = 0
    for size, shape in zip(bucket.sizes, bucket.shapes):
        n = int(np.prod(shape, dtype=np.int64))  # real elems (slot >= 1)
        out.append(jnp.reshape(buf[off:off + n], shape))
        off += size
    return out


# ---------------------------------------------------------------------------
# ZeRO shard layout: a bucket planned with ``shard_multiple=world`` divides
# into ``world`` equal contiguous shards in RANK-MAJOR order — rank
# ``r = cross_rank * local_size + local_rank`` owns elements
# ``[r * seg, (r + 1) * seg)`` of the flat buffer (``seg = padded // world``).
# The compiled reduce-scatter/all-gather (ops/collective_ops.py) produce and
# consume exactly this layout, and because it matches how ``P(HVD_AXES)``
# splits a leading dim, a ZeRO optimizer-state leaf outside the trace is
# simply the flat bucket itself, sharded — no permutation to undo when
# checkpointing or elastically resharding.
# ---------------------------------------------------------------------------


def shard_size(bucket: Bucket, world: int) -> int:
    """Per-rank shard elements of a bucket planned with
    ``shard_multiple=world``."""
    if bucket.padded_size % world:
        raise ValueError(
            f"bucket padded_size {bucket.padded_size} does not divide into "
            f"{world} shards — plan with plan_buckets(shard_multiple=world)")
    return bucket.padded_size // world


def shard_slice(buf: jax.Array, world: int, rank) -> jax.Array:
    """This rank's contiguous flat shard of a packed bucket buffer.
    ``rank`` may be a traced per-device index (``hvd.rank()`` inside
    shard_map) or a python int (host-side slicing for elastic reshard)."""
    if buf.shape[0] % world:
        raise ValueError(
            f"buffer of {buf.shape[0]} elements does not divide into "
            f"{world} shards")
    seg = buf.shape[0] // world
    import jax.lax as lax

    return lax.dynamic_slice_in_dim(buf, rank * seg, seg, 0)


def shard_unslice(shards: Sequence[jax.Array]) -> jax.Array:
    """Reassemble a flat bucket buffer from its per-rank shards in rank
    order (the host-side inverse of :func:`shard_slice`; in-trace the
    all-gather collective does this on the wire)."""
    shards = [jnp.ravel(jnp.asarray(s)) for s in shards]
    return jnp.concatenate(shards) if len(shards) > 1 else shards[0]


def allreduce_pytree(
    tree,
    *,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    compression=Compression.none,
    threshold_bytes: Optional[int] = None,
    axes=None,
    hierarchical: Optional[bool] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    presummed: bool = False,
    quantized: Optional[bool] = None,
    error_feedback=None,
    block: Optional[int] = None,
    tuned_params=None,
    overlap: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
):
    """Allreduce every leaf of a pytree with tensor fusion.

    This is what :class:`horovod_tpu.DistributedOptimizer` runs on the
    gradient tree — the analogue of the reference's per-step fused
    NCCL allreduce cycle (RunLoopOnce → FuseResponses → NCCLAllreduce,
    operations.cc:571-624).

    Leaves that are already replicated across the mesh axes (VMA-invariant)
    are handled without a collective; ``presummed`` controls their
    interpretation (see :func:`collective_ops._reduce_replicated`). The
    default ``presummed=False`` gives plain collective semantics (equal
    contributions); the gradient paths (DistributedOptimizer, tape) pass
    ``presummed=True`` because shard_map autodiff auto-psums gradients of
    replicated parameters. Only genuinely per-rank leaves are packed into
    fused buffers and reduced on the wire.

    ``quantized`` routes each fused bucket through the blockwise-int8 DCN
    wire (the quantized allreduce plan, plan/compiler.py); bucket padding to
    ``ATOMIC_UNIT`` keeps the per-block scales aligned with the shard
    layout. ``error_feedback`` is a pytree of per-rank residual
    accumulators matching ``tree`` (zeros initially); when given, the
    return value becomes ``(reduced_tree, new_error_feedback)`` — residuals
    are packed with the same bucket plan as the gradients, so each bucket
    carries its quantization error into the next step (EF-SGD). Non-float
    and replicated leaves pass their residual through unchanged (it stays
    zero).

    ``tuned_params`` (an ``autotune.TunedParams``) applies an autotuner
    override: it fills ``threshold_bytes``, ``hierarchical``, the int8
    scale-``block``, and the ``overlap``/``num_comm_streams`` pair
    wherever the caller left them unset, so a tuning session (or its
    frozen winner) steers the trace without touching the process-wide env
    config. Explicit per-call arguments still win.

    ``overlap`` (default: the ``HOROVOD_OVERLAP`` knob) issues the bucket
    collectives through the reverse-layer stream schedule
    (:func:`stream_order` + per-bucket
    :func:`~horovod_tpu.ops.collective_ops.allreduce_stream`), in flights
    of ``num_comm_streams`` buckets whose unpacking is deferred until the
    flight is issued — so up to that many collectives sit in the program
    with no consumer between them and the latency-hiding scheduler can
    run them under backward compute. Bucket contents and per-bucket math
    are untouched, so overlap mode is bit-identical to off
    (docs/overlap.md).

    ``plan`` (a :class:`horovod_tpu.plan.WirePlan` for the gradient
    allreduce) threads the wire composition explicitly instead of the
    boolean knobs, which remain as aliases: wherever a knob is unset it
    derives from the plan (``quantized`` from its int8 legs,
    ``hierarchical`` from its tree shape, ``overlap``/``num_comm_streams``
    from its stream placement), and the per-bucket collectives lower
    through exactly this plan (docs/wire-plan.md)."""
    if plan is not None:
        plan = plan.validate()
        if quantized is None:
            quantized = plan.is_quantized
        if hierarchical is None:
            hierarchical = plan.is_tree and not plan.is_quantized
        if block is None:
            block = plan.quant_block
        if overlap is None:
            overlap = plan.overlap
        if num_comm_streams is None:
            num_comm_streams = plan.streams
    if tuned_params is not None:
        if threshold_bytes is None:
            threshold_bytes = tuned_params.fusion_threshold_bytes
        if hierarchical is None:
            hierarchical = tuned_params.hierarchical_allreduce
        if block is None:
            block = tuned_params.quant_block
        if fused is None:
            # Same resolution DistributedOptimizer applies: the tuned
            # kernel-backend knob steers the wire wherever the caller
            # left it unset (docs/fused-kernels.md).
            fused = getattr(tuned_params, "fused", None)
    leaves, treedef = jax.tree.flatten(tree)
    if error_feedback is not None:
        quantized = True if quantized is None else quantized
        ef_leaves = jax.tree.flatten(error_feedback)[0]
        if len(ef_leaves) != len(leaves):
            raise ValueError(
                "error_feedback tree structure does not match the gradient "
                f"tree ({len(ef_leaves)} vs {len(leaves)} leaves)")
    if not leaves:
        return tree if error_feedback is None else (tree, error_feedback)
    axes_t = C._resolve_axes(axes)
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    new_ef: List[Optional[jax.Array]] = (
        None if error_feedback is None else list(ef_leaves))

    varying_idx: List[int] = []
    for i, leaf in enumerate(leaves):
        if axes_t and C._is_replicated(leaf, axes_t):
            out[i] = C.allreduce(
                leaf, op=op, compression=compression, axes=axes,
                hierarchical=hierarchical, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor, quantized=quantized,
                block=block, fused=fused, plan=plan, _presummed=presummed)
        else:
            varying_idx.append(i)

    if varying_idx:
        vleaves = [leaves[i] for i in varying_idx]
        v_ef = (None if new_ef is None
                else [ef_leaves[i] for i in varying_idx])
        buckets = plan_buckets(vleaves, threshold_bytes)
        overlap_on, n_streams = _resolve_overlap(overlap, num_comm_streams,
                                                 tuned_params)
        order = (stream_order(buckets) if overlap_on
                 else tuple(range(len(buckets))))
        flight = n_streams if overlap_on else 1
        for s in range(0, len(order), flight):
            issued = []
            for j in order[s:s + flight]:
                bucket = buckets[j]
                buf = pack(bucket, vleaves)
                use_ef = (new_ef is not None
                          and jnp.issubdtype(bucket.dtype, jnp.floating))
                if use_ef:
                    rbuf = pack(bucket, v_ef)
                    if overlap_on:
                        red, rnew = C.allreduce_stream(
                            buf, rbuf, bucket_id=j, op=op,
                            compression=compression, axes=axes,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor, block=block,
                            fused=fused, plan=plan)
                    else:
                        red, rnew = C.quantized_allreduce(
                            buf, rbuf, op=op, compression=compression,
                            axes=axes, prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor, block=block,
                            fused=fused, plan=plan)
                else:
                    rnew = None
                    kw = dict(op=op, compression=compression, axes=axes,
                              hierarchical=hierarchical,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              quantized=quantized, block=block,
                              fused=fused, plan=plan)
                    red = (C.allreduce_stream(buf, bucket_id=j, **kw)
                           if overlap_on else C.allreduce(buf, **kw))
                issued.append((j, red, rnew))
            # Unpack AFTER the whole flight is issued: no consumer sits
            # between in-flight collectives, so the scheduler may run
            # them concurrently (flight == 1 reproduces the serial
            # issue→unpack order of overlap-off exactly).
            for j, red, rnew in issued:
                bucket = buckets[j]
                if rnew is not None:
                    for i, r in zip(bucket.leaf_indices,
                                    unpack(bucket, rnew)):
                        new_ef[varying_idx[i]] = r
                for i, leaf in zip(bucket.leaf_indices, unpack(bucket, red)):
                    out[varying_idx[i]] = leaf
    result = jax.tree.unflatten(treedef, out)
    if error_feedback is None:
        return result
    return result, jax.tree.unflatten(treedef, new_ef)
