"""Collective operations: allreduce / allgather / broadcast / alltoall / join.

Reference surface: the op set of ``horovod/common/message.h:50-52``
(ALLREDUCE, ALLGATHER, BROADCAST, JOIN, ADASUM, ALLTOALL) exposed per
framework as ``hvd.allreduce/allgather/broadcast/alltoall``
(torch/mpi_ops.py:130-646, tensorflow/mpi_ops.py).

TPU-native redesign
-------------------
The reference executes every collective from a background thread through
NCCL/MPI/Gloo after a rank-0 negotiation round (operations.cc:571-624).  On
TPU the fast path is the opposite: collectives are **compiled into the XLA
program** over the ICI mesh, where XLA schedules and fuses them with compute.
So each op here has two modes, selected automatically:

* **compiled (in-jit)** — when tracing under ``jax.shard_map`` over the
  Horovod mesh axes, ops lower straight to ``lax.psum`` / ``lax.all_gather``
  / ``lax.all_to_all`` / masked-``psum`` broadcast.  This is the analogue of
  the reference's NCCL ops (nccl_operations.cc), with XLA playing the role of
  the fusion buffer and stream scheduler.
* **eager (host)** — outside jit, ops run over the *process world* (one
  participant per host), matching how a reference user would allreduce a
  metric or broadcast an object outside the training graph. Data rides a
  cached one-op jit program over the leader chips.

Hierarchical allreduce (reference: NCCLHierarchicalAllreduce,
nccl_operations.cc:190-380) decomposes into intra-host ``psum_scatter`` (ICI)
→ cross-host ``psum`` (DCN) → intra-host ``all_gather`` (ICI), enabled by
``HOROVOD_HIERARCHICAL_ALLREDUCE`` or per-call.
"""

from __future__ import annotations

import contextlib
import enum
import os
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import basics
from ..common.basics import CROSS_AXIS, HVD_AXES, LOCAL_AXIS
from ..common.exceptions import (DuplicateTensorNameError,
                                 NotInitializedError)
from ..monitor import registry as _metrics
from ..plan import accounting as _accounting
from ..plan import compiler as _plan_compiler
from ..plan import planner as _planner
# Wire accounting + overlap instrumentation live with the plan compiler
# (horovod_tpu/plan/accounting.py, docs/wire-plan.md); re-exported here
# for the public `hvd.record_wire_stats` surface and compatibility.
from ..plan.accounting import (  # noqa: F401
    WireStats,
    _acct,
    _acct_enabled,
    _modeled_wire_ms,
    _wire_recorders,
    record_wire_stats,
)
from . import compression as _compression
from .compression import Compression


class ReduceOp(enum.IntEnum):
    """Reduction ops (reference: torch/mpi_ops.py:48-56 — Average, Sum,
    Adasum; plus Min/Max/Product which XLA gives us for free)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Reference-style aliases (hvd.Average / hvd.Sum / hvd.Adasum).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def _hvd_axes_in_trace() -> Tuple[str, ...]:
    """Horovod mesh axes bound in the current trace, in rank-major
    ``(pod, cross, local)`` order (the pod axis only exists on a 3-level
    ``mesh_shape=(cross, local, pods)`` mesh)."""
    return basics._trace_world_axes()


def _resolve_axes(axes) -> Tuple[str, ...]:
    if axes is None:
        return _hvd_axes_in_trace()
    if isinstance(axes, str):
        return (axes,)
    return tuple(axes)


# Canonical axis helpers live in common/basics.py (the plan compiler uses
# them too); aliased here for the historical `C._axis_size` call sites.
_axis_size = basics._axis_size
_unbound_axis_error = basics._unbound_axis_error


def _world_size(axes: Tuple[str, ...]):
    n = 1
    for a in axes:
        n *= _axis_size(a)
    return n


def _vma(x) -> frozenset:
    """Varying-manual-axes of ``x``: which mesh axes the value differs
    across. JAX >= 0.6 tracks this in the aval (``jax.typeof(x).vma``);
    jax 0.4.x's ``shard_map(check_rep=True)`` tracks the complement — the
    set of axes a value is provably *replicated* over — on its rewrite
    tracers, so there vma = bound axes - rep. An empty set means the value
    is provably identical on every device."""
    try:
        return frozenset(jax.typeof(x).vma)
    except Exception:
        pass
    try:  # jax < 0.6: check_rep replication tracking
        from jax.experimental.shard_map import get_replication

        while True:
            try:
                rep = get_replication(x)
                break
            except Exception:
                # Wrapper tracers (JVP/linearize) carry the rep on their
                # primal; get_replication itself unwraps batching.
                primal = getattr(x, "primal", None)
                if primal is None:
                    raise
                x = primal
        return frozenset(basics._bound_axes()) - frozenset(rep)
    except Exception:  # pragma: no cover - non-traced / API drift
        return frozenset()


def _pvary(x, axes) -> "jax.Array":
    """Cast ``x`` to be varying over ``axes`` (a free type-level
    broadcast). ``lax.pcast`` on jax >= 0.6; jax 0.4.x spells the same
    rep-set adjustment ``shard_map.pbroadcast``."""
    if not axes:
        return x
    try:
        return lax.pcast(x, tuple(axes), to="varying")
    except AttributeError:  # jax < 0.6
        from jax.experimental.shard_map import pbroadcast

        try:
            return pbroadcast(x, tuple(axes))
        except Exception:
            # pbroadcast rejects operands that are ALREADY device-varying
            # over the axes — which only happens when the rep set was not
            # recoverable from a wrapper tracer. Varying is what the
            # caller wanted; the value itself is untouched either way.
            return x


def pvary_missing(x, axes) -> "jax.Array":
    """Cast ``x`` to be varying over whichever of ``axes`` it is not
    already varying over (a free type-level broadcast; no-op when none
    are missing). The single home for this idiom — used by the gradient
    tape, the Pallas kernel wrappers, and the pipeline scan inits."""
    missing = tuple(a for a in axes if a not in _vma(x))
    return _pvary(x, missing) if missing else x


def _is_replicated(x, axes: Tuple[str, ...]) -> bool:
    return not (set(axes) & _vma(x))


def _scale(tensor, factor):
    """Pre/post scaling (reference: prescale/postscale in message.h:48-113 and
    the ScaleBuffer CUDA kernel, ops/cuda/cuda_kernels.cu:128). On TPU this is
    a fused elementwise multiply XLA folds into the surrounding program."""
    if factor is None or factor == 1.0:
        return tensor
    if jnp.issubdtype(tensor.dtype, jnp.integer):
        return (tensor * factor).astype(tensor.dtype)
    return tensor * jnp.asarray(factor, dtype=tensor.dtype)


# ---------------------------------------------------------------------------
# Wire lowering: every compiled collective below routes through the plan
# compiler (horovod_tpu/plan/, docs/wire-plan.md). The entry points here
# keep the public reference-parity API — op semantics, scaling,
# compression casts, replicated short-circuits, eager fallbacks — derive
# a WirePlan from the knobs (or take an explicit ``plan=``), and hand the
# wire composition to plan.compiler, which owns the leg lowering rules
# and the trace-time wire accounting (the bench A/B instrumentation).
# ---------------------------------------------------------------------------


def _quant_block_size(block: Optional[int]) -> int:
    if block:
        return int(block)
    if basics.is_initialized():
        return basics.config().quant_block
    return _compression.QUANT_BLOCK


def _resolve_plan(plan, default_fn):
    """An explicit validated ``plan=`` wins; otherwise derive the default
    from the knob set (``default_fn`` is a zero-arg planner call)."""
    if plan is not None:
        return plan.validate()
    return default_fn()


# ---------------------------------------------------------------------------
# Bucket-level reduce-scatter / all-gather — the ZeRO-1 wire pair.
#
# A fused gradient bucket planned with ``plan_buckets(shard_multiple=world)``
# (ops/fusion.py) reduce-scatters into ``world`` contiguous flat shards in
# RANK-MAJOR order (rank r = cross*local_size + local owns
# ``[r*seg, (r+1)*seg)``), the optimizer updates only its shard, and the
# updated values all-gather back. Rank-major ordering matches how
# ``P(HVD_AXES)`` splits a leading dim, so sharded optimizer state outside
# the trace is the flat bucket itself — no permutation.
#
# The hierarchical decomposition follows HiCCL's placement rule (the
# compiler enforces it as an IR validation rule): the ICI leg always
# rides the payload dtype; only the cross-host DCN leg is eligible for
# the blockwise-int8 wire. The reduce_scatter plan is the reduce half of
# the quantized-allreduce plan, the all_gather plan its gather half —
# ZeRO splits that collective around the optimizer update. Both lower
# through plan.compiler (lower_reduce_scatter / lower_all_gather).
# ---------------------------------------------------------------------------


def _rs_postscale(shard, op: ReduceOp, world: int, postscale_factor: float):
    post = postscale_factor
    if op == ReduceOp.AVERAGE:
        post = post / world
    return _scale(shard, post)


def reduce_scatter(
    tensor,
    residual=None,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    name: Optional[str] = None,
    axes=None,
    quantized: Optional[bool] = None,
    block: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
    _presummed: bool = False,
):
    """Reduce a flat buffer across all ranks and return this rank's
    contiguous ``1/world`` shard (rank-major: rank ``r`` owns elements
    ``[r*seg, (r+1)*seg)`` of the reduction).

    The ZeRO-1 gradient wire: where :func:`allreduce` moves
    ``2n(k-1)/k`` bytes per device, the reduce-scatter half moves
    ``n(k-1)/k`` and leaves each rank holding exactly the shard its
    optimizer partition updates. Only ``op=Average``/``Sum`` are defined
    (a scatter of min/max has no reference analogue and no user).

    ``quantized`` (default: the ``HOROVOD_QUANTIZED_ALLREDUCE`` knob)
    sends blockwise-int8 on the cross-host (DCN) leg of the hierarchical
    decomposition (the reduce half of the quantized-allreduce plan,
    plan/compiler.py); the ICI leg keeps
    the payload dtype. ``residual`` is the error-feedback accumulator for
    that leg, sized ``n / local_size`` (this rank's ICI-scattered shard —
    quantization error lives on what this rank *sends*, which is its
    post-ICI shard, not its final ``1/world`` segment); pass zeros
    initially and the call returns ``(shard, new_residual)``. Without
    ``residual`` the return is just ``shard``. On exact paths (quantized
    off, no cross axis, eager) a provided residual is consumed into the
    payload and returned as zeros.

    In-trace the input must divide evenly by the world size — pack it
    with ``plan_buckets(shard_multiple=world)`` (ops/fusion.py). Eagerly
    the reduction runs over the process world through the native core
    (allreduce + local slice; byte savings are a compiled-path feature).

    ``plan`` (a validated :class:`horovod_tpu.plan.WirePlan` for the
    ``reduce_scatter`` collective) overrides the knob-derived leg
    composition; the boolean knobs remain as aliases (docs/wire-plan.md).
    """
    tensor = jnp.asarray(tensor)
    if tensor.ndim != 1:
        raise ValueError(
            f"reduce_scatter operates on flat bucket buffers, got shape "
            f"{tensor.shape} — ravel and pad with plan_buckets/pack")
    if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
        raise ValueError(f"reduce_scatter supports Average/Sum, got {op}")
    axes_t = _resolve_axes(axes)
    if plan is not None and quantized is None:
        quantized = plan.is_quantized
    quantized = _resolve_quantized(quantized, Compression.none)
    quantized = quantized and jnp.issubdtype(tensor.dtype, jnp.floating)

    if not axes_t:
        return _eager_reduce_scatter(tensor, residual, op,
                                     prescale_factor, postscale_factor,
                                     name)

    world = _world_size(axes_t)
    n = int(tensor.shape[0])
    if n % world:
        raise ValueError(
            f"reduce_scatter buffer of {n} elements does not divide into "
            f"{world} shards — plan buckets with shard_multiple=world")
    seg = n // world

    if _is_replicated(tensor, axes_t):
        # No wire. presummed (gradient path): the value is already the
        # cross-rank sum — slice it (Average adds the /world). Otherwise
        # equal per-rank contributions: Sum scales by world, Average is
        # the identity — exactly what the wire would return.
        x = _scale(tensor, prescale_factor)
        rank = lax.axis_index(axes_t)
        shard = lax.dynamic_slice_in_dim(x, rank * seg, seg, 0)
        if _presummed:
            shard = _rs_postscale(shard, op, world, postscale_factor)
        else:
            if op == ReduceOp.SUM:
                shard = _scale(shard, float(world))
            shard = _scale(shard, postscale_factor)
        new_res = None if residual is None else jnp.zeros_like(residual)
        return shard if residual is None else (shard, new_res)

    flat = _scale(pvary_missing(tensor, axes_t), prescale_factor)
    eff_plan = _resolve_plan(
        plan, lambda: _planner.derive_reduce_scatter(
            levels=_planner.levels_of(axes_t), quantized=quantized,
            error_feedback=residual is not None, block=block,
            fused=fused))
    shard, new_res = _plan_compiler.lower_reduce_scatter(
        eff_plan, flat, residual=residual,
        block=_quant_block_size(block), axes=axes_t, world=world)
    shard = _rs_postscale(shard, op, world, postscale_factor)
    return shard if residual is None else (shard, new_res)


def all_gather(
    shard,
    residual=None,
    *,
    name: Optional[str] = None,
    axes=None,
    quantized: Optional[bool] = None,
    block: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
):
    """Concatenate per-rank flat shards in rank-major order into the full
    replicated buffer — the inverse of :func:`reduce_scatter` and the
    second half of the ZeRO-1 step (broadcast of the updated parameter
    shards).

    The result is replicated BY CONSTRUCTION (the repo's masked-psum
    idiom: each rank contributes its shard into a zeroed buffer at its
    own offset, disjoint support makes the psum exact), so it feeds
    ``out_specs=P()`` consumers directly — a plain ``lax.all_gather``
    output carries a device-varying mark that would poison them.

    ``quantized`` sends blockwise-int8 on the cross-host (DCN) leg (the
    gather half of the quantized-allreduce plan, plan/compiler.py) —
    with optional error feedback:
    ``residual`` is the accumulator over this rank's OWNED segment
    (shape ``[seg]``); when given the return becomes
    ``(full, new_residual)``. Every rank (owner included) consumes the
    same dequantized value, so the buffer stays exactly replicated.

    Distinct from :func:`allgather` (the reference-parity op): this is
    the flat bucket primitive — replication by construction, quantized
    DCN leg, eager fallback over the process world.
    """
    shard = jnp.asarray(shard)
    if shard.ndim != 1:
        raise ValueError(
            f"all_gather operates on flat shard buffers, got shape "
            f"{shard.shape}")
    axes_t = _resolve_axes(axes)
    if plan is not None and quantized is None:
        quantized = plan.is_quantized
    quantized = _resolve_quantized(quantized, Compression.none)
    quantized = quantized and jnp.issubdtype(shard.dtype, jnp.floating)

    if not axes_t:
        return _eager_shard_all_gather(shard, residual, name)

    world = _world_size(axes_t)

    if _is_replicated(shard, axes_t):
        # Equal shard everywhere: the gather is a local tile.
        full = jnp.tile(shard, world)
        new_res = None if residual is None else jnp.zeros_like(residual)
        return full if residual is None else (full, new_res)

    use_quant = (quantized and set(axes_t) == set(HVD_AXES)
                 and _axis_size(CROSS_AXIS) > 1)
    eff_plan = _resolve_plan(
        plan, lambda: _planner.derive_all_gather(
            levels=_planner.levels_of(axes_t) if use_quant else None,
            quantized=use_quant, error_feedback=residual is not None,
            block=block, fused=fused))
    if eff_plan.is_quantized and not use_quant:
        # An explicit quantized plan on a mesh with no DCN hop (or
        # custom axes) has no int8 leg to lower — fall back exact.
        eff_plan = _planner.flat_plan("all_gather")
    full, new_res = _plan_compiler.lower_all_gather(
        eff_plan, shard, residual=residual,
        block=_quant_block_size(block), axes=axes_t, world=world,
        rank=lax.axis_index(axes_t))
    return full if residual is None else (full, new_res)


def _eager_reduce_scatter(tensor, residual, op: ReduceOp,
                          prescale_factor: float, postscale_factor: float,
                          name: Optional[str]):
    """Host-path reduce_scatter over the process world: native allreduce
    then the local rank-major slice (exact wire; the byte savings and the
    quantized leg are compiled-path features)."""
    ctrl, world = _eager_ctx()
    x = _scale(tensor, prescale_factor)
    if residual is not None:
        x = x + residual.astype(x.dtype)
    if tensor.shape[0] % world:
        raise ValueError(
            f"reduce_scatter buffer of {tensor.shape[0]} elements does "
            f"not divide into {world} shards")
    seg = tensor.shape[0] // world
    if world == 1:
        shard = x
    else:
        red = _eager_allreduce(x, ReduceOp.SUM,
                               _eager_name(name, "reduce_scatter"))
        r = basics.rank()
        shard = red[r * seg:(r + 1) * seg]
    shard = _rs_postscale(shard, op, world, postscale_factor)
    if residual is None:
        return shard
    return shard, jnp.zeros_like(residual)


def _eager_shard_all_gather(shard, residual, name: Optional[str]):
    """Host-path all_gather of flat shards (native allgather concatenates
    in rank order, which IS the rank-major layout)."""
    ctrl, world = _eager_ctx()
    x = shard
    new_res = None
    if residual is not None:
        x = x + residual.astype(x.dtype)
        new_res = jnp.zeros_like(residual)
    if world == 1:
        full = x
    else:
        full = _eager_allgather(x, _eager_name(name, "shard_all_gather"))
    return full if residual is None else (full, new_res)


# ---------------------------------------------------------------------------
# Overlap stream entry points (docs/overlap.md).
#
# One fused bucket per call, issued in the reverse-layer stream schedule
# (ops/fusion.py stream_order) so buckets whose leaves finish early in
# backprop launch first and XLA's latency-hiding scheduler can run them
# under the still-executing backward. The wrappers change NO numerics —
# they bracket the exact same collective with trace-time bookkeeping:
# per-bucket OVERLAP:* timeline spans and WireStats.overlap_bytes (the
# bench's comm_hidden_fraction numerator). The bracket itself
# (plan/accounting.py overlap_stream) lives with the plan compiler, so
# any plan-compiled collective is instrumented identically.
# ---------------------------------------------------------------------------

_overlap_stream = _accounting.overlap_stream


def allreduce_stream(tensor, residual=None, *, bucket_id=0, **kwargs):
    """Per-bucket streaming allreduce: :func:`allreduce` (or, with
    ``residual``, :func:`quantized_allreduce`) bracketed with
    ``OVERLAP:ALLREDUCE`` bookkeeping. Bit-identical to the wrapped call —
    the overlap comes from WHERE the scheduler (ops/fusion.py) issues it,
    not from different math. Returns what the wrapped op returns
    (``out``, or ``(out, new_residual)`` when ``residual`` is given)."""
    with _overlap_stream("ALLREDUCE", bucket_id):
        if residual is not None:
            return quantized_allreduce(tensor, residual, **kwargs)
        return allreduce(tensor, **kwargs)


def reduce_scatter_stream(tensor, residual=None, *, bucket_id=0, **kwargs):
    """Per-bucket streaming reduce-scatter (the ZeRO gradient wire under
    the overlap schedule): :func:`reduce_scatter` bracketed with
    ``OVERLAP:REDUCE_SCATTER`` bookkeeping; same contract."""
    with _overlap_stream("REDUCE_SCATTER", bucket_id):
        return reduce_scatter(tensor, residual, **kwargs)


def all_gather_stream(shard, residual=None, *, bucket_id=0, **kwargs):
    """Per-bucket streaming all-gather (the ZeRO update broadcast under
    the overlap schedule): :func:`all_gather` bracketed with
    ``OVERLAP:ALL_GATHER`` bookkeeping; same contract."""
    with _overlap_stream("ALL_GATHER", bucket_id):
        return all_gather(shard, residual, **kwargs)


def _reduce_replicated(x, op: ReduceOp, axes: Tuple[str, ...],
                       presummed: bool):
    """Allreduce semantics for an input that is provably identical on every
    rank (VMA-invariant) — no collective needed.

    Two interpretations exist and the caller picks via ``presummed``:

    * ``presummed=False`` (direct ``hvd.allreduce`` calls): every rank holds
      the same value, so Sum → N·x, Average/Min/Max → x, Product → x^N —
      exactly what the wire collective would return on equal inputs.
    * ``presummed=True`` (gradient paths: DistributedOptimizer, tape): under
      ``jax.shard_map``, autodiff *auto-psums* gradients of replicated
      parameters, so an invariant gradient is already the cross-rank SUM of
      local gradients. Horovod-Average then only needs the ÷N; Horovod-Sum
      is the identity. Without this, wrapping a plain ``jax.grad`` step in
      DistributedOptimizer would double-count by a factor of N.
    """
    n = _world_size(axes)
    if presummed:
        if op in (ReduceOp.SUM, ReduceOp.ADASUM):
            return x
        if op == ReduceOp.AVERAGE:
            if jnp.issubdtype(x.dtype, jnp.integer):
                return x // n
            return x / jnp.asarray(n, dtype=x.dtype)
        raise ValueError(
            f"op {op} is not meaningful for pre-reduced gradients")
    if op == ReduceOp.SUM:
        if jnp.issubdtype(x.dtype, jnp.integer):
            return x * n
        return x * jnp.asarray(n, dtype=x.dtype)
    if op in (ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX, ReduceOp.ADASUM):
        return x  # equal contributions: avg/min/max/adasum are the identity
    if op == ReduceOp.PRODUCT:
        return x ** n
    raise ValueError(f"unsupported reduce op {op}")


def _reduce_in_jit(x, op: ReduceOp, axes: Tuple[str, ...],
                   hierarchical: bool, plan=None, fused=None):
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.ADASUM):
        eff_plan = _resolve_plan(
            plan, lambda: _planner.derive_allreduce(
                levels=_planner.levels_of(axes), quantized=False,
                hierarchical=bool(hierarchical), fused=fused))
        red = _plan_compiler.lower_psum(eff_plan, x, axes)
        if op == ReduceOp.AVERAGE:
            n = _world_size(axes)
            if jnp.issubdtype(x.dtype, jnp.integer):
                red = red // n
            else:
                red = red / jnp.asarray(n, dtype=red.dtype)
        return red
    if op == ReduceOp.MIN:
        return lax.pmin(x, axes)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axes)
    if op == ReduceOp.PRODUCT:
        # XLA has no pprod; exp/log is lossy, so gather + local reduce. The
        # closing pmax over identical values re-establishes replication for
        # the sharding checker at negligible extra cost.
        g = lax.all_gather(x, axes, axis=0, tiled=False)
        return lax.pmax(jnp.prod(g, axis=0), axes)
    raise ValueError(f"unsupported reduce op {op}")


def _resolve_quantized(quantized: Optional[bool], compression) -> bool:
    """Per-call arg > quantized compressor > HOROVOD_QUANTIZED_ALLREDUCE."""
    if quantized is not None:
        return bool(quantized)
    if getattr(compression, "is_quantized", False):
        return True
    return basics.is_initialized() and basics.config().quantized_allreduce


def allreduce(
    tensor,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=Compression.none,
    name: Optional[str] = None,
    axes=None,
    hierarchical: Optional[bool] = None,
    quantized: Optional[bool] = None,
    block: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
    _presummed: bool = False,
):
    """Allreduce ``tensor`` across all ranks.

    Reference: hvd.allreduce (tensorflow/__init__.py:53-153,
    torch/mpi_ops.py:163-228). ``op=Average`` divides the sum by world size;
    ``op=Adasum`` uses the adaptive-summation reduction (see ops/adasum.py).
    ``compression`` casts to a 16-bit wire format around the reduction
    (prefer ``Compression.bf16`` on TPU).

    ``quantized`` (default: ``HOROVOD_QUANTIZED_ALLREDUCE``, or implied by
    ``compression=Compression.int8``) sends blockwise-scaled int8 on the
    DCN hop of the hierarchical reduce-scatter/all-gather decomposition —
    the ``[ici.rs > dcn.rs[int8] > dcn.ag[int8] > ici.ag]`` wire plan
    (plan/compiler.py lower_quantized_allreduce); ICI legs keep the
    payload dtype. For error-feedback accumulation use
    :func:`quantized_allreduce`. With the knob off (the default) this
    path is bit-identical to the unquantized implementation. ``block``
    overrides the ``HOROVOD_QUANT_BLOCK`` scale-block size for this call
    (the autotuner threads its tuned value through here).

    ``plan`` (a validated :class:`horovod_tpu.plan.WirePlan` for the
    ``allreduce`` collective) overrides the knob-derived leg composition
    outright; the ``hierarchical``/``quantized`` booleans remain as
    aliases that derive the same plans (docs/wire-plan.md).

    If ``tensor`` is provably replicated across the requested mesh axes
    (VMA-invariant), no collective is emitted — see
    :func:`_reduce_replicated`. ``_presummed`` is set by the gradient paths
    (optimizer/tape) to mark that an invariant input is an autodiff-summed
    gradient rather than an equal per-rank contribution.
    """
    out, _ = _allreduce_impl(
        tensor, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, compression=compression,
        name=name, axes=axes, hierarchical=hierarchical,
        quantized=quantized, residual=None, block=block, fused=fused,
        plan=plan, _presummed=_presummed)
    return out


def quantized_allreduce(
    tensor,
    residual=None,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=Compression.none,
    name: Optional[str] = None,
    axes=None,
    block: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
):
    """Quantized allreduce with explicit error-feedback state.

    Returns ``(reduced, new_residual)``. ``residual`` is the error-feedback
    accumulator from the previous step (same shape as ``tensor``; pass
    zeros initially): it is added to the payload before the wire and the
    returned residual carries this rank's quantization error into the next
    step, which keeps SGD/Adam convergence at full-precision quality while
    the wire moves ~4x fewer DCN bytes. With ``residual=None`` the error is
    dropped (stateless quantization) and the second return value is None.

    The residual lives in the *transmitted* space — post ``prescale``, post
    ``compression`` cast, pre reduction — so keep those settings constant
    across steps. On exact paths (no cross axis, non-shardable size, eager
    world of one) the residual is still consumed and returns as zeros.
    """
    return _allreduce_impl(
        tensor, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, compression=compression,
        name=name, axes=axes, hierarchical=None, quantized=True,
        residual=residual, block=block, fused=fused, plan=plan,
        _presummed=False)


def _allreduce_impl(
    tensor,
    *,
    op: ReduceOp,
    prescale_factor: float,
    postscale_factor: float,
    compression,
    name: Optional[str],
    axes,
    hierarchical: Optional[bool],
    quantized: Optional[bool],
    residual,
    block: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
    _presummed: bool = False,
):
    tensor = jnp.asarray(tensor)
    axes_t = _resolve_axes(axes)
    if plan is not None:
        plan = plan.validate()
        if quantized is None:
            # Pod-only int8 legs (the quantized pod hop) lower through
            # the tree ladder, not the 2-level DCN-quantized path.
            quantized = plan.is_dcn_quantized
        if hierarchical is None:
            hierarchical = plan.is_tree and not plan.is_dcn_quantized
        if block is None:
            block = plan.quant_block
    quantized = _resolve_quantized(quantized, compression)
    # Quantization is defined for float sum/average reductions only; other
    # ops (min/max/product/adasum) always ride the exact wire.
    quantized = (quantized and jnp.issubdtype(tensor.dtype, jnp.floating)
                 and op in (ReduceOp.SUM, ReduceOp.AVERAGE))
    if op == ReduceOp.ADASUM and not (
            axes_t and _is_replicated(tensor, axes_t)):
        from . import adasum as _adasum

        return _adasum.adasum_allreduce(
            tensor, axes=axes, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            compression=compression), residual

    tensor = _scale(tensor, prescale_factor)
    # A quantized compressor is not a wire cast: the int8 layout happens
    # inside the collective (real path) or as a local fake-quant round trip
    # (fallback paths) — never through compress() on the real path, where
    # it would double-quantize.
    real_quant_cast = getattr(compression, "is_quantized", False)
    compressed, ctx = ((tensor, None) if real_quant_cast
                       else compression.compress(tensor))
    new_residual = residual
    if axes_t:
        if _is_replicated(compressed, axes_t):
            # No wire, no quantization error; the residual passes through
            # untouched (it is zero on this path by construction).
            red = _reduce_replicated(compressed, op, axes_t, _presummed)
        else:
            # Partially replicated (varying on a strict subset of the
            # requested axes, e.g. a TP-invariant loss allreduced over the
            # full DPxTP mesh): pvary the invariant axes so the collective
            # type-checks — each replicated copy then contributes, exactly
            # the wire semantics of equal inputs on those ranks.
            missing = tuple(sorted(set(axes_t) - _vma(compressed)))
            if missing and _vma(compressed):
                compressed = _pvary(compressed, missing)
            if (quantized and set(axes_t) == set(HVD_AXES)
                    and op in (ReduceOp.SUM, ReduceOp.AVERAGE)):
                eff_plan = _resolve_plan(
                    plan if (plan is not None and plan.is_dcn_quantized)
                    else None,
                    lambda: _planner.quantized_allreduce_plan(
                        block=block,
                        error_feedback=residual is not None,
                        fused=_planner._resolve_fused(fused)))
                red, new_residual = \
                    _plan_compiler.lower_quantized_allreduce(
                        eff_plan, compressed, residual=residual,
                        block=_quant_block_size(block))
                if op == ReduceOp.AVERAGE:
                    n = _world_size(axes_t)
                    red = red / jnp.asarray(n, dtype=red.dtype)
            else:
                if quantized and real_quant_cast:
                    # Quantization requested but the reduction doesn't
                    # decompose over (cross, local): fake-quant the
                    # contribution so numerics still match the quantized
                    # semantics; the wire stays full-width.
                    if residual is not None:
                        compressed = compressed + residual.astype(
                            compressed.dtype)
                    wire = _compression.fake_quantize_int8(
                        compressed, _quant_block_size(block))
                    if residual is not None:
                        new_residual = (compressed - wire).astype(
                            residual.dtype)
                    compressed = wire
                elif residual is not None:
                    # Exact wire: consume the residual, nothing left over.
                    compressed = compressed + residual.astype(
                        compressed.dtype)
                    new_residual = jnp.zeros_like(residual)
                if hierarchical is None:
                    hierarchical = (
                        basics.is_initialized()
                        and basics.config().hierarchical_allreduce
                    )
                exact_plan = (plan if plan is not None
                              and plan.collective == "allreduce"
                              and not plan.is_dcn_quantized else None)
                red = _reduce_in_jit(compressed, op, axes_t,
                                     bool(hierarchical), plan=exact_plan,
                                     fused=fused)
    else:
        # hierarchical=False matches what the eager data plane does (flat
        # rings), so only an explicit True is an unsatisfiable request —
        # autotuner TunedParams overrides legitimately pass False here.
        if hierarchical:
            raise ValueError(
                "allreduce(hierarchical=True) is only supported in-jit; "
                "set HOROVOD_HIERARCHICAL_ALLREDUCE for the eager path")
        if quantized:
            # Eager path: the native core reduces full-width dtypes, so the
            # quantization is applied as a local fake-quant of this rank's
            # contribution — identical numerics to the compiled hop-2
            # contribution, full-width bytes (the byte savings are a
            # compiled-path feature).
            if residual is not None:
                compressed = compressed + residual.astype(compressed.dtype)
            wire = _compression.fake_quantize_int8(
                compressed, _quant_block_size(block))
            if residual is not None:
                new_residual = (compressed - wire).astype(residual.dtype)
            compressed = wire
        red = _eager_allreduce(compressed, op, name)
    red = compression.decompress(red, ctx)
    return _scale(red, postscale_factor), new_residual


def grouped_allreduce(tensors: Sequence, **kwargs):
    """Allreduce a list of tensors as one logical group (reference:
    grouped allreduce added for torch in mpi_ops.py; the fusion analogue).

    Under jit, XLA fuses the per-tensor psums; for stronger guarantees use
    :mod:`horovod_tpu.ops.fusion` which packs one flat buffer per dtype.
    On the eager path the group is packed host-side into one flat buffer
    per wire dtype and enqueued as ONE native collective per buffer — one
    controller negotiation per group instead of N (reference grouped-op
    semantics; like the reference's fusion buffer, Adasum then treats the
    packed buffer as a single logical vector)."""
    tensors = [jnp.asarray(t) for t in tensors]
    axes_t = _resolve_axes(kwargs.get("axes"))
    if axes_t or not tensors:
        return [allreduce(t, **kwargs) for t in tensors]
    return _eager_grouped_allreduce(tensors, **kwargs)


def _eager_grouped_allreduce(tensors, *, name: Optional[str] = None,
                             op: ReduceOp = ReduceOp.AVERAGE,
                             prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             compression=None, axes=None,
                             hierarchical: Optional[bool] = None):
    if hierarchical:
        raise ValueError(
            "allreduce(hierarchical=True) is only supported in-jit; set "
            "HOROVOD_HIERARCHICAL_ALLREDUCE for the eager path")
    compression = compression or Compression.none
    ctrl, world = _eager_ctx()

    wires, ctxs = [], []
    for t in tensors:
        w, c = compression.compress(_scale(t, prescale_factor))
        wires.append(w)
        ctxs.append(c)
    if world == 1:
        return [_scale(compression.decompress(w, c), postscale_factor)
                for w, c in zip(wires, ctxs)]

    opmap = {ReduceOp.SUM: ctrl.SUM, ReduceOp.AVERAGE: ctrl.SUM,
             ReduceOp.MIN: ctrl.MIN, ReduceOp.MAX: ctrl.MAX,
             ReduceOp.PRODUCT: ctrl.PRODUCT, ReduceOp.ADASUM: ctrl.ADASUM}
    post = 1.0 / world if op == ReduceOp.AVERAGE else 1.0
    gname = _eager_name(name, "grouped_allreduce")

    # One flat buffer (and one negotiation) per wire dtype, in first-seen
    # order; results unpack back to the original shapes/positions.
    by_dtype: dict = {}
    for i, w in enumerate(wires):
        by_dtype.setdefault(jnp.dtype(w.dtype), []).append(i)
    out: list = [None] * len(tensors)
    handles = []
    for dt, idxs in by_dtype.items():
        flat = np.concatenate(
            [np.asarray(_to_numpy(wires[i])).ravel() for i in idxs])
        handles.append((dt, idxs, ctrl.allreduce_async(
            flat, f"{gname}.{dt.name}", op=opmap[op], postscale=post)))
    for dt, idxs, h in handles:
        buf = h.wait()
        offset = 0
        for i in idxs:
            n = wires[i].size
            piece = jnp.asarray(
                buf[offset:offset + n]).reshape(wires[i].shape)
            offset += n
            out[i] = _scale(compression.decompress(piece, ctxs[i]),
                            postscale_factor)
    return out


def allgather(tensor, *, name: Optional[str] = None, axes=None,
              hierarchical: Optional[bool] = None):
    """Gather tensors from all ranks, concatenated along dim 0.

    Reference: hvd.allgather (torch/mpi_ops.py:230-291). The reference
    supports ragged first dims via the coordinator's size exchange; under XLA
    shapes are static, so in-jit all shards must share a shape — ragged
    gathers belong on the eager path (allgather_object in
    parallel/functions.py covers the reference's ragged use cases).

    ``hierarchical`` (default: the ``HOROVOD_HIERARCHICAL_ALLGATHER`` knob,
    reference operations.cc:463-472 / MPIHierarchicalAllgather,
    mpi_operations.cc:180-280) decomposes the world gather into an intra-host
    gather over ICI followed by a cross-host gather of per-host superblocks
    over DCN. Host-major rank packing makes the two orderings identical, so
    numerics match the flat gather exactly. The eager path honors the same
    knob inside the native core (cc/src/collectives.cc
    HierarchicalAllgatherV).
    """
    tensor = jnp.asarray(tensor)
    axes_t = _resolve_axes(axes)
    if axes_t:
        if _is_replicated(tensor, axes_t):
            # Equal contribution from every rank: the gather is a local tile.
            reps = (_world_size(axes_t),) + (1,) * (tensor.ndim - 1)
            return jnp.tile(tensor, reps)
        if hierarchical is None:
            hierarchical = (basics.is_initialized()
                            and basics.config().hierarchical_allgather)
        # Exact tuple match: the two-stage decomposition reproduces the
        # cross-major concatenation of axes=(cross, local); a reversed axes
        # tuple means local-major order and must stay on the flat path.
        if hierarchical and axes_t == HVD_AXES:
            # Local (ICI) gather first, then cross (DCN) gather of the
            # per-host superblocks; rank order = (cross, local) lex order =
            # the flat gather's order.
            local = lax.all_gather(tensor, LOCAL_AXIS, axis=0, tiled=True)
            return lax.all_gather(local, CROSS_AXIS, axis=0, tiled=True)
        return lax.all_gather(tensor, axes_t, axis=0, tiled=True)
    if hierarchical is not None:
        # The eager data plane takes its hierarchical decision from the
        # process-wide HOROVOD_HIERARCHICAL_ALLGATHER knob inside the
        # native core; a per-call override cannot be honored there.
        raise ValueError(
            "allgather(hierarchical=...) is only supported in-jit; set "
            "HOROVOD_HIERARCHICAL_ALLGATHER for the eager path")
    return _eager_allgather(tensor, name)


def broadcast(tensor, root_rank: int = 0, *, name: Optional[str] = None,
              axes=None):
    """Broadcast ``tensor`` from ``root_rank`` to all ranks.

    Reference: hvd.broadcast (torch/mpi_ops.py:293-344). Lowers to a masked
    ``psum`` on every platform (one collective, no size× gather blow-up):
    every rank contributes zeros except the root. See the in-body comment
    for why the per-platform CollectiveBroadcast lowering was dropped.
    """
    tensor = jnp.asarray(tensor)
    axes_t = _resolve_axes(axes)
    if not axes_t:
        return _eager_broadcast(tensor, root_rank, name)
    if _is_replicated(tensor, axes_t):
        return tensor  # already equal everywhere: nothing to move
    wire = tensor
    bool_in = wire.dtype == jnp.bool_
    if bool_in:
        wire = wire.astype(jnp.uint8)

    # Masked psum on every platform: each rank contributes zeros except the
    # root, one collective, no size-x gather blow-up — and the result is
    # replicated BY CONSTRUCTION in JAX's VMA model. The per-platform
    # CollectiveBroadcast lowering (lax.pbroadcast) was dropped: its result
    # stays statically device-varying under jax 0.9, so selecting between
    # the two via lax.platform_dependent builds a switch with VMA-divergent
    # branches, which fails abstract evaluation under jit for any
    # device-varying operand (XLA on TPU still lowers the masked AllReduce
    # onto ICI).
    # Select, not multiply: NaN/Inf in a non-root payload (e.g. an elastic
    # rejoin whose own params diverged) would survive `wire * 0` and poison
    # the sum on every rank.
    is_root = lax.axis_index(axes_t) == root_rank
    out = lax.psum(jnp.where(is_root, wire, jnp.zeros_like(wire)), axes_t)
    if bool_in:
        out = out.astype(jnp.bool_)
    return out


def alltoall(tensor, splits=None, *, name: Optional[str] = None, axes=None):
    """Scatter slices of ``tensor`` along dim 0 to every rank and gather the
    received slices, concatenated along dim 0.

    Reference: hvd.alltoall (operations.cc:1031-1092,
    collective_operations.h:192-257). Returns ``(output, received_splits)``
    for parity with the reference's uneven-split API. In-jit, XLA requires
    static shapes, so only the even-split case (``splits=None`` with dim 0
    divisible by world size, or all-equal splits) is compiled; uneven splits
    are an eager/controller feature.
    """
    tensor = jnp.asarray(tensor)
    axes_t = _resolve_axes(axes)
    if not axes_t:
        out, recv = _eager_alltoall(tensor, splits, name)
        if recv is None:  # world of one
            n = tensor.shape[0] if tensor.ndim else 0
            recv = jnp.asarray([n], dtype=jnp.int32)
        return out, recv
    n = _world_size(axes_t)
    if splits is not None:
        s = np.asarray(splits)
        if not (s.ndim == 1 and len(s) == n and np.all(s == s[0])):
            raise NotImplementedError(
                "uneven alltoall splits require static shapes under XLA: "
                "use hvd.alltoall_ragged(tensor, splits, capacity=...) — "
                "the compiled static-capacity protocol for the reference's "
                "uneven path (operations.cc:1031-1092) — or equal splits "
                "here")
    if tensor.shape[0] % n != 0:
        raise ValueError(
            f"alltoall dim 0 ({tensor.shape[0]}) must be divisible by the "
            f"world size ({n})")
    if _is_replicated(tensor, axes_t):
        # Equal input on every rank: rank r receives its own block from each
        # sender — a local slice + tile, no wire traffic.
        blk = tensor.shape[0] // n
        mine = lax.dynamic_slice_in_dim(
            tensor, lax.axis_index(axes_t) * blk, blk, 0)
        out = jnp.tile(mine, (n,) + (1,) * (tensor.ndim - 1))
    else:
        out = lax.all_to_all(tensor, axes_t, split_axis=0, concat_axis=0,
                             tiled=True)
    recv = jnp.full((n,), tensor.shape[0] // n, dtype=jnp.int32)
    return out, recv


def alltoall_ragged(tensor, splits, *, capacity: int,
                    name: Optional[str] = None, axes=None,
                    recv_splits=None):
    """Uneven alltoall that compiles under ``jit`` via a static-capacity
    padded exchange.

    The reference negotiates per-pair receive counts at runtime and
    allocates an exactly-sized output (operations.cc:1031-1092;
    ``AlltoallGetRecvSplits``, controller.h:145).  XLA requires static
    shapes, so the TPU-native protocol trades exactness for a static
    per-pair bound:

    1. each pair block (the rows destined for rank ``i``) is padded to
       ``capacity`` rows into an ``[n, capacity, ...]`` send buffer
       (padding rows are zeroed so no garbage rides the wire);
    2. the per-pair counts ride a tiny int32 ``lax.all_to_all`` — the
       compiled analogue of the controller's recv-splits negotiation;
    3. one tiled ``lax.all_to_all`` moves the padded payload over ICI;
    4. received blocks are compacted to the front of the output with a
       drop-mode scatter on the padding rows.

    Args:
      tensor: ``[T, ...]`` laid out destination-major — rows
        ``[sum(splits[:i]), sum(splits[:i+1]))`` go to rank ``i``.
      splits: int32 ``[n]``; may be a *traced* array (dynamic values,
        static shape).  Entries are clamped to ``capacity``: rows beyond
        it are dropped at the sender and the clamped count is what the
        receiver sees in ``recv_splits`` (the Switch-MoE overflow
        contract; pick ``capacity >= max(splits)`` for losslessness).
      capacity: static per-pair row bound (python int).
      recv_splits: optional precomputed int32 ``[n]`` of incoming
        per-pair counts (e.g. from a prior ``alltoall_ragged`` with the
        same splits this step) — skips the counts negotiation
        collective.  Values are clamped to ``capacity``; they must match
        what peers actually send or rows will be mis-compacted.

    Returns ``(out, recv_splits)`` where ``out`` is
    ``[n * capacity, ...]`` with the received blocks compacted to the
    front (rows past ``sum(recv_splits)`` are zeros) and ``recv_splits``
    is int32 ``[n]`` — ``recv_splits[i]`` rows arrived from rank ``i``.

    Outside shard_map the same contract runs over the process world
    through the native controller's uneven path (clamp + compact on the
    host, then pad the exact-sized result up to the capacity layout).
    """
    tensor = jnp.asarray(tensor)
    if tensor.ndim == 0:
        raise ValueError("alltoall_ragged requires a tensor with ndim >= 1")
    capacity = int(capacity)
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    axes_t = _resolve_axes(axes)

    if not axes_t:
        return _eager_alltoall_ragged(tensor, splits, capacity, name)

    n = _world_size(axes_t)
    if not isinstance(splits, jax.core.Tracer):
        if np.any(np.asarray(splits) < 0):
            raise ValueError(f"splits must be non-negative, got {splits}")
    splits = jnp.maximum(jnp.asarray(splits, dtype=jnp.int32), 0)
    if splits.shape != (n,):
        raise ValueError(
            f"splits must have shape ({n},) for a world of {n}, got "
            f"{splits.shape}")
    sp = jnp.minimum(splits, capacity)

    T = tensor.shape[0]
    rest = tensor.shape[1:]
    j = jnp.arange(capacity, dtype=jnp.int32)
    valid_send = j[None, :] < sp[:, None]                  # [n, capacity]
    if T == 0:
        send = jnp.zeros((n, capacity) + rest, tensor.dtype)
    else:
        # Block offsets follow the CALLER's layout (the original splits,
        # overflow rows included); only the first sp[i] rows of each
        # block are picked up.
        offs = jnp.cumsum(splits) - splits
        idx = jnp.clip(offs[:, None] + j[None, :], 0, T - 1)
        send = jnp.take(tensor, idx.reshape(-1), axis=0).reshape(
            (n, capacity) + rest)
        mask = valid_send.reshape((n, capacity) + (1,) * len(rest))
        send = jnp.where(mask, send, jnp.zeros((), tensor.dtype))

    if n > 1:
        # pvary replicated operands: all_to_all needs device-varying
        # inputs under jax 0.9's VMA model.
        if recv_splits is None:
            recv_splits = lax.all_to_all(
                pvary_missing(sp, axes_t), axes_t, split_axis=0,
                concat_axis=0, tiled=True)
        else:
            recv_splits = jnp.clip(
                jnp.asarray(recv_splits, jnp.int32), 0, capacity)
        recv = lax.all_to_all(
            pvary_missing(send, axes_t), axes_t, split_axis=0,
            concat_axis=0, tiled=True)
    else:
        recv_splits = sp if recv_splits is None else jnp.clip(
            jnp.asarray(recv_splits, jnp.int32), 0, capacity)
        recv = send

    # Compact: scatter valid rows to the front, padding rows off the end
    # (mode="drop" discards out-of-bounds destinations).
    roffs = jnp.cumsum(recv_splits) - recv_splits
    valid_recv = j[None, :] < recv_splits[:, None]
    dest = jnp.where(valid_recv, roffs[:, None] + j[None, :], n * capacity)
    flat = recv.reshape((n * capacity,) + rest)
    out = jnp.zeros_like(flat).at[dest.reshape(-1)].set(flat, mode="drop")
    return out, recv_splits


def _eager_alltoall_ragged(tensor, splits, capacity: int,
                           name: Optional[str] = None):
    """Host-path ``alltoall_ragged``: same padded-output contract, data
    moves through the native controller's uneven alltoall."""
    world = _eager_world()
    splits_np = np.asarray(splits, dtype=np.int64)
    if splits_np.shape != (world,):
        raise ValueError(
            f"splits must have shape ({world},) for a process world of "
            f"{world}, got {splits_np.shape}")
    if np.any(splits_np < 0):
        raise ValueError(f"splits must be non-negative, got {splits_np}")
    sp = np.minimum(splits_np, capacity)
    offs = np.cumsum(splits_np) - splits_np
    keep = np.concatenate(
        [offs[i] + np.arange(sp[i]) for i in range(world)]
    ).astype(np.int64) if world else np.zeros((0,), np.int64)
    compacted = jnp.take(tensor, keep, axis=0)
    out, recv = _eager_alltoall(compacted, sp.astype(np.int32), name)
    if recv is None:  # world of one: everything loops back locally
        recv = jnp.asarray(sp, dtype=jnp.int32)
    total = world * capacity
    pad = total - out.shape[0]
    if pad:
        out = jnp.concatenate(
            [out, jnp.zeros((pad,) + out.shape[1:], out.dtype)], axis=0)
    return out, jnp.asarray(recv, dtype=jnp.int32)


def join() -> int:
    """Signal that this process has exhausted its data (reference: JoinOp,
    collective_operations.cc:256-264; torch/mpi_ops.py:646).

    In the reference, joined ranks contribute zeros to subsequent collectives
    until all ranks join; the call returns the rank of the last rank to join.
    Single-controller SPMD has no per-rank data exhaustion inside the
    compiled step — handle ragged data by padding/masking the global batch.
    Eagerly the native core implements the full joined-rank protocol
    (identity contributions until all ranks join).
    """
    s = basics._require_init()
    s.joined = True
    ctrl, world = _eager_ctx()
    if world == 1:
        return basics.rank()
    h = ctrl.join_async()
    h.wait()
    return h.join_result()


def barrier() -> None:
    """Host-side barrier over processes (reference: controller Barrier,
    controller.h:145)."""
    ctrl, world = _eager_ctx()
    if ctrl is not None and world > 1:
        ctrl.barrier()


# ---------------------------------------------------------------------------
# Eager (host) path — process-world collectives through the native core.
#
# One participant per worker process (the reference's process model). Data
# crosses process boundaries through the C++ controller + TCP data plane
# (cc/): enqueue → rank-0 negotiation → fused ring collective → in-place
# result. Under a single process they reduce over a world of one, which
# still applies op semantics exactly (average of one tensor is the tensor).
# ---------------------------------------------------------------------------

_eager_name_lock = threading.Lock()
_eager_name_counter = [0]


def _eager_name(name: Optional[str], kind: str) -> str:
    """Stable auto-name: processes stay aligned because collectives are
    issued in identical program order on every rank (the same contract the
    reference's auto-generated op names rely on)."""
    if name is not None:
        return name
    with _eager_name_lock:
        n = _eager_name_counter[0]
        _eager_name_counter[0] += 1
    return f"eager.{kind}.{n}"


def _eager_world() -> int:
    s = basics._require_init()
    return s.controller.size() if s.controller is not None else s.process_count


def _controller():
    return basics._require_init().controller


def _eager_ctx():
    """(controller, world) for an eager collective. A multi-process job
    whose controller is missing (HOROVOD_CONTROLLER=none, or HOROVOD_SIZE
    unset under jax.distributed) must fail loudly: silently skipping the
    collective would let ranks diverge unreduced."""
    # Chaos gate for the eager path: 'crash' is a worker dying
    # mid-collective (peers see HorovodInternalError and the elastic
    # restore path engages); 'stall' is a straggler rank.
    from ..chaos import injector as _chaos

    _chaos.inject("collective.eager")
    s = basics._require_init()
    ctrl = s.controller
    world = ctrl.size() if ctrl is not None else s.process_count
    if ctrl is None and world > 1:
        raise RuntimeError(
            "eager collective in a multi-process job but the native "
            "controller is disabled (HOROVOD_CONTROLLER=none or launcher "
            "env contract missing) — cannot communicate between processes")
    return ctrl, world


def _reset_eager_state() -> None:
    """Called by basics.shutdown(): auto-generated collective names restart
    from 0 so ranks stay aligned across an elastic shutdown/init cycle."""
    with _eager_name_lock:
        _eager_name_counter[0] = 0
    with _handles._lock:
        _handles._results.clear()
        _handles._names.clear()
        _handles._next = 0


def _to_numpy(tensor) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(tensor))


@contextlib.contextmanager
def _eager_instrumented(kind: str, name: str):
    """Observability bracket for one eager (host-path) collective: the
    StallInspector tracks it in flight (so a straggler rank — or a chaos
    ``stall`` injected in ``_eager_ctx`` — surfaces as a rank-attributed
    ``STALL:*`` warning, docs/observability.md), and the wall time of a
    completed op feeds the ``comm.eager.latency_ms`` histogram."""
    from ..monitor import flight as _flight
    from ..monitor import stall as _stall
    from ..monitor import straggler as _straggler

    if _metrics.metrics_enabled():
        _metrics.counter("comm.eager.calls", kind=kind).inc()
    t0 = time.perf_counter()
    with _stall.track(name, kind=kind):
        yield
    ms = (time.perf_counter() - t0) * 1e3
    if _metrics.metrics_enabled():
        _metrics.histogram("comm.eager.latency_ms", kind=kind).observe(ms)
        # Straggler attribution (monitor/straggler.py): eager wall time
        # charges the wire.dcn phase — the process-world data plane is
        # host-to-host TCP, DCN-class wire. A rank whose eager
        # collectives drag (chaos delay, a sick NIC) shows up as a
        # (rank, wire.dcn) outlier after cross-rank aggregation.
        _straggler.record_phase("wire.dcn", ms)
    # The eager path has no timeline event of its own; the flight ring
    # records each completed call so a dump shows the collective trail.
    _flight.instant("FLIGHT:COLLECTIVE", tid="flight",
                    args={"name": name, "kind": kind,
                          "ms": round(ms, 3)})


def _eager_allreduce(tensor, op: ReduceOp, name: Optional[str] = None):
    name = _eager_name(name, "allreduce")
    with _eager_instrumented("allreduce", name):
        ctrl, world = _eager_ctx()
        if world == 1:
            return tensor  # sum/avg/min/max/product over a world of one
        arr = _to_numpy(tensor)
        opmap = {
            ReduceOp.SUM: ctrl.SUM,
            ReduceOp.AVERAGE: ctrl.SUM,
            ReduceOp.MIN: ctrl.MIN,
            ReduceOp.MAX: ctrl.MAX,
            ReduceOp.PRODUCT: ctrl.PRODUCT,
            ReduceOp.ADASUM: ctrl.ADASUM,
        }
        postscale = 1.0 / world if op == ReduceOp.AVERAGE else 1.0
        out = ctrl.allreduce_async(arr, name,
                                   op=opmap[op], postscale=postscale).wait()
        return jnp.asarray(out)


def _eager_allgather(tensor, name: Optional[str] = None):
    name = _eager_name(name, "allgather")
    with _eager_instrumented("allgather", name):
        ctrl, world = _eager_ctx()
        if world == 1:
            return tensor
        out = ctrl.allgather_async(_to_numpy(tensor), name).wait()
        return jnp.asarray(out)


def _eager_broadcast(tensor, root_rank: int, name: Optional[str] = None):
    name = _eager_name(name, "broadcast")
    with _eager_instrumented("broadcast", name):
        ctrl, world = _eager_ctx()
        if world == 1:
            return tensor
        out = ctrl.broadcast_async(_to_numpy(tensor), name,
                                   root=root_rank).wait()
        return jnp.asarray(out)


def _eager_alltoall(tensor, splits, name: Optional[str] = None):
    name = _eager_name(name, "alltoall")
    with _eager_instrumented("alltoall", name):
        ctrl, world = _eager_ctx()
        if world == 1:
            return tensor, None
        sp = None if splits is None else [int(x) for x in np.asarray(splits)]
        h = ctrl.alltoall_async(_to_numpy(tensor), name, splits=sp)
        out = h.wait()
        return jnp.asarray(out), jnp.asarray(h.recv_splits(),
                                             dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Handle-based async API (reference: torch/mpi_ops.py:66-161 — allreduce_async
# returns an int handle; synchronize(handle) blocks; poll(handle) checks).
#
# JAX arrays are asynchronous futures by construction: dispatch returns
# immediately and block_until_ready() is the synchronize. The HandleManager
# preserves the reference contract (including duplicate-name rejection,
# common.h:163) on top of that.
# ---------------------------------------------------------------------------


class _HandleManager:
    """Reference: torch/handle_manager.{h,cc} + the name table in
    TensorQueue (tensor_queue.h:28)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._results = {}
        self._names = set()
        self._next = 0

    def allocate(self, value, name: Optional[str]):
        with self._lock:
            if name is not None:
                if name in self._names:
                    raise DuplicateTensorNameError(
                        f"Tensor name {name!r} already in an in-flight "
                        "collective (reference: DUPLICATE_NAME_ERROR, "
                        "common.h:163)")
                self._names.add(name)
            h = self._next
            self._next += 1
            self._results[h] = (value, name)
            return h

    def poll(self, handle: int) -> bool:
        with self._lock:
            if handle not in self._results:
                # Already synchronized/cleared: completed (the reference's
                # HandleManager reports finished handles as done).
                return True
            value, _ = self._results[handle]
        try:
            return bool(value.is_ready())
        except AttributeError:
            return True

    def wait_and_clear(self, handle: int):
        with self._lock:
            value, name = self._results.pop(handle)
            if name is not None:
                self._names.discard(name)
        return jax.block_until_ready(value)


_handles = _HandleManager()


def allreduce_async(tensor, *, name: Optional[str] = None, **kwargs) -> int:
    """Dispatch an allreduce, returning an integer handle
    (reference: torch/mpi_ops.py:119-127)."""
    return _handles.allocate(allreduce(tensor, name=name, **kwargs), name)


def allgather_async(tensor, *, name: Optional[str] = None, **kwargs) -> int:
    return _handles.allocate(allgather(tensor, name=name, **kwargs), name)


def broadcast_async(tensor, root_rank: int = 0, *,
                    name: Optional[str] = None, **kwargs) -> int:
    return _handles.allocate(
        broadcast(tensor, root_rank, name=name, **kwargs), name)


def alltoall_async(tensor, splits=None, *, name: Optional[str] = None,
                   **kwargs) -> int:
    return _handles.allocate(alltoall(tensor, splits, name=name, **kwargs),
                             name)


def poll(handle: int) -> bool:
    """True when the collective behind ``handle`` has completed
    (reference: torch/mpi_ops.py:88-99)."""
    return _handles.poll(handle)


def synchronize(handle: int):
    """Block until the collective completes and return its result
    (reference: torch/mpi_ops.py:101-127)."""
    return _handles.wait_and_clear(handle)
