"""Fused linear cross-entropy: LM head matmul + softmax-xent, no logits.

The reference has no LM-head machinery at all (CNN-era framework); on TPU
the final ``hidden @ embeddingᵀ → softmax_cross_entropy`` chain is the
second HBM hog in an LM step after attention: at GPT-124M bench shapes
(N = 16·1024 tokens, V = 32000) the fp32 logits tensor is 2 GB — written
by the matmul, re-read by the softmax, regenerated and re-read in the
backward.

:func:`linear_cross_entropy` computes per-token
``loss_n = logsumexp_v(x_n · w_v) - x_n · w_{y_n}`` with Pallas kernels
that stream vocab blocks through VMEM (online logsumexp, same recipe as
flash attention's streaming softmax) and a custom VJP that recomputes the
blockwise softmax from the saved ``lse`` residual:

    dx_n = g_n · Σ_v (softmax_nv - 1[v = y_n]) · w_v
    dw_v = Σ_n g_n · (softmax_nv - 1[v = y_n]) · x_n

so HBM traffic is O(N·C + V·C) instead of O(N·V). Labels ride as an
(N, 8) int32 operand (broadcast sublane dim, Mosaic block-mapping
minimum); the one-hot is built in-kernel by comparing a vocab-position
iota against the label column.

Off-TPU the kernels run in Pallas interpreter mode (CPU test suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 naming
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from .flash_attention import (
    _harmonize_vma,
    _interpret,
    _out_struct,
    _pick_block,
)

_NEG_INF = -1e30

# The dominant HBM cost is streaming the [V, C] weight matrix once per
# row block (it exceeds VMEM), so block_n is the lever: W traffic per
# kernel = (N / block_n) · V·C bytes. 1024 rows × a 640–1024-column vocab
# block keeps x/acc/s under ~7 MB of VMEM while cutting W re-reads 4×
# vs 256-row blocks (measured: the difference between losing and winning
# against the dense einsum+optax head at V = 32000).
from .flash_attention import _block_knob

_DEF_BLOCK_N = _block_knob("HOROVOD_XENT_BLOCK_N", 1024)  # token rows/cell
_DEF_BLOCK_V = _block_knob("HOROVOD_XENT_BLOCK_V", 1024)  # vocab cols/cell


def _onehot_mask(labels_col, j, bn, bv):
    """[bn, bv] bool: vocab position == label (labels_col is [bn, 1])."""
    vpos = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    return vpos == labels_col


def _fwd_kernel(x_ref, w_ref, lab_ref, loss_ref, lse_ref,
                m_scr, l_scr, t_scr, *, bn, bv, nv):
    i = pl.program_id(0)   # token-row block
    j = pl.program_id(1)   # vocab block (innermost: scratch carries)
    del i

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    # Body under a traced always-true pl.when: vma-mixed arithmetic
    # (unvarying scratch vs sharded operands) is only harmonized inside
    # cond branches by the HLO interpreter (see flash_attention._run_pred).
    @pl.when(j >= 0)
    def _body():
        s = jax.lax.dot_general(
            x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bn, bv]

        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

        # Accumulate the label logit: exactly one vocab block contains it.
        hit = _onehot_mask(lab_ref[:, 0:1], j, bn, bv)
        t_scr[:] += jnp.broadcast_to(
            jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True),
            t_scr.shape)

    @pl.when(j == nv - 1)
    def _finish():
        lse = m_scr[:, 0:1] + jnp.log(l_scr[:, 0:1])
        loss_ref[...] = jnp.broadcast_to(lse - t_scr[:, 0:1],
                                         loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_dx_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dx_ref, acc_scr,
                   *, bn, bv, nv):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j >= 0)
    def _body():
        s = jax.lax.dot_general(
            x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bn, bv]
        p = jnp.exp(s - lse_ref[:, 0:1])               # softmax block
        hit = _onehot_mask(lab_ref[:, 0:1], j, bn, bv)
        ds = (p - hit.astype(jnp.float32)) * g_ref[:, 0:1]
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(w_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bn, C]

    @pl.when(j == nv - 1)
    def _finish():
        dx_ref[...] = acc_scr[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dw_ref, acc_scr,
                   *, bn, bv, nn):
    j = pl.program_id(0)   # vocab block
    i = pl.program_id(1)   # token block (innermost: scratch carries)

    @pl.when(i == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(i >= 0)
    def _body():
        s = jax.lax.dot_general(
            x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bn, bv]
        p = jnp.exp(s - lse_ref[:, 0:1])
        hit = _onehot_mask(lab_ref[:, 0:1], j, bn, bv)
        ds = (p - hit.astype(jnp.float32)) * g_ref[:, 0:1]
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(x_ref.dtype), x_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bv, C]

    @pl.when(i == nn - 1)
    def _finish():
        dw_ref[...] = acc_scr[:].astype(dw_ref.dtype)


def _broadcast8(x, dtype=None):
    x = jnp.asarray(x)
    if dtype is not None:
        x = x.astype(dtype)
    return jnp.broadcast_to(x[:, None], (*x.shape, 8))


def _xent_fwd(x, w, labels8, bn, bv):
    N, C = x.shape
    V = w.shape[0]
    nn, nv = N // bn, V // bv
    loss8, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, bn=bn, bv=bv, nv=nv),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, C), lambda i, j: (i, 0)),     # x
            pl.BlockSpec((bv, C), lambda i, j: (j, 0)),     # w
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),     # labels
        ],
        out_specs=[
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),     # loss
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),     # lse
        ],
        out_shape=[
            _out_struct((N, 8), jnp.float32, x, w, labels8),
            _out_struct((N, 8), jnp.float32, x, w, labels8),
        ],
        scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32)] * 3,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(x, w, labels8)
    return loss8[:, 0], lse8


def _xent_bwd(x, w, labels8, lse8, g8, bn, bv):
    N, C = x.shape
    V = w.shape[0]
    nn, nv = N // bn, V // bv
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, bn=bn, bv=bv, nv=nv),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, C), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, C), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),     # lse
            pl.BlockSpec((bn, 8), lambda i, j: (i, 0)),     # g
        ],
        out_specs=pl.BlockSpec((bn, C), lambda i, j: (i, 0)),
        out_shape=_out_struct((N, C), x.dtype, x, w, labels8, lse8, g8),
        scratch_shapes=[pltpu.VMEM((bn, C), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(x, w, labels8, lse8, g8)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bn=bn, bv=bv, nn=nn),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec((bn, C), lambda j, i: (i, 0)),
            pl.BlockSpec((bv, C), lambda j, i: (j, 0)),
            pl.BlockSpec((bn, 8), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 8), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 8), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bv, C), lambda j, i: (j, 0)),
        out_shape=_out_struct((V, C), w.dtype, x, w, labels8, lse8, g8),
        scratch_shapes=[pltpu.VMEM((bv, C), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(x, w, labels8, lse8, g8)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _linear_xent(x, w, labels8, bn, bv):
    loss, _ = _xent_fwd(x, w, labels8, bn, bv)
    return loss


def _linear_xent_vjp_fwd(x, w, labels8, bn, bv):
    loss, lse8 = _xent_fwd(x, w, labels8, bn, bv)
    return loss, (x, w, labels8, lse8)


def _linear_xent_vjp_bwd(bn, bv, res, g):
    x, w, labels8, lse8 = res
    dx, dw = _xent_bwd(x, w, labels8, lse8, _broadcast8(g, jnp.float32),
                       bn, bv)
    return dx, dw, None


_linear_xent.defvjp(_linear_xent_vjp_fwd, _linear_xent_vjp_bwd)


def _dense_xent(x, w, labels, dtype=None):
    """The plain XLA formulation: einsum head + optax cross-entropy.
    Single source for both linear_cross_entropy's no-legal-blocking
    fallback and lm_head_loss's dense branch."""
    import optax

    logits = jnp.einsum("...c,vc->...v",
                        x if dtype is None else x.astype(dtype),
                        w if dtype is None else w.astype(dtype),
                        preferred_element_type=jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def linear_cross_entropy(x, w, labels, *,
                         block_n=None,
                         block_v=None):
    """Per-token cross entropy of ``softmax(x @ wᵀ)`` against ``labels``.

    ``x``: [..., C] activations (any leading shape); ``w``: [V, C] vocab
    embedding/head matrix; ``labels``: [...] int. Returns [...] fp32
    losses. Differentiable w.r.t. ``x`` and ``w`` (custom VJP, Pallas
    kernels; the [N, V] logits never touch HBM). Falls back to the plain
    XLA formulation when no legal blocking exists.

    Blocks default to the kernel autotuner's cached/swept choice for this
    (shape, chip) (ops/kernel_autotune.py) unless the
    ``HOROVOD_XENT_BLOCK_N/V`` knobs or explicit arguments pin them.
    """
    import os

    lead = x.shape[:-1]
    C = x.shape[-1]
    V = w.shape[0]
    N = 1
    for d in lead:
        N *= d
    xf = x.reshape(N, C)
    lab = labels.reshape(N)
    if block_n is None and block_v is None:
        if (os.environ.get("HOROVOD_XENT_BLOCK_N")
                or os.environ.get("HOROVOD_XENT_BLOCK_V")):
            block_n = _block_knob("HOROVOD_XENT_BLOCK_N", 1024)
            block_v = _block_knob("HOROVOD_XENT_BLOCK_V", 1024)
        else:
            from . import kernel_autotune

            if kernel_autotune.enabled():
                block_n, block_v = kernel_autotune.xent_blocks(
                    N, V, C, x.dtype, (_DEF_BLOCK_N, _DEF_BLOCK_V),
                    _pick_block)
            else:
                block_n, block_v = _DEF_BLOCK_N, _DEF_BLOCK_V
    else:
        block_n = _DEF_BLOCK_N if block_n is None else block_n
        block_v = _DEF_BLOCK_V if block_v is None else block_v
    bn, bv = _pick_block(N, block_n), _pick_block(V, block_v)
    if bn is None or bv is None:
        return _dense_xent(xf, w, lab, dtype=jnp.float32).reshape(lead)
    xf, w, lab8 = _harmonize_vma(xf, w, _broadcast8(lab, jnp.int32))
    loss = _linear_xent(xf, w, lab8, bn, bv)
    return loss.reshape(lead)


def lm_head_loss(x, w, labels, *, mode: str = "auto"):
    """LM-head loss with measured dispatch: XLA's dense einsum+optax head
    wherever its logits fit, the fused Pallas kernel beyond.

    Measured on one v5e (GPT-124M step, seq 1024, per-chip batch 8,
    BENCH_r04 sweep): the dense head is uniformly FASTER at every vocab
    that compiles — 110.4k vs 105.2k tok/s at V=32k, 94.5k vs 90.8k at
    64k, 76.5k vs 70.5k at 128k, 55.4k vs 49.2k at 256k (4–11%; XLA's
    fused matmul+xent is near-roofline and its [N, V] round trip is
    cheaper than this kernel's extra W re-streams). There is NO
    throughput crossover: the fused kernel's value is the operating
    envelope — at [32k tokens x 128k vocab] the dense step fails to
    compile (the fp32 logits alone are 17 GB against 16 GB HBM) while
    the fused path runs. ``mode="auto"`` therefore picks dense while a
    single fp32 logits buffer (``N * V * 4`` bytes — the unit XLA must
    materialize at least once in the dense head) stays under
    ``HOROVOD_XENT_AUTO_LOGITS_GB`` (default 10 GiB: strictly above the
    measured-working 256k point, which is exactly 8 GiB, so that point
    stays dense with margin rather than by strict-inequality luck; and
    safely below the failing 17 GB point), and fused above it.
    ``mode="dense"``/``"fused"`` force a path.
    """
    import os

    if mode not in ("auto", "dense", "fused"):
        raise ValueError(f"mode must be auto|dense|fused, got {mode!r}")
    use_fused = mode == "fused"
    # Read the block knob at CALL time (unlike the import-time module
    # default) so a runtime os.environ override works the way the
    # adjacent HOROVOD_XENT_AUTO_LOGITS_GB knob does. An empty string
    # means unset (shell idiom), matching _env_int's treatment.
    env_bn = os.environ.get("HOROVOD_XENT_BLOCK_N") or None
    block_n = _block_knob("HOROVOD_XENT_BLOCK_N", _DEF_BLOCK_N)
    if mode == "auto":
        N = 1
        for d in x.shape[:-1]:
            N *= d
        budget = float(os.environ.get(
            "HOROVOD_XENT_AUTO_LOGITS_GB", "10")) * 2 ** 30
        use_fused = N * w.shape[0] * 4.0 > budget
        if use_fused and env_bn is None:
            # Auto only fires at large N·V, where the 1024-row block's
            # backward overflows the VMEM scoped stack inside a full
            # train-step fusion context (measured: 17.18M vs the 16M
            # limit at [32k tokens, 128k vocab]); 512 rows compiles and
            # measures identically standalone (196.6 vs 196.9 ms).
            block_n = min(512, block_n)
            from . import kernel_autotune

            if kernel_autotune.enabled():
                # Tune within the in-context-safe grid (bn <= 512); the
                # sweep-failure default stays the safe 512-row block.
                block_n, bv = kernel_autotune.xent_blocks(
                    N, w.shape[0], x.shape[-1], x.dtype,
                    (block_n, _DEF_BLOCK_V), _pick_block)
                return linear_cross_entropy(x, w, labels,
                                            block_n=block_n, block_v=bv)
    if use_fused:
        return linear_cross_entropy(x, w, labels, block_n=block_n)
    return _dense_xent(x, w, labels)
