"""Adasum: adaptive-summation reduction.

Reference: ``horovod/common/ops/adasum/adasum.h`` — the pairwise combine
(adasum.h:101-141) scales each operand by ``1 - dot(a,b) / (2 |.|^2)`` so
that parallel components are averaged and orthogonal components are summed,
then applies it recursively over ranks via vector-halving distance-doubling
(``FusedAllreduce``, adasum.h:196+). Requires a power-of-two rank count
(torch/mpi_ops.py:95-115). docs/adasum_user_guide.rst describes the math.

TPU-native redesign
-------------------
The reference's VHDD exists to keep per-rank memory and link traffic at
O(n/P) on a CPU/GPU cluster. On a TPU slice the reduction runs *inside* the
compiled program, so we express the same binary combine tree directly:
``all_gather`` the per-rank contributions over the mesh axes (one ICI
collective), then fold the tree level-by-level with ``lax`` ops on every
chip. Dot products and norms are computed in float32 regardless of wire
dtype — the reference leans on fp64/AVX for this (adasum.h:101-141,
half.h:142); bf16 accumulation would destroy the scaling coefficients.

The gathered tree combine is numerically identical to VHDD's recursive
halving (same pairing order) and turns into pure MXU/VPU work after one
gather — but it holds a P× copy of the tensor on every chip. For tensors
where that blow-up matters (``size * P >= GATHER_THRESHOLD_ELEMS``, power-
of-two worlds) :func:`_vhdd_allreduce` runs the reference's actual
distributed VHDD in-jit: per level, pairs exchange *half* their current
segment via ``lax.ppermute``, the level's dot/norm partials are assembled
with one tiny all_gather, and the final reassembly is a single psum of
disjointly-placed shards (which also re-establishes replication for the
sharding checker). Per-chip memory stays O(n); traffic is ≈3n total
(≈n halving + ≈2n psum reassembly — see :func:`_vhdd_allreduce` for why
the ≈n all_gather reassembly loses under JAX's VMA model).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import collective_ops as C


def adasum_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Combine two contributions (reference: adasum.h:101-141).

    result = a * (1 - dot/(2|a|^2)) + b * (1 - dot/(2|b|^2)),
    with a zero-norm operand falling back to coefficient 1.
    """
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    acoef = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)),
                      1.0)
    bcoef = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)),
                      1.0)
    return (acoef * af + bcoef * bf).astype(a.dtype)


def _tree_combine(stack: jax.Array) -> jax.Array:
    """Fold ``stack[P, ...]`` with the Adasum combine in VHDD pairing order:
    level 1 pairs (0,1),(2,3),...; level 2 pairs the results; etc."""
    p = stack.shape[0]
    while p > 1:
        if p % 2 == 1:
            # Non-power-of-two world: carry the odd tail rank up unpaired
            # (the reference instead requires power-of-two ranks,
            # torch/mpi_ops.py:95-115 — we relax that).
            tail = stack[p - 1:p]
            body = stack[: p - 1]
        else:
            tail = None
            body = stack
        left = body[0::2]
        right = body[1::2]
        combined = jax.vmap(adasum_combine)(left, right)
        stack = combined if tail is None else jnp.concatenate([combined, tail])
        p = stack.shape[0]
    return stack[0]


# Use the distributed VHDD once the gathered stack (elements x world size)
# would cross this many elements (64M f32 = 256 MB of gather buffer).
GATHER_THRESHOLD_ELEMS = 64 * 1024 * 1024


def _bit_reverse(i: int, bits: int) -> int:
    out = 0
    for j in range(bits):
        out |= ((i >> j) & 1) << (bits - 1 - j)
    return out


def _vhdd_allreduce(tensor: jax.Array, axes_t: Tuple[str, ...]) -> jax.Array:
    """Distributed vector-halving distance-doubling Adasum (reference:
    FusedAllreduce, adasum.h:196+), in-jit over the mesh axes.

    Level l (distance d=2^l): pair (r, r^d) splits its current segment —
    the lower rank keeps the first half — and the halves travel by
    ``ppermute``. The level's global dot/|a|²/|b|² are assembled from
    per-rank partials with a 3-float all_gather masked to the 2d-rank block
    (the reference's SumAllreduceWithComm over reduction_comms_). After
    log2(P) levels rank r owns the combined block ``bitrev(r)``; one psum
    of disjointly-placed shards reassembles the replicated result.

    Traffic: ≈3n per rank — ≈n for the halving phase plus ≈2n for the psum
    reassembly. The textbook VHDD doubling phase (or an all_gather of the
    n/P shards) would cost only ≈n, but under JAX's VMA model (jax 0.9)
    every all_gather/ppermute result is statically device-varying with no
    zero-cost way to assert replication, so clearing it costs ≥n more;
    psum is replicated by construction. Memory stays O(n) per chip either
    way, which is what this path exists for.
    """
    P = C._world_size(axes_t)
    levels = P.bit_length() - 1
    rank = lax.axis_index(axes_t)
    orig_dtype, orig_shape = tensor.dtype, tensor.shape
    flat = tensor.astype(jnp.float32).ravel()
    n0 = flat.shape[0]
    n = ((n0 + P - 1) // P) * P  # zero-pad: zeros are inert in dot/norms
    flat = jnp.pad(flat, (0, n - n0))

    seg = flat
    ids = jnp.arange(P)
    for l in range(levels):
        d = 1 << l
        half = seg.shape[0] // 2
        lower = (rank & d) == 0
        first, second = seg[:half], seg[half:]
        send = jnp.where(lower, second, first)
        kept = jnp.where(lower, first, second)
        perm = [(r, r ^ d) for r in range(P)]
        recv = lax.ppermute(send, axes_t, perm)
        a = jnp.where(lower, kept, recv)
        b = jnp.where(lower, recv, kept)
        partial = jnp.stack(
            [jnp.vdot(a, b), jnp.vdot(a, a), jnp.vdot(b, b)])
        allp = lax.all_gather(partial, axes_t, axis=0)  # (P, 3)
        block = (ids >> (l + 1)) == (rank >> (l + 1))
        dot, na, nb = jnp.sum(
            jnp.where(block[:, None], allp, 0.0), axis=0)
        acoef = jnp.where(na > 0,
                          1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)),
                          1.0)
        bcoef = jnp.where(nb > 0,
                          1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)),
                          1.0)
        seg = acoef * a + bcoef * b

    # Rank r's shard is logical block bitrev(r): place it there and psum the
    # disjoint shards. An ``all_gather`` of the n/P shards would move only
    # ~n (vs the psum's ~2n), but in JAX's VMA model (jax 0.9) every
    # all_gather/ppermute result is statically device-varying and there is
    # no zero-cost "assert replicated": clearing it needs a pbroadcast
    # (+n on TPU) or masked psum (+2n), netting nothing. psum is the one
    # reassembly that is replicated *by construction*.
    shard_len = n // P
    brev = rank * 0
    for j in range(levels):
        brev = brev | (((rank >> j) & 1) << (levels - 1 - j))
    full = jnp.zeros((n,), jnp.float32)
    full = lax.dynamic_update_slice_in_dim(full, seg, brev * shard_len, 0)
    out = lax.psum(full, axes_t)
    return out[:n0].reshape(orig_shape).astype(orig_dtype)


def adasum_allreduce(
    tensor: jax.Array,
    *,
    axes=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=None,
) -> jax.Array:
    """Adasum-allreduce across the Horovod mesh axes (in-jit only).

    Reference call path: EnqueueTensorAllreduce with ReduceOp::ADASUM →
    AdasumMPIAllreduceOp / AdasumGpuAllreduceOp (ops/adasum_*_operations.cc).

    ``compression`` reduces the gather's wire payload; the combine math
    still accumulates in float32 (see :func:`adasum_combine`), so only the
    contributions travel compressed, as in the reference's fp16 Adasum path
    (adasum.h AVX fp16 dispatch).
    """
    axes_t = C._resolve_axes(axes)
    tensor = C._scale(tensor, prescale_factor)
    if not axes_t:
        # Eager path: the native core runs recursive-doubling Adasum over
        # the process world (cc/src/adasum.cc).
        out = C._eager_allreduce(tensor, C.ReduceOp.ADASUM)
        return C._scale(out, postscale_factor)
    world = C._world_size(axes_t)
    if (world & (world - 1)) == 0 and world > 1 and \
            tensor.size * world >= GATHER_THRESHOLD_ELEMS:
        # Large tensor on a power-of-two world: distributed VHDD keeps
        # per-chip memory at O(n) instead of the gather's O(n*P).
        out = _vhdd_allreduce(tensor, axes_t)
        return C._scale(out, postscale_factor)
    ctx = None
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    stack = lax.all_gather(tensor, axes_t, axis=0, tiled=False)
    if compression is not None:
        stack = compression.decompress(stack, ctx)
    out = _tree_combine(stack)
    # Every rank computed the identical combined value; the closing rank-0
    # broadcast re-establishes replication for the sharding checker.
    out = C.broadcast(out, 0, axes=axes_t)
    return C._scale(out, postscale_factor)
