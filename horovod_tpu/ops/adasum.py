"""Adasum: adaptive-summation reduction.

Reference: ``horovod/common/ops/adasum/adasum.h`` — the pairwise combine
(adasum.h:101-141) scales each operand by ``1 - dot(a,b) / (2 |.|^2)`` so
that parallel components are averaged and orthogonal components are summed,
then applies it recursively over ranks via vector-halving distance-doubling
(``FusedAllreduce``, adasum.h:196+). Requires a power-of-two rank count
(torch/mpi_ops.py:95-115). docs/adasum_user_guide.rst describes the math.

TPU-native redesign
-------------------
The reference's VHDD exists to keep per-rank memory and link traffic at
O(n/P) on a CPU/GPU cluster. On a TPU slice the reduction runs *inside* the
compiled program, so we express the same binary combine tree directly:
``all_gather`` the per-rank contributions over the mesh axes (one ICI
collective), then fold the tree level-by-level with ``lax`` ops on every
chip. Dot products and norms are computed in float32 regardless of wire
dtype — the reference leans on fp64/AVX for this (adasum.h:101-141,
half.h:142); bf16 accumulation would destroy the scaling coefficients.

The gathered tree combine is numerically identical to VHDD's recursive
halving (same pairing order) and turns into pure MXU/VPU work after one
gather. A distributed ppermute-based VHDD is a later optimization for
tensors too large to gather.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import collective_ops as C


def adasum_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Combine two contributions (reference: adasum.h:101-141).

    result = a * (1 - dot/(2|a|^2)) + b * (1 - dot/(2|b|^2)),
    with a zero-norm operand falling back to coefficient 1.
    """
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    acoef = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)),
                      1.0)
    bcoef = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)),
                      1.0)
    return (acoef * af + bcoef * bf).astype(a.dtype)


def _tree_combine(stack: jax.Array) -> jax.Array:
    """Fold ``stack[P, ...]`` with the Adasum combine in VHDD pairing order:
    level 1 pairs (0,1),(2,3),...; level 2 pairs the results; etc."""
    p = stack.shape[0]
    while p > 1:
        if p % 2 == 1:
            # Non-power-of-two world: carry the odd tail rank up unpaired
            # (the reference instead requires power-of-two ranks,
            # torch/mpi_ops.py:95-115 — we relax that).
            tail = stack[p - 1:p]
            body = stack[: p - 1]
        else:
            tail = None
            body = stack
        left = body[0::2]
        right = body[1::2]
        combined = jax.vmap(adasum_combine)(left, right)
        stack = combined if tail is None else jnp.concatenate([combined, tail])
        p = stack.shape[0]
    return stack[0]


def adasum_allreduce(
    tensor: jax.Array,
    *,
    axes=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=None,
) -> jax.Array:
    """Adasum-allreduce across the Horovod mesh axes (in-jit only).

    Reference call path: EnqueueTensorAllreduce with ReduceOp::ADASUM →
    AdasumMPIAllreduceOp / AdasumGpuAllreduceOp (ops/adasum_*_operations.cc).

    ``compression`` reduces the gather's wire payload; the combine math
    still accumulates in float32 (see :func:`adasum_combine`), so only the
    contributions travel compressed, as in the reference's fp16 Adasum path
    (adasum.h AVX fp16 dispatch).
    """
    axes_t = C._resolve_axes(axes)
    tensor = C._scale(tensor, prescale_factor)
    if not axes_t:
        # Eager path: the native core runs recursive-doubling Adasum over
        # the process world (cc/src/adasum.cc).
        out = C._eager_allreduce(tensor, C.ReduceOp.ADASUM)
        return C._scale(out, postscale_factor)
    ctx = None
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    stack = lax.all_gather(tensor, axes_t, axis=0, tiled=False)
    if compression is not None:
        stack = compression.decompress(stack, ctx)
    out = _tree_combine(stack)
    # Every rank computed the identical combined value; the closing rank-0
    # broadcast re-establishes replication for the sharding checker.
    out = C.broadcast(out, 0, axes=axes_t)
    return C._scale(out, postscale_factor)
