"""Pallas TPU fused residual-add + LayerNorm.

The reference framework has no normalization kernels (CNN-era data
parallelism; its BN analogue is SyncBatchNorm's CUDA path). On TPU the
transformer's residual stream is pure HBM traffic: the pre-LN block
pattern

    h = x + sublayer_out        # one [N, C] write
    y = LN(h) * gamma + beta    # one [N, C] read + write

round-trips the stream an extra time whenever XLA does not fuse the add
into the LayerNorm's reductions. This kernel computes both in one pass:
one read of x and sublayer_out, one write of h (the stream continues
through it) and y — the VERDICT r4 "fused LN+residual" MFU lever, built
so the TPU A/B is one bench flag (``--fused-ln``).

Forward grid: row blocks of the flattened [N, C] stream; per-row mean /
rstd live only in VMEM. The backward recomputes the row statistics from
the saved ``h`` (recompute-over-store: no stats residual, no awkward
[N, 1] outputs) and emits per-row-block partial dgamma/dbeta that a
cheap XLA sum folds.

Numerics: statistics and the normalized value are fp32 regardless of the
stream dtype (same policy as flax ``nn.LayerNorm(dtype=...)`` with fp32
params); ``h`` is materialized in the stream dtype — identical to what
the unfused pattern stores.

Off-TPU the kernel runs in Pallas interpreter mode so the CPU test suite
exercises the identical code path (tests/test_layer_norm.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 naming
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from .flash_attention import _harmonize_vma, _interpret, _out_struct

_DEF_BLOCK_ROWS = 256


def _pad_rows(n: int, preferred: int):
    """(block_rows, padded_n): rows pad up to a block multiple instead of
    hunting for an exact divisor — a prime N must not degrade to 1-row
    blocks (a sublane-1 tile per grid step, far slower than unfused)."""
    br = min(preferred, n)
    return br, ((n + br - 1) // br) * br


def _padded(a, n_pad):
    if not n_pad:
        return a
    return jnp.concatenate(
        [a, jnp.zeros((n_pad,) + a.shape[1:], a.dtype)], axis=0)


def _fwd_kernel(x_ref, r_ref, g_ref, b_ref, y_ref, h_ref, *, eps, inv_c):
    # The whole body lives in a pl.when with a TRACED truth predicate:
    # scalar constants (1/C, eps) mixed with varying blocks trip the HLO
    # interpreter's vma checking under shard_map outside when-bodies
    # (same idiom as flash_attention._run_pred's always-run case).
    i = pl.program_id(0)

    @pl.when(i >= 0)
    def _():
        h = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
        mean = jnp.sum(h, axis=-1, keepdims=True) * inv_c
        var = jnp.sum(jnp.square(h - mean), axis=-1, keepdims=True) * inv_c
        rstd = jax.lax.rsqrt(var + eps)
        y = (h - mean) * rstd * g_ref[...].astype(jnp.float32) + \
            b_ref[...].astype(jnp.float32)
        h_ref[...] = h.astype(h_ref.dtype)
        y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(h_ref, g_ref, dy_ref, dh_ref, dx_ref, dg_ref, db_ref,
                dg_scr, db_scr, *, eps, inv_c, nb):
    # dgamma/dbeta partials accumulate in VMEM scratch across the
    # (sequential) row-block grid and are written once at the last step:
    # a per-block (1, C) output block would violate Mosaic's (8, 128)
    # block-shape minimum (the r5 TPU bring-up failure — interpreter mode
    # never checks it), while the (8, C) full-array output below is
    # always legal.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_scr[...] = jnp.zeros_like(dg_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    @pl.when(i >= 0)  # traced truth: see _fwd_kernel
    def _():
        h = h_ref[...].astype(jnp.float32)
        dy = dy_ref[...].astype(jnp.float32)
        g = g_ref[...].astype(jnp.float32)
        mean = jnp.sum(h, axis=-1, keepdims=True) * inv_c
        var = jnp.sum(jnp.square(h - mean), axis=-1, keepdims=True) * inv_c
        rstd = jax.lax.rsqrt(var + eps)
        xhat = (h - mean) * rstd
        dyg = dy * g
        c1 = jnp.sum(dyg, axis=-1, keepdims=True) * inv_c
        c2 = jnp.sum(dyg * xhat, axis=-1, keepdims=True) * inv_c
        dln = rstd * (dyg - c1 - xhat * c2)
        dx_ref[...] = (dln + dh_ref[...].astype(jnp.float32)).astype(
            dx_ref.dtype)
        # Full-tile broadcast accumulate (all 8 sublanes carry the same
        # value) — avoids single-sublane scatter writes; row 0 is read out.
        dg_scr[...] += jnp.broadcast_to(
            jnp.sum(dy * xhat, axis=0, keepdims=True), dg_scr.shape)
        db_scr[...] += jnp.broadcast_to(
            jnp.sum(dy, axis=0, keepdims=True), db_scr.shape)

    @pl.when(i == nb - 1)
    def _finish():
        dg_ref[...] = dg_scr[...]
        db_ref[...] = db_scr[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def ln_residual(x, res, gamma, beta, eps: float = 1e-5,
                block_rows: int = _DEF_BLOCK_ROWS):
    """``h = x + res;  y = LN(h) * gamma + beta`` in one fused pass.

    Args:
      x, res: ``[..., C]`` stream and sublayer output (same shape/dtype).
      gamma, beta: ``[C]`` scale/shift (fp32 params as in flax).

    Returns ``(y, h)`` — ``y`` in the stream dtype, ``h`` the updated
    residual stream (what the unfused pattern's add produces).
    """
    y, h = _fwd_impl(x, res, gamma, beta, eps, block_rows)
    return y, h


def _flatten(a):
    return a.reshape(-1, a.shape[-1])


def _fwd_impl(x, res, gamma, beta, eps, block_rows):
    if x.shape != res.shape:
        raise ValueError(f"x/res shape mismatch: {x.shape} vs {res.shape}")
    C = x.shape[-1]
    if gamma.shape != (C,) or beta.shape != (C,):
        raise ValueError(
            f"gamma/beta must be [{C}], got {gamma.shape}/{beta.shape}")
    orig_shape = x.shape
    x2, r2 = _flatten(x), _flatten(res)
    N = x2.shape[0]
    if N == 0:  # empty stream (e.g. a zero-row microbatch slice)
        h = x + res
        return jnp.zeros_like(h), h
    br, Np = _pad_rows(N, block_rows)
    x2, r2 = _padded(x2, Np - N), _padded(r2, Np - N)
    g2, b2 = gamma.reshape(1, C), beta.reshape(1, C)
    x2, r2, g2, b2 = _harmonize_vma(x2, r2, g2, b2)
    row_spec = pl.BlockSpec((br, C), lambda i: (i, 0))
    par_spec = pl.BlockSpec((1, C), lambda i: (0, 0))
    y, h = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, inv_c=1.0 / C),
        grid=(Np // br,),
        in_specs=[row_spec, row_spec, par_spec, par_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[_out_struct((Np, C), x.dtype, x2, r2),
                   _out_struct((Np, C), x.dtype, x2, r2)],
        interpret=_interpret(),
    )(x2, r2, g2, b2)
    return y[:N].reshape(orig_shape), h[:N].reshape(orig_shape)


def _vjp_fwd(x, res, gamma, beta, eps, block_rows):
    y, h = _fwd_impl(x, res, gamma, beta, eps, block_rows)
    return (y, h), (h, gamma)


def _vjp_bwd(eps, block_rows, residuals, cts):
    h, gamma = residuals
    dy, dh = cts
    C = h.shape[-1]
    orig_shape = h.shape
    h2, dy2, dh2 = _flatten(h), _flatten(dy), _flatten(dh)
    N = h2.shape[0]
    if N == 0:
        z = jnp.zeros_like(gamma)
        return jnp.zeros_like(h), jnp.zeros_like(h), z, z
    br, Np = _pad_rows(N, block_rows)
    h2 = _padded(h2, Np - N)
    dy2 = _padded(dy2, Np - N)  # zero rows: no dgamma/dbeta pollution
    dh2 = _padded(dh2, Np - N)
    nb = Np // br
    g2 = gamma.reshape(1, C)
    h2, g2, dy2, dh2 = _harmonize_vma(h2, g2, dy2, dh2)
    row_spec = pl.BlockSpec((br, C), lambda i: (i, 0))
    par_spec = pl.BlockSpec((1, C), lambda i: (0, 0))
    acc_spec = pl.BlockSpec((8, C), lambda i: (0, 0))
    dx, dgp, dbp = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, inv_c=1.0 / C, nb=nb),
        grid=(nb,),
        in_specs=[row_spec, par_spec, row_spec, row_spec],
        out_specs=[row_spec, acc_spec, acc_spec],
        out_shape=[_out_struct((Np, C), h.dtype, h2, dy2, dh2),
                   _out_struct((8, C), jnp.float32, h2, dy2, dh2),
                   _out_struct((8, C), jnp.float32, h2, dy2, dh2)],
        scratch_shapes=[pltpu.VMEM((8, C), jnp.float32),
                        pltpu.VMEM((8, C), jnp.float32)],
        # The scratch accumulators carry across row blocks: the grid dim
        # must stay sequential.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(h2, g2, dy2, dh2)
    dx = dx[:N].reshape(orig_shape)
    dgamma = dgp[0].astype(gamma.dtype)
    dbeta = dbp[0].astype(gamma.dtype)
    # h = x + res: both inputs receive the same cotangent.
    return dx, dx, dgamma, dbeta


ln_residual.defvjp(_vjp_fwd, _vjp_bwd)
