"""One-shot per-(kernel, shape, chip) Pallas block-size autotuning.

The reference autotunes its performance knobs (fusion threshold, cycle
time) with a Bayesian ParameterManager (horovod/common/optim/ — this
repo's native counterpart is cc/src/parameter_manager.cc + gp.cc). On
TPU, the knobs that matter most are the Pallas kernel block sizes: the
flash-attention block choice alone measured +9% end-to-end GPT
throughput (1024 vs 512, README). This module folds those knobs into an
autotune pass:

* first use of a kernel at a new (shape, dtype, chip) sweeps a small
  candidate grid — each candidate timed as a jitted ``lax.scan`` chain of
  fwd+bwd applications so the device runs a contiguous multi-hundred-ms
  batch (single-dispatch timings through the remote-relay runtime are
  untrustworthy; long chains are);
* the winner lands in an on-disk JSON cache (``HOROVOD_AUTOTUNE_CACHE``,
  default ``~/.cache/horovod_tpu/kernel_autotune.json``) keyed like the
  reference's autotune log — kernel kind, chip kind, shape signature —
  so every later process skips straight to it;
* explicit ``block_*`` arguments and the ``HOROVOD_FLASH_BLOCK_Q/K`` /
  ``HOROVOD_XENT_BLOCK_N/V`` env knobs always win over the autotuner,
  and off-TPU (interpreter-mode tests) the hand-tuned defaults are used
  untouched. ``HOROVOD_KERNEL_AUTOTUNE=0`` disables the sweep entirely.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
_mem: Dict[str, dict] = {}
_loaded = False

# Bump when a kernel's implementation changes in a way that invalidates
# previously-tuned block choices (ADVICE r4: stale cache entries were
# returned before any legality/sweep logic runs). The candidate grid is
# additionally hashed into the key, so grid edits self-invalidate.
_KERNEL_VERSIONS: Dict[str, int] = {
    "flash_attention": 1,
    "linear_xent": 1,
}


def _grid_token(candidates: Sequence[Tuple[int, ...]]) -> str:
    import hashlib

    return hashlib.md5(
        repr(sorted(tuple(c) for c in candidates)).encode()
    ).hexdigest()[:8]


def _cache_path() -> str:
    return os.environ.get(
        "HOROVOD_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "horovod_tpu",
                     "kernel_autotune.json"))


def enabled() -> bool:
    from ..common.config import _env_bool

    if not _env_bool("HOROVOD_KERNEL_AUTOTUNE", True):
        return False
    import jax

    return jax.default_backend() == "tpu"


def _load_locked() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(_cache_path()) as f:
            _mem.update(json.load(f))
    except (OSError, json.JSONDecodeError, ValueError):
        pass  # cache is an optimization, never a failure


def _store_locked(key: str, entry: dict) -> None:
    _mem[key] = entry
    path = _cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Read-merge-write under an OS lock: concurrent processes tuning
        # different shapes must not clobber each other's entries.
        import fcntl

        with open(path + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            disk: dict = {}
            try:
                with open(path) as f:
                    disk = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError):
                pass
            disk[key] = entry
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(disk, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
    except OSError as e:  # cache is an optimization, never a failure
        logging.debug("autotune cache write failed: %s", e)


def cache_lookup(key: str) -> Optional[dict]:
    """Entry stored under ``key`` in the shared autotune cache file
    (``HOROVOD_AUTOTUNE_CACHE``), or None. Used by the collective-knob
    autotuner (autotune/driver.py) so kernel block choices and frozen
    collective tunables live in ONE warm-start file with the same
    locking, atomicity, and multi-host fingerprint discipline."""
    with _lock:
        _load_locked()
        entry = _mem.get(key)
    return entry if isinstance(entry, dict) else None


def cache_store(key: str, entry: dict) -> None:
    """Persist ``entry`` under ``key`` in the shared autotune cache file
    (read-merge-write under the OS lock; see :func:`cache_lookup`)."""
    with _lock:
        _store_locked(key, entry)


def get_or_tune(kind: str, sig: str,
                candidates: Sequence[Tuple[int, ...]],
                bench: Callable[[Tuple[int, ...]], float],
                default: Tuple[int, ...]) -> Tuple[int, ...]:
    """The cached best candidate for (kind, chip, sig), sweeping once if
    unseen. ``bench(candidate)`` returns seconds per application (lower
    is better) or raises — failing candidates are skipped. Falls back to
    ``default`` when disabled, off-TPU, or every candidate fails."""
    if not enabled():
        return default
    import jax

    chip = getattr(jax.devices()[0], "device_kind", "tpu")
    ver = _KERNEL_VERSIONS.get(kind, 1)
    key = f"{kind}|{chip}|{sig}|v{ver}.g{_grid_token(candidates)}"
    with _lock:
        _load_locked()
        hit = _mem.get(key)
    cached = tuple(hit["blocks"]) if (
        isinstance(hit, dict) and isinstance(hit.get("blocks"), list)
    ) else None
    if jax.process_count() > 1:
        # Multi-host SPMD must compile IDENTICAL programs on every host.
        # Per-host cache files can legitimately differ (one host tuned,
        # another not), so a local cache hit is only trusted after the
        # init-time fingerprint agreement proved every host loaded the
        # same cache (verify_multihost_cache); otherwise every host
        # falls back to the (identical-by-construction) default. No
        # collective runs here — a hot-path collective gated on
        # host-local state could deadlock divergent hosts.
        if _multihost_cache_ok[0] and cached is not None:
            return cached
        return default
    if cached is not None:
        return cached

    results: List[Tuple[float, Tuple[int, ...]]] = []
    errors: List[str] = []
    t_sweep = time.perf_counter()

    def _sweep() -> None:
        for cand in candidates:
            try:
                dt = bench(cand)
                results.append((dt, cand))
            except Exception as e:  # compile/VMEM failure: candidate illegal
                errors.append(f"{cand}: {type(e).__name__}: {str(e)[:200]}")
                logging.info("autotune %s %s: candidate %s failed (%s)",
                             kind, sig, cand, str(e)[:200])

    # The sweep fires at TRACE time (kernels resolve their blocks while
    # the caller's train step is being traced), and under an ambient jit
    # trace the bench's inner jit calls would be STAGED into that trace
    # instead of executed — the host fetch then hits a tracer and every
    # candidate dies with TracerArrayConversionError (the r5 hardware
    # sessions' silent all-candidates failure). JAX's trace state is
    # thread-local, so a worker thread has a clean trace context while
    # sharing the initialized device client: real compile + execute +
    # timing, regardless of the caller's trace depth.
    # jax context managers (default_device & co) are thread-local: carry
    # the caller's effective default device into the worker so the bench
    # times the device the caller pinned, not whatever device 0 is doing.
    # Anything escaping _sweep's per-candidate try (it only catches
    # Exception) re-raises in the caller — a bare Thread would hand it to
    # threading.excepthook and the empty-results path would then lie
    # ("ALL candidates failed" with no errors).
    caller_device = jax.config.jax_default_device
    escaped: List[BaseException] = []

    def _sweep_with_context() -> None:
        try:
            if caller_device is None:
                _sweep()
            else:
                with jax.default_device(caller_device):
                    _sweep()
        except BaseException as e:
            escaped.append(e)

    worker = threading.Thread(target=_sweep_with_context,
                              name="hvd-autotune")
    worker.start()
    worker.join()
    if escaped:
        raise escaped[0]
    if not results:
        # Every candidate failing is not a per-candidate legality quirk —
        # it is the sweep silently not working (e.g. the relay timing
        # linearity check rejecting everything). Say so once, loudly,
        # with the evidence (r5: a whole hardware session produced no
        # sweep lines because this path logged only at INFO).
        logging.warning(
            "horovod_tpu autotune: %s %s — ALL %d candidates failed; "
            "using default blocks %s. Errors:\n  %s", kind, sig,
            len(candidates), default, "\n  ".join(errors))
        return default
    results.sort()
    best_dt, best = results[0]
    entry = {"blocks": list(best), "seconds_per_call": best_dt,
             "sweep_seconds": round(time.perf_counter() - t_sweep, 1),
             "results": [{"blocks": list(c), "seconds": round(d, 6)}
                         for d, c in results]}
    with _lock:
        _store_locked(key, entry)
    logging.warning(
        "horovod_tpu autotune: %s %s -> blocks %s (%.3f ms/call; swept %d "
        "candidates in %.0fs; cached in %s)", kind, sig, best,
        best_dt * 1e3, len(results), entry["sweep_seconds"], _cache_path())
    return best


# Multi-host cache trust: set once by verify_multihost_cache() at init
# time. Until it runs (and proves every host loaded an identical cache
# file), multi-host get_or_tune uses only the defaults.
_multihost_cache_ok = [False]


def cache_fingerprint() -> str:
    """Canonical digest of the loaded autotune cache."""
    import hashlib

    with _lock:
        _load_locked()
        blob = json.dumps(_mem, sort_keys=True)
    return hashlib.md5(blob.encode()).hexdigest()


def verify_multihost_cache() -> bool:
    """One-shot init-time agreement: allgather the cache fingerprint
    across the process world; local cache hits are trusted in multi-host
    mode only if every host loaded the same cache file (ADVICE r4:
    divergent per-host caches compile divergent XLA programs — a
    hang/garbage risk in SPMD).

    Called from ``hvd.init()`` — the one point where every process is
    guaranteed in lockstep, so the collective cannot deadlock divergent
    hosts the way a lazy hot-path agreement could. Returns the verdict
    (also stored module-globally for get_or_tune)."""
    import jax

    if jax.process_count() <= 1:
        _multihost_cache_ok[0] = True  # single host: nothing to diverge
        return True
    try:
        from ..ops import collective_ops as C
        from ..parallel.functions import allgather_object

        # The allgather must actually span every jax process, or the
        # "agreement" is vacuous.
        if C._eager_world() < jax.process_count():
            logging.info(
                "autotune: eager agreement channel spans %d < %d jax "
                "processes; cannot verify cache consistency",
                C._eager_world(), jax.process_count())
            ok = False
        else:
            prints = allgather_object(cache_fingerprint())
            ok = len(set(prints)) == 1
    except Exception as e:  # no agreement channel: defaults are safe
        logging.info("autotune multi-host cache verification unavailable "
                     "(%s); using default blocks", e)
        ok = False
    if not ok:
        logging.warning(
            "horovod_tpu autotune: per-host kernel caches differ (or "
            "could not be verified); multi-host runs will use default "
            "block sizes. Ship one HOROVOD_AUTOTUNE_CACHE file to every "
            "host to enable tuned blocks.")
    _multihost_cache_ok[0] = ok
    return ok


def _timed_chain(step_fn, args, target_seconds: float = 0.5,
                 max_chain: int = 16384,
                 chain: Optional[int] = None) -> Tuple[float, int]:
    """Seconds per application of ``step_fn``, measured as a jitted
    ``lax.scan`` chain (contiguous device work; iterations serialized
    through the carry so nothing is DCE'd or overlapped away).

    Remote-relay runtimes can return from ``block_until_ready`` early on
    small programs, making short timings fiction — so the chain length
    grows geometrically until one call costs >= ``target_seconds`` of
    wall clock, and the result is accepted only if doubling the chain
    roughly doubles the time (linearity check). Raises when no
    trustworthy measurement can be made. Returns (seconds_per_call,
    chain_used); pass ``chain`` to skip the growth calibration (reusing
    the first candidate's calibration keeps a sweep at two compiles per
    candidate)."""
    import jax
    import numpy as np
    from jax import lax

    def _drain(out):
        # A host FETCH is the only real barrier on relay runtimes:
        # block_until_ready can return early (measured: 0.1 ms for a
        # multi-second program), and async dispatch otherwise bleeds one
        # call's device time into the next measurement.
        np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]

    def make(chain):
        def many(carry, *rest):
            def body(c, _):
                return step_fn(c, *rest), None

            out, _ = lax.scan(body, carry, None, length=chain)
            return out

        f = jax.jit(many)
        _drain(f(*args))  # compile + warm
        return f

    def timed(f):
        t0 = time.perf_counter()
        _drain(f(*args))
        return time.perf_counter() - t0

    if chain is None:
        chain = 64
        while True:
            f = make(chain)
            t = min(timed(f), timed(f))
            if t >= target_seconds or chain >= max_chain:
                break
            grow = max(2, min(16, int(target_seconds / max(t, 1e-4))))
            chain = min(max_chain, chain * grow)
    else:
        f = make(chain)
        t = min(timed(f), timed(f))
    f2 = make(chain * 2)
    t2 = min(timed(f2), timed(f2))
    ratio = t2 / max(t, 1e-9)
    if not 1.3 <= ratio <= 3.0:
        raise RuntimeError(
            f"timing not linear in work (chain {chain}: {t:.3f}s, "
            f"x2: {t2:.3f}s, ratio {ratio:.2f}) — relay timing "
            f"untrustworthy at this size")
    # Difference estimator: the extra `chain` iterations of the doubled
    # call cost (t2 - t), cancelling fixed per-call dispatch overhead.
    return max(t2 - t, 1e-9) / chain, chain


def flash_blocks(B: int, Tq: int, Tk: int, H: int, D: int, dtype,
                 causal: bool, default: Tuple[int, int],
                 pick_block) -> Tuple[int, int]:
    """Autotuned (block_q, block_k) for a flash-attention shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    sig = f"B{B}.Tq{Tq}.Tk{Tk}.H{H}.D{D}.{jnp.dtype(dtype).name}" \
          f".{'c' if causal else 'f'}"

    # Too-small workloads (e.g. the B=1 model.init trace) neither benefit
    # from tuning nor time reliably — keep the default, don't sweep.
    if 4.0 * B * H * Tq * Tk * D < 1e10:
        return default

    # Candidate grid, deduplicated by the EFFECTIVE blocking after the
    # legality shrink (different preferences can collapse to one choice).
    grid = [(bq, bk) for bq in (512, 1024, 2048) for bk in (512, 1024,
                                                            2048)]
    seen, cands = set(), []
    for bq, bk in grid:
        eff = (pick_block(Tq, bq), pick_block(Tk, bk))
        if None in eff or eff in seen:
            continue
        seen.add(eff)
        cands.append((bq, bk))
    if len(cands) <= 1:
        return default

    cal = {"chain": None}  # calibrate once, reuse across candidates

    def bench(cand):
        bq, bk = cand
        from .flash_attention import flash_attention

        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(B, Tq, H, D), dtype) * 0.3
        k = jnp.asarray(rs.randn(B, Tk, H, D), dtype) * 0.3
        v = jnp.asarray(rs.randn(B, Tk, H, D), dtype) * 0.3

        def step(q, k, v):
            g = jax.grad(lambda q: flash_attention(
                q, k, v, causal=causal, block_q=bq,
                block_k=bk).astype(jnp.float32).sum())(q)
            # Couple the carry to the grad with a small NON-ZERO factor:
            # a 0.0 coupling is constant-folded and the whole chain DCE'd
            # into a no-op (measured: 0.000 ms "kernels").
            return q + (1e-8 * g).astype(q.dtype)

        dt, cal["chain"] = _timed_chain(step, (q, k, v),
                                        chain=cal["chain"])
        return dt

    return get_or_tune("flash_attention", sig, cands, bench, default)


def xent_blocks(N: int, V: int, C: int, dtype,
                default: Tuple[int, int], pick_block) -> Tuple[int, int]:
    """Autotuned (block_n, block_v) for the fused linear cross-entropy.

    block_n candidates stop at 512: the 1024-row backward overflows the
    VMEM scoped stack inside full train-step fusion contexts at large
    N·V (measured 17.18M vs the 16M limit) even where it compiles
    standalone — a standalone sweep cannot see that, so the in-context-
    safe bound is enforced here."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    sig = f"N{N}.V{V}.C{C}.{jnp.dtype(dtype).name}"
    if 6.0 * N * V * C < 1e10:  # tiny head: don't sweep (see flash gate)
        return default
    grid = [(bn, bv) for bn in (256, 512) for bv in (512, 1024, 2048)]
    seen, cands = set(), []
    for bn, bv in grid:
        eff = (pick_block(N, bn), pick_block(V, bv))
        if None in eff or eff in seen:
            continue
        seen.add(eff)
        cands.append((bn, bv))
    if len(cands) <= 1:
        return default

    cal = {"chain": None}

    def bench(cand):
        bn, bv = cand
        from .softmax_xent import linear_cross_entropy

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(N, C), dtype)
        w = jnp.asarray(rs.randn(V, C) * 0.02, dtype)
        y = jnp.asarray(rs.randint(0, V, (N,)))

        def step(x, w, y):
            g = jax.grad(lambda x: linear_cross_entropy(
                x, w, y, block_n=bn, block_v=bv).mean())(x)
            return x + (1e-8 * g).astype(x.dtype)  # non-zero: see flash

        dt, cal["chain"] = _timed_chain(step, (x, w, y),
                                        chain=cal["chain"])
        return dt

    return get_or_tune("linear_xent", sig, cands, bench, default)
