"""Gradient compression applied around allreduce.

Reference: ``horovod/tensorflow/compression.py`` / ``horovod/torch/compression.py``
(fp16 cast before allreduce, cast back after; tensorflow/compression.py:46-64).

TPU note: bfloat16 is the MXU-native 16-bit format — it keeps fp32's exponent
range, so unlike fp16 it needs no loss scaling and reduces over ICI at half
the bandwidth of fp32. ``Compression.fp16`` is kept for API parity and maps
to IEEE float16; prefer ``Compression.bf16`` on TPU.

Beyond the reference's dtype casts, ``Compression.int8`` provides
blockwise-scaled int8 quantization (EQuARX-style: one fp32 scale per
``QUANT_BLOCK``-element block, values in [-127, 127]). Inside the compiled
hierarchical allreduce it rides the wire as real int8 + scales on the
cross-host (DCN) hop (plan/compiler.py lower_quantized_allreduce); everywhere
else — eager path, partial-axis reductions — ``compress`` degrades to a
local quantize→dequantize round trip ("fake quant"), which preserves the
numerics of a quantized contribution without needing an int8-aware wire
reduction. The quantization *primitives* here are pure jnp so the fusion,
collective, and test layers all share one definition of the format.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

# Elements per quantization scale block. 256 = 4 x FUSION_BUFFER_ATOMIC_UNIT
# (fusion.ATOMIC_UNIT = 64), so fused-bucket padding keeps whole blocks
# meaningful; non-multiple tails are zero-padded inside quantize_int8.
QUANT_BLOCK = 256


def _block_scales(blocks: jnp.ndarray) -> jnp.ndarray:
    """Per-block positive scale: absmax/127, with absmax==0 mapped to 1 so
    all-zero blocks quantize to exact zeros instead of 0/0."""
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    return jnp.where(absmax > 0, absmax / 127.0, jnp.ones_like(absmax))


def quantize_int8(
    tensor, block: int = QUANT_BLOCK
) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple]:
    """Blockwise int8 quantization of a float tensor.

    Flattens ``tensor``, zero-pads to a multiple of ``block``, and emits
    ``(q, scales, meta)``: ``q`` int8 ``[n_blocks, block]``, ``scales``
    float32 ``[n_blocks]`` (absmax/127 per block), and ``meta`` carrying
    the original shape/dtype for :func:`dequantize_int8`. Round-trip error
    is bounded per element by ``scales[b] / 2`` (round-to-nearest).
    """
    tensor = jnp.asarray(tensor)
    shape, dtype = tensor.shape, tensor.dtype
    flat = jnp.ravel(tensor).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    scales = _block_scales(blocks)
    q = jnp.clip(jnp.round(blocks / scales[:, None]), -127, 127)
    return q.astype(jnp.int8), scales, (shape, dtype, n)


def dequantize_int8(q, scales, meta) -> jnp.ndarray:
    """Inverse of :func:`quantize_int8` (up to the bounded rounding error):
    fp32 multiply-accumulate, then the original shape and dtype."""
    shape, dtype, n = meta
    flat = (q.astype(jnp.float32) * scales[:, None]).reshape(-1)[:n]
    return flat.reshape(shape).astype(dtype)


def fake_quantize_int8(tensor, block: int = QUANT_BLOCK) -> jnp.ndarray:
    """Quantize→dequantize round trip in the original dtype: the value a
    quantized wire contribution carries, without the int8 layout. This is
    what hop-1 of the real quantized collective transmits, so eager-path
    semantics match the compiled path contribution-for-contribution."""
    return dequantize_int8(*quantize_int8(tensor, block))


class Compressor:
    """Interface: compress returns (compressed_tensor, context); decompress
    restores the original dtype."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(ctx, jnp.floating) and ctx != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast float tensors to float16 on the wire (reference:
    tensorflow/compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bfloat16 on the wire — the TPU-native choice."""

    wire_dtype = jnp.bfloat16


class QuantizedCompressor(Compressor):
    """Blockwise-scaled int8 wire format (``Compression.int8``).

    Unlike the cast compressors, int8 blocks with per-block scales are NOT
    closed under addition, so the generic compress/decompress slot cannot
    hand an int8 payload to a sum-reduction. ``compress`` therefore returns
    the fake-quantized value in the original dtype — exactly the
    contribution hop-1 of the real quantized collective transmits — and
    ``allreduce`` routes quantized compression to the real int8
    reduce-scatter/all-gather wire (the quantized allreduce plan,
    plan/compiler.py)
    whenever it is tracing over the full (cross, local) mesh. Pair with
    error feedback (``quantized_allreduce(residual=...)`` or
    ``DistributedOptimizer(quantized=True)``) to carry the quantization
    error into the next step's gradient.
    """

    is_quantized = True
    block: Optional[int] = None  # None -> QUANT_BLOCK / HOROVOD_QUANT_BLOCK

    @classmethod
    def compress(cls, tensor):
        tensor = jnp.asarray(tensor)
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return fake_quantize_int8(tensor, cls.block or QUANT_BLOCK), None
        return tensor, None  # ints/bools pass through, like the cast path

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor


class Compression:
    """Namespace mirroring the reference's ``hvd.Compression`` (plus the
    TPU-native additions ``bf16`` and ``int8``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = QuantizedCompressor
