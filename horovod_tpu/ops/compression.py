"""Gradient compression applied around allreduce.

Reference: ``horovod/tensorflow/compression.py`` / ``horovod/torch/compression.py``
(fp16 cast before allreduce, cast back after; tensorflow/compression.py:46-64).

TPU note: bfloat16 is the MXU-native 16-bit format — it keeps fp32's exponent
range, so unlike fp16 it needs no loss scaling and reduces over ICI at half
the bandwidth of fp32. ``Compression.fp16`` is kept for API parity and maps
to IEEE float16; prefer ``Compression.bf16`` on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress returns (compressed_tensor, context); decompress
    restores the original dtype."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(ctx, jnp.floating) and ctx != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast float tensors to float16 on the wire (reference:
    tensorflow/compression.py:46-64)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bfloat16 on the wire — the TPU-native choice."""

    wire_dtype = jnp.bfloat16


class Compression:
    """Namespace mirroring the reference's ``hvd.Compression``."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
