"""Pallas TPU flash attention: exact attention without the [T, T] round-trip.

The reference framework has no attention kernels at all (it is a CNN-era
data-parallel framework, SURVEY §5.7); its GPU analogue would be a fused
CUDA kernel. On TPU the hot op is the attention score/softmax/value chain:
``dense_attention`` (parallel/sequence.py) materializes a [B, H, T, T] fp32
score tensor twice (scores + probabilities) — at GPT-124M bench shapes
(B=16, H=12, T=1024) that is ~1.6 GB of HBM round-trip per layer, which
dwarfs the matmul time on a bandwidth-limited chip.

This module implements the standard flash-attention schedule as Pallas TPU
kernels (guide: /opt/skills/guides/pallas_guide.md):

* forward: grid (B·H, Tq/bq, Tk/bk); the k-block axis is innermost, so the
  per-q-block running max ``m``, normalizer ``l`` and output accumulator
  live in VMEM scratch across k-steps; scores never leave VMEM. Emits the
  logsumexp residual for the backward pass.
* backward: the split-kernel formulation — one kernel accumulates dQ over
  k-blocks, a second accumulates dK/dV over q-blocks — with the
  ``delta = rowsum(dO ⊙ O)`` precomputed as a cheap fused elementwise op
  in plain XLA. Both kernels recompute probabilities from q, k and the
  saved logsumexp (recompute-over-store: O(T·D) residuals instead of
  O(T²)).
* causal masking skips fully-masked k-blocks via ``pl.when`` (upper
  triangle costs nothing) when block positions are static; with runtime
  offsets (ring partials) the mask runs with global positions instead.
* :func:`flash_ring_attention` composes the kernels with sequence
  parallelism: K/V blocks rotate around the mesh axis via
  ``lax.ppermute`` while each ring step runs the flash kernel with
  global causal positions and partial outputs merge by logsumexp; the
  backward replays the ring with dk/dv accumulators traveling alongside
  their blocks (they arrive home after n rotations).

Everything is static-shaped; block sizes adapt to divide the sequence
(see ``_pick_block`` — a whole-sequence block covers anything <= the
preferred block, and long sequences with no 128-aligned divisor fall back
to the dense path). Off-TPU the kernels run in Pallas interpreter mode so
the CPU test suite exercises the identical code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU backend)

if not hasattr(pltpu, "CompilerParams"):  # jax < 0.6 naming
    pltpu.CompilerParams = pltpu.TPUCompilerParams

if not hasattr(jax, "shard_map"):
    # jax < 0.6: the experimental shard_map's check_rep machinery has no
    # replication rule for pallas_call. The sound rule for a per-device
    # kernel: every output is replicated exactly over the axes ALL
    # operands are replicated over (tensor operands are vma-harmonized
    # before each call; scalar offset operands may stay replicated).
    try:
        from jax.experimental import shard_map as _sm_compat
        from jax._src.pallas.pallas_call import pallas_call_p as _pc_p

        def _pallas_rep_rule(mesh, *in_rep, **params):
            reps = [set(r) for r in in_rep if r is not None]
            return set.intersection(*reps) if reps else None

        _sm_compat.register_check(_pc_p)(_pallas_rep_rule)
        _sm_compat.register_norewrite(_pc_p)
    except Exception:  # pragma: no cover - internal-API drift
        pass

_NEG_INF = -1e30  # finite: keeps running-max arithmetic NaN-free

# Large blocks amortize Mosaic's per-grid-cell overhead and give the MXU
# deep work per cell: a [1024, 1024] f32 score tile is 4 MB of VMEM —
# comfortably under the ~16 MB budget next to the q/k/v/o blocks and
# scratch — and measured on v5e (GPT-124M, seq 1024) block size is worth
# 2x end-to-end: 512-blocks beat the dense path by 28%, 1024-blocks add
# another ~9% (117.2k vs 107.7k tok/s). Tunable like the other HOROVOD_*
# knobs (e.g. for other chip generations' VMEM sizes).


def _block_knob(name: str, default: int) -> int:
    from ..common.config import _env_int

    v = _env_int(name, default)
    if v < 128:
        raise ValueError(
            f"{name}={v}: Pallas kernel blocks must be >= 128 "
            f"(MXU/lane tile)")
    return v


_DEF_BLOCK_Q = _block_knob("HOROVOD_FLASH_BLOCK_Q", 1024)
_DEF_BLOCK_K = _block_knob("HOROVOD_FLASH_BLOCK_K", 1024)


def _resolve_blocks(B, Tq, Tk, H, D, dtype, causal):
    """Block sizes for a flash call that pinned neither block: env knobs
    win; otherwise the kernel autotuner's cached/swept choice (TPU,
    single-process); otherwise the hand-tuned defaults. Multi-process
    SPMD only READS the autotune cache (a sweep could pick different
    blocks on different hosts → divergent programs); ship the cache file
    to every host to use tuned blocks there."""
    import os

    # `or` (not `in`): an empty string means unset, the shell idiom
    # _env_int also honors — consistent with the xent knobs.
    if (os.environ.get("HOROVOD_FLASH_BLOCK_Q")
            or os.environ.get("HOROVOD_FLASH_BLOCK_K")):
        return (_block_knob("HOROVOD_FLASH_BLOCK_Q", 1024),
                _block_knob("HOROVOD_FLASH_BLOCK_K", 1024))
    from . import kernel_autotune

    if not kernel_autotune.enabled():
        return _DEF_BLOCK_Q, _DEF_BLOCK_K
    return kernel_autotune.flash_blocks(
        B, Tq, Tk, H, D, dtype, causal,
        (_DEF_BLOCK_Q, _DEF_BLOCK_K), _pick_block)


def _interpret() -> bool:
    """Run in interpreter mode off-TPU (CPU test suite)."""
    return jax.default_backend() != "tpu"


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-manual-axes are the union of the
    operands' — required inside ``jax.shard_map`` (check_vma), harmless
    outside (vma=frozenset()). jax < 0.6 has no aval-level vma (its
    shard_map tracks replication on the tracer instead), so the plain
    struct is the correct spelling there."""
    from .collective_ops import _vma

    vma = frozenset().union(*[_vma(x) for x in operands])
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # jax < 0.6
        return jax.ShapeDtypeStruct(shape, dtype)


def _harmonize_vma(*arrays):
    """pcast every array to the union of the group's varying-manual-axes.

    Inside ``shard_map``, kernel operands must agree on vma (standard XLA
    primitives get automatic ``pvary`` insertion; pallas kernel jaxprs do
    not). The pcast is a type-level broadcast — free forward, and its
    transpose is the psum a replicated operand's cotangent needs anyway
    (identical to what autodiff inserts for the dense formulation).
    No-op outside shard_map."""
    from .collective_ops import _vma, pvary_missing

    union = frozenset().union(*[_vma(a) for a in arrays])
    if not union:
        return arrays
    axes = tuple(sorted(union))
    return tuple(pvary_missing(a, axes) for a in arrays)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _scalar_spec():
    """Offset operand: one (8, 128) int32 tile, same block every grid step.

    A (1, 1) SMEM scalar would be the idiomatic choice, but jax 0.9's HLO
    interpreter (the CPU test path) rejects pallas calls mixing SMEM scalar
    operands with sharded tensor operands under shard_map's vma checking —
    a tile-aligned VMEM operand behaves identically on both backends and
    costs 4 KB."""
    return pl.BlockSpec((1, 8, 128), lambda b, i, j: (0, 0, 0))


def _as_scalar(x):
    return jnp.broadcast_to(jnp.asarray(x, jnp.int32), (1, 8, 128))


def _causal_mask(s, qoff, koff, i, j, bq, bk):
    """Mask with GLOBAL positions: local block position + runtime offset.
    Offsets arrive as operands (see ``_scalar_spec``) so ring/sharded
    callers can pass traced values (e.g. ``axis_index * T_local``)."""
    qpos = qoff + i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = koff + j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _run_pred(causal, static_skip, i, j, bq, bk):
    """Should block (i, j) compute? A k block contributes iff its first key
    position <= the q block's last query position — decidable statically
    only when offsets are zero (static_skip). With runtime offsets every
    block runs and the global-position mask does the work (callers skip
    whole fully-masked PARTIALS host-side instead: see _ring_fwd_impl;
    mixing the varying offset operands with program-id arithmetic in a
    pl.when predicate trips vma checking). The always-run case returns a
    traced truth (a literal ``True`` would inline the body, which equally
    trips the HLO interpreter's vma checks under shard_map)."""
    if causal and static_skip:
        return j * bk <= i * bq + bq - 1
    return j >= 0


def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, bq, bk, nk, static_skip):
    i = pl.program_id(1)   # q block
    j = pl.program_id(2)   # k block (innermost: scratch carries across j)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_pred(causal, static_skip, i, j, bq, bk)

    @pl.when(run)
    def _body():
        q = q_ref[0]                                    # [bq, D]
        k = k_ref[0]                                    # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, qoff_ref[...][0, 0, 0], koff_ref[...][0, 0, 0], i, j, bq, bk)

        m_prev = m_scr[:, 0:1]                          # [bq, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # Fully-masked rows (possible when a ring partial sees a k block
        # entirely in its causal future): m_new stays at _NEG_INF and
        # s - m_new == 0 would wrongly give p = 1 — zero those rows.
        p = jnp.where(m_new > _NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    j_last = jnp.minimum(nk - 1, (i * bq + bq - 1) // bk) \
        if (causal and static_skip) else nk - 1

    @pl.when(j == j_last)
    def _finish():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        # Fully-masked rows have l == 0: emit o = 0 and lse = -inf-like so
        # a ring merge weights them out. Visible rows always have l > 0
        # (a causal row sees at least its own token).
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse carries a sublane dim of 8 (Mosaic block-mapping minimum for
        # the trailing-two dims); value broadcast across it.
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(l > 0, m + jnp.log(safe_l), _NEG_INF),
            lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal, bq, bk, q_off=0, k_off=0,
               static_skip=True):
    """q,k,v: [BH, T, D] → (o [BH, Tq, D], lse [BH, Tq, 8] f32).

    ``q_off``/``k_off`` are global positions of the first query/key token
    (may be traced, e.g. ``lax.axis_index(...) * T_local`` under a ring);
    pass ``static_skip=False`` whenever they can be nonzero."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // bq, Tk // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, static_skip=static_skip)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((BH, Tq, D), q.dtype, q, k, v, q_off, k_off),
            _out_struct((BH, Tq, 8), jnp.float32, q, k, v, q_off, k_off),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running normalizer l
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(_as_scalar(q_off), _as_scalar(k_off), q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, acc_scr,
                   *, scale, causal, bq, bk, nk, static_skip):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_pred(causal, static_skip, i, j, bq, bk)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qoff_ref[...][0, 0, 0], koff_ref[...][0, 0, 0], i, j, bq, bk)
        # Masked entries: s = -1e30 and finite lse → p = 0 automatically;
        # fully-masked rows have lse = -1e30 from the forward, giving
        # exp(-1e30 - (-1e30)) = 1 on masked entries — zero them.
        p = jnp.where(lse_ref[0, :, 0:1] > _NEG_INF / 2,
                      jnp.exp(s - lse_ref[0, :, 0:1]), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0, :, 0:1])
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    j_last = jnp.minimum(nk - 1, (i * bq + bq - 1) // bk) \
        if (causal and static_skip) else nk - 1

    @pl.when(j == j_last)
    def _finish():
        dq_ref[0] = (acc_scr[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, bq, bk, nq, static_skip):
    j = pl.program_id(1)   # k block
    i = pl.program_id(2)   # q block (innermost: scratch carries across i)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _run_pred(causal, static_skip, i, j, bq, bk)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qoff_ref[...][0, 0, 0], koff_ref[...][0, 0, 0], i, j, bq, bk)
        p = jnp.where(lse_ref[0, :, 0:1] > _NEG_INF / 2,
                      jnp.exp(s - lse_ref[0, :, 0:1]), 0.0)  # [bq, bk]
        do = do_ref[0]                                   # [bq, D]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0, :, 0:1])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _prep_residuals(o, do):
    """delta = rowsum(dO ⊙ O) with the broadcast sublane dim."""
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                             # [BH, Tq]
    return jnp.broadcast_to(delta[..., None], (*delta.shape, 8))


def _flash_bwd_dq(q, k, v, do, lse, delta, scale, causal, bq, bk,
                  q_off=0, k_off=0, static_skip=True):
    BH, Tq, D = q.shape
    nq, nk = Tq // bq, k.shape[1] // bk
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, static_skip=static_skip),
        grid=(BH, nq, nk),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # do
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((BH, Tq, D), q.dtype, q, k, v, do, lse,
                              delta, q_off, k_off),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(_as_scalar(q_off), _as_scalar(k_off), q, k, v, do, lse, delta)


def _flash_bwd_dkv(q, k, v, do, lse, delta, scale, causal, bq, bk,
                   q_off=0, k_off=0, static_skip=True):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // bq, Tk // bk
    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, static_skip=static_skip),
        grid=(BH, nk, nq),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),   # do
            pl.BlockSpec((1, bq, 8), lambda b, j, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, 8), lambda b, j, i: (b, i, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((BH, Tk, D), k.dtype, q, k, v, do, lse, delta,
                        q_off, k_off),
            _out_struct((BH, Tk, D), v.dtype, q, k, v, do, lse, delta,
                        q_off, k_off),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(_as_scalar(q_off), _as_scalar(k_off), q, k, v, do, lse, delta)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, bq, bk):
    delta = _prep_residuals(o, do)
    dq = _flash_bwd_dq(q, k, v, do, lse, delta, scale, causal, bq, bk)
    dk, dv = _flash_bwd_dkv(q, k, v, do, lse, delta, scale, causal, bq, bk)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _pick_block(T: int, preferred: int) -> Optional[int]:
    """Largest legal block size for a sequence of length T.

    T <= preferred: the whole sequence is one block (block dims equal to
    the array dims are always accepted by Mosaic, aligned or not).
    Otherwise the largest multiple of 128 <= preferred that divides T.
    None -> no legal blocking; caller falls back to the dense path.
    """
    if T <= preferred:
        return T
    for b in range(preferred - preferred % 128, 127, -128):
        if T % b == 0:
            return b
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, bq, bk):
    o, _ = _flash_fwd(q, k, v, scale, causal, bq, bk)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, scale, causal, bq, bk)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, bq, bk, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, g, scale, causal, bq, bk)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# ring composition: sequence-parallel flash attention
# ---------------------------------------------------------------------------


def _pack(x):
    """[B, T, H, D] → [B·H, T, D]."""
    B, T, H, D = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, T, D)


def _unpack(x, B, H):
    BH, T, D = x.shape
    return jnp.transpose(x.reshape(B, H, T, D), (0, 2, 1, 3))


def _ring_axes(axis, *tensors):
    from .collective_ops import _vma

    ring = {axis} if isinstance(axis, str) else set(axis)
    extra = frozenset().union(*[_vma(t) for t in tensors])
    return tuple(sorted(ring | extra))


def _ring_fwd_impl(q, k, v, axis, scale, causal, bq, bk):
    """Packed [BH, T_local, D] ring forward → (o f32, merged lse [BH, T])."""
    from jax import lax

    from ..parallel.sequence import _axis_size
    from .collective_ops import pvary_missing

    n = _axis_size(axis)
    my = lax.axis_index(axis)
    T_local = q.shape[1]
    perm = [(r, (r + 1) % n) for r in range(n)]
    axes_t = _ring_axes(axis, q, k, v)

    def _vary(x):
        return pvary_missing(x, axes_t)

    def merge(o, lse, k_blk, v_blk, i):
        # Blocks travel +1 per rotation: after i steps we hold (my - i)'s.
        src = (my - i) % n

        def compute(k_blk, v_blk):
            o_i, lse_i = _flash_fwd(
                q, k_blk, v_blk, scale, causal, bq, bk,
                q_off=my * T_local, k_off=src * T_local, static_skip=False)
            return o_i.astype(jnp.float32), lse_i[:, :, 0]  # [BH,T,D],[BH,T]

        if causal:
            # A block from a later shard (src > my) is entirely in the
            # causal future: skip the whole kernel call on this chip —
            # roughly half the ring steps cost nothing.
            def empty(k_blk, v_blk):
                return (_vary(jnp.zeros(q.shape, jnp.float32)),
                        _vary(jnp.full(q.shape[:2], _NEG_INF, jnp.float32)))

            o_i, lse_i = lax.cond(src > my, empty, compute, k_blk, v_blk)
        else:
            o_i, lse_i = compute(k_blk, v_blk)
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_i - lse_new)[..., None]
        return o * w_old + o_i * w_new, lse_new

    def step(carry, i):
        o, lse, k_blk, v_blk = carry
        o, lse = merge(o, lse, k_blk, v_blk, i)
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (o, lse, k_blk, v_blk), None

    o0 = _vary(jnp.zeros(q.shape, jnp.float32))
    lse0 = _vary(jnp.full(q.shape[:2], _NEG_INF, jnp.float32))
    # Last iteration peeled: its rotation result would be discarded, and
    # for n=1 the scan is empty and no ppermute is emitted at all.
    (o, lse, k_blk, v_blk), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(n - 1))
    o, lse = merge(o, lse, k_blk, v_blk, n - 1)
    return o, lse


def _ring_bwd_impl(q, k, v, o, lse, do, axis, scale, causal, bq, bk):
    """Ring backward: dq accumulates locally; dk/dv accumulators travel the
    ring WITH their k/v blocks and arrive home after n rotations."""
    from jax import lax

    from ..parallel.sequence import _axis_size
    from .collective_ops import pvary_missing

    n = _axis_size(axis)
    my = lax.axis_index(axis)
    T_local = q.shape[1]
    perm = [(r, (r + 1) % n) for r in range(n)]
    axes_t = _ring_axes(axis, q, k, v, o, lse, do)

    def _vary(x):
        return pvary_missing(x, axes_t)

    lse8 = jnp.broadcast_to(lse[..., None], (*lse.shape, 8))
    delta = _prep_residuals(o, do)

    def contrib(dq, k_blk, v_blk, dk_blk, dv_blk, i):
        src = (my - i) % n

        def compute(k_blk, v_blk):
            q_off, k_off = my * T_local, src * T_local
            dq_i = _flash_bwd_dq(q, k_blk, v_blk, do, lse8, delta, scale,
                                 causal, bq, bk, q_off=q_off, k_off=k_off,
                                 static_skip=False)
            dk_i, dv_i = _flash_bwd_dkv(q, k_blk, v_blk, do, lse8, delta,
                                        scale, causal, bq, bk, q_off=q_off,
                                        k_off=k_off, static_skip=False)
            return (dq_i.astype(jnp.float32), dk_i.astype(jnp.float32),
                    dv_i.astype(jnp.float32))

        if causal:
            # Fully-future block: no gradient flows either way — skip both
            # kernels on this chip (mirrors the forward's host-side skip).
            def empty(k_blk, v_blk):
                zero = lambda x: _vary(jnp.zeros(x.shape, jnp.float32))
                return zero(q), zero(k_blk), zero(v_blk)

            dq_i, dk_i, dv_i = lax.cond(src > my, empty, compute,
                                        k_blk, v_blk)
        else:
            dq_i, dk_i, dv_i = compute(k_blk, v_blk)
        return dq + dq_i, dk_blk + dk_i, dv_blk + dv_i

    def step(carry, i):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        dq, dk_blk, dv_blk = contrib(dq, k_blk, v_blk, dk_blk, dv_blk, i)
        # dk/dv accumulators travel with their blocks; k/v feed the next
        # step's kernels.
        dk_blk = lax.ppermute(dk_blk, axis, perm)
        dv_blk = lax.ppermute(dv_blk, axis, perm)
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    zeros = lambda x: _vary(jnp.zeros(x.shape, jnp.float32))
    # Last iteration peeled: dk/dv still need their final hop home, but
    # the k/v rotation result would be discarded.
    (dq, k_blk, v_blk, dk_blk, dv_blk), _ = jax.lax.scan(
        step, (zeros(q), k, v, zeros(k), zeros(v)), jnp.arange(n - 1))
    dq, dk_blk, dv_blk = contrib(dq, k_blk, v_blk, dk_blk, dv_blk, n - 1)
    dk = lax.ppermute(dk_blk, axis, perm)
    dv = lax.ppermute(dv_blk, axis, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring(q, k, v, axis, scale, causal, bq, bk):
    o, _ = _ring_fwd_impl(q, k, v, axis, scale, causal, bq, bk)
    return o.astype(q.dtype)


def _ring_vjp_fwd(q, k, v, axis, scale, causal, bq, bk):
    o, lse = _ring_fwd_impl(q, k, v, axis, scale, causal, bq, bk)
    o = o.astype(q.dtype)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis, scale, causal, bq, bk, res, g):
    q, k, v, o, lse = res
    return _ring_bwd_impl(q, k, v, o, lse, g, axis, scale, causal, bq, bk)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def flash_ring_attention(q, k, v, *, axis, causal: bool = True,
                         scale: Optional[float] = None,
                         block_q: int = _DEF_BLOCK_Q,
                         block_k: int = _DEF_BLOCK_K):
    """Sequence-parallel exact attention: flash kernels on a ppermute ring.

    The fused long-context path — each chip holds a contiguous
    [B, T/n, H, D] sequence shard; K/V blocks rotate around the mesh axis
    (``lax.ppermute`` riding ICI neighbours) and every ring step runs the
    Pallas flash kernel with GLOBAL causal positions, merging partial
    outputs by logsumexp. Backward replays the ring with the dq/dk/dv
    kernels; dk/dv accumulators travel with their blocks and arrive home
    after n rotations. Combines :func:`ring_attention`'s O(T/n) per-chip
    sequence memory with the flash kernel's VMEM-resident scores (the XLA
    ring materializes [T/n, T/n] f32 score tiles in HBM each step).

    Same layout/semantics as :func:`ring_attention`; must run inside
    ``jax.shard_map`` with the sequence sharded on ``axis``.
    """
    from ..parallel.sequence import _axis_size

    B, T_local, H, D = q.shape
    n = _axis_size(axis)
    if n == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k)
    if isinstance(axis, list):
        axis = tuple(axis)  # hashable for the custom_vjp nondiff arg
    if block_q < 128 or block_k < 128:
        raise ValueError(
            f"block_q/block_k must be >= 128 (MXU/lane tile), got "
            f"{block_q}/{block_k}")
    bq, bk = _pick_block(T_local, block_q), _pick_block(T_local, block_k)
    if bq is None or bk is None:
        from ..parallel.sequence import ring_attention

        return ring_attention(q, k, v, axis=axis, causal=causal,
                              scale=scale)
    scale_f = float(scale) if scale is not None else D ** -0.5
    o = _ring(_pack(q), _pack(k), _pack(v), axis, scale_f, causal, bq, bk)
    return _unpack(o, B, H)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Exact attention with the flash schedule. Layout [B, T, H, D].

    Differentiable (custom VJP with Pallas backward kernels). Block sizes
    shrink to a divisor of the sequence when needed (a single whole-sequence
    block is always legal — Mosaic accepts block dims equal to the array
    dim); only a long sequence with no 128-aligned divisor falls back to
    the dense path — numerics are identical either way.

    ``block_q``/``block_k`` default to the kernel autotuner's choice for
    this (shape, chip) — swept once, cached on disk
    (ops/kernel_autotune.py) — unless the ``HOROVOD_FLASH_BLOCK_Q/K``
    knobs pin them or the caller passes explicit values.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if causal and Tq != Tk:
        raise ValueError(
            f"causal flash attention needs Tq == Tk, got {Tq} != {Tk}")
    if block_q is None and block_k is None:
        block_q, block_k = _resolve_blocks(B, Tq, Tk, H, D, q.dtype,
                                           causal)
    else:
        block_q = _DEF_BLOCK_Q if block_q is None else block_q
        block_k = _DEF_BLOCK_K if block_k is None else block_k
    if block_q < 128 or block_k < 128:
        raise ValueError(
            f"block_q/block_k must be >= 128 (MXU/lane tile), got "
            f"{block_q}/{block_k}")
    bq, bk = _pick_block(Tq, block_q), _pick_block(Tk, block_k)
    if bq is None or bk is None:
        from ..parallel.sequence import dense_attention

        return dense_attention(q, k, v, causal=causal, scale=scale)
    scale = float(scale) if scale is not None else D ** -0.5

    qp, kp, vp = _harmonize_vma(_pack(q), _pack(k), _pack(v))
    o = _flash(qp, kp, vp, scale, causal, bq, bk)
    return jnp.transpose(o.reshape(B, H, Tq, D), (0, 2, 1, 3))
