"""Pallas TPU flash attention: exact attention without the [T, T] round-trip.

The reference framework has no attention kernels at all (it is a CNN-era
data-parallel framework, SURVEY §5.7); its GPU analogue would be a fused
CUDA kernel. On TPU the hot op is the attention score/softmax/value chain:
``dense_attention`` (parallel/sequence.py) materializes a [B, H, T, T] fp32
score tensor twice (scores + probabilities) — at GPT-124M bench shapes
(B=16, H=12, T=1024) that is ~1.6 GB of HBM round-trip per layer, which
dwarfs the matmul time on a bandwidth-limited chip.

This module implements the standard flash-attention schedule as Pallas TPU
kernels (guide: /opt/skills/guides/pallas_guide.md):

* forward: grid (B·H, Tq/bq, Tk/bk); the k-block axis is innermost, so the
  per-q-block running max ``m``, normalizer ``l`` and output accumulator
  live in VMEM scratch across k-steps; scores never leave VMEM. Emits the
  logsumexp residual for the backward pass.
* backward: the split-kernel formulation — one kernel accumulates dQ over
  k-blocks, a second accumulates dK/dV over q-blocks — with the
  ``delta = rowsum(dO ⊙ O)`` precomputed as a cheap fused elementwise op
  in plain XLA. Both kernels recompute probabilities from q, k and the
  saved logsumexp (recompute-over-store: O(T·D) residuals instead of
  O(T²)).
* causal masking skips fully-masked k-blocks via ``pl.when`` (upper
  triangle costs nothing), and the MXU sees only [bq, bk] = [128, 128]
  tiles.

Everything is static-shaped; block sizes adapt to divide the sequence
(see ``_pick_block`` — a whole-sequence block covers anything <= the
preferred block, and long sequences with no 128-aligned divisor fall back
to the dense path). Off-TPU the kernels run in Pallas interpreter mode so
the CPU test suite exercises the identical code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU backend)

_NEG_INF = -1e30  # finite: keeps running-max arithmetic NaN-free

# Large blocks amortize Mosaic's per-grid-cell overhead (a [512, 512] score
# tile is ~1 MB of VMEM f32 — far under the ~16 MB budget together with the
# q/k/v/o blocks) and give the MXU deep work per cell; measured on v5e they
# are the difference between losing to the dense path and beating it.
_DEF_BLOCK_Q = 512
_DEF_BLOCK_K = 512


def _interpret() -> bool:
    """Run in interpreter mode off-TPU (CPU test suite)."""
    return jax.default_backend() != "tpu"


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-manual-axes are the union of the
    operands' — required inside ``jax.shard_map`` (check_vma), harmless
    outside (vma=frozenset())."""
    from .collective_ops import _vma

    vma = frozenset().union(*[_vma(x) for x in operands])
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, bq, bk, nk):
    i = pl.program_id(1)   # q block
    j = pl.program_id(2)   # k block (innermost: scratch carries across j)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: k block j overlaps the allowed triangle of q block i iff its
    # first key position <= the block's last query position.
    run = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]                                    # [bq, D]
        k = k_ref[0]                                    # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)

        m_prev = m_scr[:, 0:1]                          # [bq, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    j_last = jnp.minimum(nk - 1, (i * bq + bq - 1) // bk) if causal \
        else nk - 1

    @pl.when(j == j_last)
    def _finish():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        # Causal rows always see their own token so l > 0; for non-causal
        # the same holds (no masked rows).
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lse carries a sublane dim of 8 (Mosaic block-mapping minimum for
        # the trailing-two dims); value broadcast across it.
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal, bq, bk):
    """q,k,v: [BH, T, D] → (o [BH, Tq, D], lse [BH, Tq] f32)."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // bq, Tk // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((BH, Tq, D), q.dtype, q, k, v),
            _out_struct((BH, Tq, 8), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running normalizer l
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale, causal, bq, bk, nk):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, 0:1])             # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0, :, 0:1])
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    j_last = jnp.minimum(nk - 1, (i * bq + bq - 1) // bk) if causal \
        else nk - 1

    @pl.when(j == j_last)
    def _finish():
        dq_ref[0] = (acc_scr[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, bq, bk, nq):
    j = pl.program_id(1)   # k block
    i = pl.program_id(2)   # q block (innermost: scratch carries across i)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (i * bq + bq - 1 >= j * bk) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, 0:1])              # [bq, bk]
        do = do_ref[0]                                   # [bq, D]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0, :, 0:1])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, bq, bk):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // bq, Tk // bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                             # [BH, Tq]
    # lse/delta ride a broadcast sublane dim of 8 (block-mapping minimum).
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 8))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # do
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((BH, Tq, D), q.dtype, q, k, v, do, lse, delta),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),   # do
            pl.BlockSpec((1, bq, 8), lambda b, j, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, 8), lambda b, j, i: (b, i, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((BH, Tk, D), k.dtype, q, k, v, do, lse, delta),
            _out_struct((BH, Tk, D), v.dtype, q, k, v, do, lse, delta),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _pick_block(T: int, preferred: int) -> Optional[int]:
    """Largest legal block size for a sequence of length T.

    T <= preferred: the whole sequence is one block (block dims equal to
    the array dims are always accepted by Mosaic, aligned or not).
    Otherwise the largest multiple of 128 <= preferred that divides T.
    None -> no legal blocking; caller falls back to the dense path.
    """
    if T <= preferred:
        return T
    for b in range(preferred - preferred % 128, 127, -128):
        if T % b == 0:
            return b
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, bq, bk):
    o, _ = _flash_fwd(q, k, v, scale, causal, bq, bk)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, scale, causal, bq, bk)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, bq, bk, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, g, scale, causal, bq, bk)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = _DEF_BLOCK_Q,
                    block_k: int = _DEF_BLOCK_K):
    """Exact attention with the flash schedule. Layout [B, T, H, D].

    Differentiable (custom VJP with Pallas backward kernels). Block sizes
    shrink to a divisor of the sequence when needed (a single whole-sequence
    block is always legal — Mosaic accepts block dims equal to the array
    dim); only a long sequence with no 128-aligned divisor falls back to
    the dense path — numerics are identical either way.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if causal and Tq != Tk:
        raise ValueError(
            f"causal flash attention needs Tq == Tk, got {Tq} != {Tk}")
    if block_q < 128 or block_k < 128:
        raise ValueError(
            f"block_q/block_k must be >= 128 (MXU/lane tile), got "
            f"{block_q}/{block_k}")
    bq, bk = _pick_block(Tq, block_q), _pick_block(Tk, block_k)
    if bq is None or bk is None:
        from ..parallel.sequence import dense_attention

        return dense_attention(q, k, v, causal=causal, scale=scale)
    scale = float(scale) if scale is not None else D ** -0.5

    # [B, T, H, D] → [B·H, T, D]
    def pack(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, x.shape[1], D)

    o = _flash(pack(q), pack(k), pack(v), scale, causal, bq, bk)
    return jnp.transpose(o.reshape(B, H, Tq, D), (0, 2, 1, 3))
