"""Object broadcast/gather helpers for MXNet.

Reference: ``horovod/mxnet/functions.py`` — ``broadcast_object`` /
``allgather_object`` ship pickled payloads as byte NDArrays. Here the
framing rides the shared byte-transport protocol
(``horovod_tpu/common/object_transport.py``); this module only supplies the
pickle serializer.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional

from ..common.object_transport import allgather_bytes, broadcast_bytes
from ..ops import collective_ops as C
from . import mpi_ops


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Pickle ``obj`` on the root and broadcast it (reference:
    mxnet/functions.py broadcast_object)."""
    name = name or "mx.broadcast_object"
    if C._eager_world() == 1:
        return obj
    data = pickle.dumps(obj) if mpi_ops.rank() == root_rank else None
    return pickle.loads(broadcast_bytes(data, root_rank, name))


def allgather_object(obj: Any, name: Optional[str] = None) -> List[Any]:
    """Gather a picklable object from every rank (reference:
    mxnet/functions.py allgather_object)."""
    name = name or "mx.allgather_object"
    if C._eager_world() == 1:
        return [obj]
    return [pickle.loads(b) for b in
            allgather_bytes(pickle.dumps(obj), name)]
