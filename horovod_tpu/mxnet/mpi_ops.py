"""MXNet collective ops over the native control plane.

Reference surface: ``horovod/mxnet/mpi_ops.py`` (allreduce/allreduce_:54-143,
allgather:145-183, broadcast/broadcast_:185-259, alltoall:261-300) backed by
``mxnet/mpi_ops.cc:426`` per-dtype C++ ops pushed onto the MXNet engine.

TPU-native redesign: like torch (horovod_tpu/torch/mpi_ops.py), mxnet is a
*host* framework here — NDArrays cross into numpy and ride the same native
C++ controller + TCP data plane (horovod_tpu/cc/) the eager JAX API uses, so
an mxnet script participates in the same world as JAX/torch processes. The
reference's engine-async dispatch (return immediately, engine tracks the
write dependency) is replaced by synchronous completion: the native
background loop already overlaps negotiation with compute, and NDArray has
no external dependency-tracking hook to attach to.

``priority`` is accepted for API parity and forwarded as a negotiation-order
hint only (the reference uses it to order engine pushes).
"""

from __future__ import annotations

import zlib
from typing import List, Optional

import numpy as np

from ..common import basics
from ..ops import collective_ops as C
from ..ops.collective_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT

__all__ = [
    "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "allreduce", "allreduce_", "allgather", "broadcast", "broadcast_",
    "alltoall", "rank", "size", "local_rank", "local_size",
    "grouped_allreduce_", "batched_broadcast_",
]


def rank() -> int:
    """Process rank in the eager/native world (mxnet is a host framework:
    one rank per worker process, like the torch binding — NOT the
    single-controller SPMD device count ``basics.size()`` reports).
    Without a native controller the jax.distributed process index is the
    rank, keeping rank()/size() mutually consistent with ``_eager_world``'s
    process_count fallback."""
    s = basics._require_init()
    return int(s.controller.rank()) if s.controller is not None \
        else int(s.process_index)


def size() -> int:
    """World size of the eager/native world (see ``rank``)."""
    return int(C._eager_world())


def local_rank() -> int:
    ctrl = C._controller()
    return int(ctrl.local_rank()) if ctrl is not None else 0


def local_size() -> int:
    ctrl = C._controller()
    return int(ctrl.local_size()) if ctrl is not None else 1


# --------------------------------------------------------------------------
# NDArray <-> numpy bridge
# --------------------------------------------------------------------------


def _nd():
    import mxnet as mx

    return mx.nd


def _to_numpy(tensor) -> np.ndarray:
    """Materialize an NDArray as a contiguous numpy array. ``asnumpy()``
    waits on the engine, so every pending mutation is visible."""
    return np.ascontiguousarray(tensor.asnumpy())


def _write_back(tensor, arr: np.ndarray):
    """Write a numpy result into an existing NDArray in place."""
    tensor[:] = arr.reshape(tensor.shape)
    return tensor


def _ctrl_ctx():
    return C._eager_ctx()


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------


def _allreduce_numpy(arr: np.ndarray, average: bool, name: Optional[str],
                     prescale_factor: float, postscale_factor: float
                     ) -> np.ndarray:
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "mx.allreduce")
    if world == 1:
        scale = prescale_factor * postscale_factor
        return arr if scale == 1.0 else arr * scale
    post = postscale_factor / world if average else postscale_factor
    handle = ctrl.allreduce_async(arr, opname, op=ctrl.SUM,
                                  prescale=float(prescale_factor),
                                  postscale=float(post))
    return handle.wait()


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              priority: int = 0, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    """Allreduce into a fresh NDArray (reference: mxnet/mpi_ops.py:54-101)."""
    out = _allreduce_numpy(_to_numpy(tensor), average, name,
                           prescale_factor, postscale_factor)
    return _nd().array(out.reshape(tensor.shape), dtype=out.dtype)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None,
               priority: int = 0, prescale_factor: float = 1.0,
               postscale_factor: float = 1.0):
    """In-place allreduce (reference: mxnet/mpi_ops.py:103-143)."""
    out = _allreduce_numpy(_to_numpy(tensor), average, name,
                           prescale_factor, postscale_factor)
    return _write_back(tensor, out)


# --------------------------------------------------------------------------
# allgather
# --------------------------------------------------------------------------


def allgather(tensor, name: Optional[str] = None, priority: int = 0):
    """First-dim concatenation across ranks; ranks may differ in dim 0
    (reference: mxnet/mpi_ops.py:145-183)."""
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "mx.allgather")
    arr = _to_numpy(tensor)
    if world == 1:
        return _nd().array(arr, dtype=arr.dtype)
    out = ctrl.allgather_async(arr, opname).wait()
    return _nd().array(out, dtype=out.dtype)


# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              priority: int = 0):
    """Broadcast into a fresh NDArray (reference: mxnet/mpi_ops.py:185-226)."""
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "mx.broadcast")
    arr = _to_numpy(tensor)
    if world > 1:
        arr = ctrl.broadcast_async(arr, opname, root=root_rank).wait()
    return _nd().array(arr.reshape(tensor.shape), dtype=arr.dtype)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None,
               priority: int = 0):
    """In-place broadcast (reference: mxnet/mpi_ops.py:228-259)."""
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "mx.broadcast")
    if world == 1:
        return tensor
    out = ctrl.broadcast_async(_to_numpy(tensor), opname,
                               root=root_rank).wait()
    return _write_back(tensor, out)


def batched_broadcast_(tensors_and_names, root_rank: int) -> None:
    """Start every broadcast before waiting on any (the torch binding's
    batched shape, torch/functions.py:30-40): N serialized
    negotiate+transfer round trips collapse into one pipelined batch."""
    ctrl, world = _ctrl_ctx()
    if world == 1:
        return
    handles = [(tensor, ctrl.broadcast_async(_to_numpy(tensor), name,
                                             root=root_rank))
               for tensor, name in tensors_and_names]
    for tensor, handle in handles:
        _write_back(tensor, handle.wait())


def grouped_allreduce_(tensors_and_names, average: bool = True,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0) -> None:
    """In-place allreduce of a whole gradient batch, packed into ONE flat
    wire buffer (and one negotiation) per dtype — the same packing the JAX
    eager path uses (ops/collective_ops.py ``_eager_grouped_allreduce``).
    This is the repo's answer to the reference's per-tensor
    engine-priority hints (mxnet/mpi_ops.cc pushes with ``priority``):
    with a synchronous bridge, the win comes from collapsing O(params)
    controller round trips into O(dtypes), not from engine scheduling.

    The group's wire name is derived from the member names (order and
    membership are deterministic across ranks: optimizer indices /
    parameter positions), so ranks negotiate the packed buffer, not the
    individual tensors. World-1 still applies prescale*postscale so the
    factors callers fold elsewhere (e.g. ``rescale_grad``) cancel exactly
    as they do at world>1."""
    if not tensors_and_names:
        return
    ctrl, world = _ctrl_ctx()
    if world == 1:
        scale = prescale_factor * postscale_factor
        if scale != 1.0:
            for tensor, _ in tensors_and_names:
                _write_back(tensor, _to_numpy(tensor) * scale)
        return
    post = postscale_factor / world if average else postscale_factor
    arrs = [_to_numpy(t) for t, _ in tensors_and_names]
    by_dtype: dict = {}
    for i, arr in enumerate(arrs):
        by_dtype.setdefault(arr.dtype, []).append(i)
    handles = []
    for dt, idxs in by_dtype.items():
        flat = np.concatenate([arrs[i].ravel() for i in idxs])
        member_names = "\0".join(tensors_and_names[i][1] for i in idxs)
        tag = zlib.crc32(member_names.encode())
        wire = f"mx.group.{dt.name}.{len(idxs)}.{tag:08x}"
        handles.append((idxs, ctrl.allreduce_async(
            flat, wire, op=ctrl.SUM, prescale=float(prescale_factor),
            postscale=float(post))))
    for idxs, handle in handles:
        buf = handle.wait()
        offset = 0
        for i in idxs:
            n = arrs[i].size
            _write_back(tensors_and_names[i][0],
                        buf[offset:offset + n].reshape(arrs[i].shape))
            offset += n


# --------------------------------------------------------------------------
# alltoall
# --------------------------------------------------------------------------


def alltoall(tensor, splits=None, name: Optional[str] = None,
             priority: int = 0):
    """Alltoall with optional uneven splits; returns the output NDArray
    (reference: mxnet/mpi_ops.py:261-300)."""
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "mx.alltoall")
    arr = _to_numpy(tensor)
    if world == 1:
        return _nd().array(arr, dtype=arr.dtype)
    sp: Optional[List[int]] = None
    if splits is not None:
        sp = [int(x) for x in
              (splits.asnumpy() if hasattr(splits, "asnumpy") else splits)]
    out = ctrl.alltoall_async(arr, opname, splits=sp).wait()
    return _nd().array(out, dtype=out.dtype)
