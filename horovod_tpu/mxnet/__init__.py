"""MXNet binding for horovod_tpu.

Reference surface: ``horovod/mxnet/__init__.py:39-140`` —
``DistributedOptimizer`` (rescale_grad folded averaging, per-index
allreduce), gluon ``DistributedTrainer`` (_allreduce_grads over the native
collectives instead of kvstore push/pull), ``broadcast_parameters`` with
deferred-initialization injection — plus the mpi_ops/functions re-exports.

TPU-native design: mxnet is a host framework here, like torch — NDArrays
bridge to numpy and ride the native C++ controller + TCP data plane
(horovod_tpu/cc/), so mxnet processes join the same world as JAX/torch/TF
processes. MXNet is EOL upstream and not installable in this image; the
binding is exercised against the minimal NDArray shim in
``tests/fake_mxnet.py``, the same strategy as the Ray integration
(tests/fake_ray.py). The shim pins the exact mxnet API surface used here.
"""

from __future__ import annotations

import types
import warnings

try:
    import mxnet as mx
except ImportError as e:  # pragma: no cover - exercised via fake_mxnet
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet (EOL upstream; not in this "
        "image). The binding is testable against tests/fake_mxnet.py. Use "
        "the JAX (horovod_tpu), PyTorch (horovod_tpu.torch), TensorFlow "
        "(horovod_tpu.tensorflow), or Keras (horovod_tpu.keras) surfaces "
        "for installed frameworks.") from e

from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    mpi_threads_supported,
    shutdown,
)
from .functions import allgather_object, broadcast_object  # noqa: F401
from .mpi_ops import (  # noqa: F401
    local_rank,
    local_size,
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    allreduce_,
    alltoall,
    broadcast,
    broadcast_,
    rank,
    size,
)


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Optimizer wrapper: allreduce-sum each gradient before the wrapped
    optimizer's update, with the 1/size average folded into the optimizer's
    ``rescale_grad`` (reference: mxnet/__init__.py:39-84 — folding the
    average into rescale_grad beats a separate postscale pass)."""

    def __init__(self, optimizer, gradient_predivide_factor: float = 1.0):
        self._optimizer = optimizer
        self._optimizer.rescale_grad *= gradient_predivide_factor / size()
        self._gradient_predivide_factor = gradient_predivide_factor

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], average=False, name=str(index[i]),
                           priority=-i,
                           prescale_factor=1.0 /
                           self._gradient_predivide_factor)
        else:
            allreduce_(grad, average=False, name=str(index),
                       prescale_factor=1.0 /
                       self._gradient_predivide_factor)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer whose ``_allreduce_grads`` rides the native collectives
    instead of kvstore push/pull, averaging via the trainer's ``_scale``
    (reference: mxnet/__init__.py:87-140). ``prefix`` namespaces tensor
    names when several trainers coexist (MXNet 2.0 param names are not
    unique)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor: float = 1.0, prefix=None):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
            warnings.warn("DistributedTrainer does not take "
                          "DistributedOptimizer as its optimizer. We have "
                          "unwrapped it for you.")
        super().__init__(params, optimizer, optimizer_params=optimizer_params,
                         kvstore=None)
        self._scale *= gradient_predivide_factor / size()
        self._gradient_predivide_factor = gradient_predivide_factor
        assert prefix is None or isinstance(prefix, str)
        self._prefix = prefix if prefix else ""

    def _allreduce_grads(self):
        if size() == 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                allreduce_(param.list_grad()[0], average=False,
                           name=self._prefix + str(i), priority=-i,
                           prescale_factor=1.0 /
                           self._gradient_predivide_factor)


def _append_broadcast_init(param, root_rank: int, name: str):
    """Wrap a deferred-init parameter's ``_init_impl`` so the broadcast runs
    right after the parameter materializes (reference:
    mxnet/__init__.py:143-149)."""
    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=name)

    return wrapped_init_impl


def broadcast_parameters(params, root_rank: int = 0, prefix=None) -> None:
    """Broadcast a dict/ParameterDict of parameters from ``root_rank``;
    deferred-initialization parameters get the broadcast injected after
    their init (reference: mxnet/__init__.py:152-195)."""
    if size() == 1:
        return

    tensors, names = [], []
    assert prefix is None or isinstance(prefix, str)
    prefix = prefix if prefix else ""
    try:
        from mxnet.gluon.parameter import ParameterDict

        valid_types = (dict, ParameterDict)
    except ImportError:  # MXNet 2.0 dropped ParameterDict
        valid_types = (dict,)
    if not isinstance(params, valid_types):
        raise ValueError(f"invalid params of type: {type(params)}")
    for name, p in sorted(params.items()):
        try:
            if isinstance(p, mx.gluon.parameter.Parameter):
                tensors.append(p.data())
            else:
                tensors.append(p)
            names.append(prefix + str(name))
        except mx.gluon.parameter.DeferredInitializationError:
            new_init = _append_broadcast_init(p, root_rank,
                                              prefix + str(name))
            p._init_impl = types.MethodType(new_init, p)

    from .mpi_ops import batched_broadcast_

    batched_broadcast_(list(zip(tensors, names)), root_rank)
