"""MXNet binding gate.

The reference ships an MXNet binding (horovod/mxnet/: NDArray adapters,
DistributedOptimizer, gluon DistributedTrainer, broadcast_parameters —
mxnet/__init__.py:39-140). MXNet reached end-of-life upstream and is not in
this image; the binding surface is declared here so `import
horovod_tpu.mxnet` fails with guidance instead of AttributeError soup.

If mxnet is installed, the same recipe as the torch binding applies:
NDArray ↔ numpy is zero-copy on CPU, and collectives ride the native
control plane (horovod_tpu/cc/). Contributions would mirror
horovod_tpu/torch/{mpi_ops,optimizer,functions}.py.
"""

try:
    import mxnet  # noqa: F401
except ImportError as e:
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet, which is not installed (MXNet "
        "is EOL upstream). Use the JAX (horovod_tpu), PyTorch "
        "(horovod_tpu.torch), TensorFlow (horovod_tpu.tensorflow), or "
        "Keras (horovod_tpu.keras) surfaces instead.") from e
