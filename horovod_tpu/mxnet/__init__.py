"""MXNet binding for horovod_tpu — high-level training wrappers.

Capability parity target: ``horovod/mxnet/__init__.py`` — an optimizer
wrapper that averages gradients across the world before each update, a
gluon Trainer whose gradient sync rides the collective API instead of
kvstore push/pull, and a parameter broadcast that also covers
deferred-initialization (shape-inferred) gluon parameters. The
implementation below is derived from that capability spec, not from the
reference's code: gradient sync goes through the repo's *grouped* eager
path (``mpi_ops.grouped_allreduce_`` — launch every async handle, then
wait; the batching provides the overlap the reference gets from per-tensor
engine-priority hints), and deferred-init parameters get a plain-closure
post-materialization hook rather than a rebound method.

TPU-native design: mxnet is a host framework here, like torch — NDArrays
bridge to numpy and ride the native C++ controller + TCP data plane
(horovod_tpu/cc/), so mxnet processes join the same world as JAX/torch/TF
processes. MXNet is EOL upstream and not installable in this image; the
binding is exercised against the minimal NDArray shim in
``tests/fake_mxnet.py``, the same strategy as the Ray integration
(tests/fake_ray.py). The shim pins the exact mxnet API surface used here.
"""

from __future__ import annotations

import warnings

try:
    import mxnet as mx
except ImportError as e:  # pragma: no cover - exercised via fake_mxnet
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet (EOL upstream; not in this "
        "image). The binding is testable against tests/fake_mxnet.py. Use "
        "the JAX (horovod_tpu), PyTorch (horovod_tpu.torch), TensorFlow "
        "(horovod_tpu.tensorflow), or Keras (horovod_tpu.keras) surfaces "
        "for installed frameworks.") from e

from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    mpi_threads_supported,
    shutdown,
)
from .functions import allgather_object, broadcast_object  # noqa: F401
from .mpi_ops import (  # noqa: F401
    local_rank,
    local_size,
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    allreduce_,
    alltoall,
    batched_broadcast_,
    broadcast,
    broadcast_,
    grouped_allreduce_,
    rank,
    size,
)


def _fold_average_into_rescale(predivide: float) -> float:
    """The collective path sums; the 1/world average (and the post-sum half
    of the predivide split) is cheapest folded into the optimizer's own
    ``rescale_grad`` multiplier, which mxnet applies once per update anyway.
    Returns the factor to multiply ``rescale_grad`` by."""
    return predivide / size()


def _grad_batch(index, grad):
    """Normalize mxnet's update signature — a single (index, grad) pair or
    parallel sequences of them — into a list of (tensor, wire-name) pairs
    for the grouped collective. Wire names are the optimizer indices, the
    only identifier mxnet guarantees stable across ranks."""
    if isinstance(index, (tuple, list)):
        return [(g, str(i)) for i, g in zip(index, grad)]
    return [(grad, str(index))]


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Data-parallel wrapper around any ``mx.optimizer.Optimizer``: each
    ``update`` first sum-allreduces the gradient batch through the grouped
    eager path, with the world average folded into the wrapped optimizer's
    ``rescale_grad``.

    ``gradient_predivide_factor`` splits the averaging around the sum:
    gradients are scaled by ``1/f`` on the wire (prescale) and ``f/world``
    in ``rescale_grad`` after it — useful to keep the summed values in
    range for low-precision wire dtypes.
    """

    def __init__(self, base_optimizer, gradient_predivide_factor: float = 1.0):
        self._base = base_optimizer
        self._predivide = float(gradient_predivide_factor)
        self._base.rescale_grad *= _fold_average_into_rescale(self._predivide)

    # Everything not overridden below — lr/wd schedules, param dicts,
    # serialization — is the wrapped optimizer's business.
    def __getattr__(self, item):
        if item == "_base":  # pre-__init__ probes (deepcopy/unpickle)
            raise AttributeError(item)
        return getattr(self._base, item)

    def _sync_gradients(self, index, grad) -> None:
        # No world-1 short-circuit: grouped_allreduce_ applies the 1/f
        # prescale there too, cancelling the f folded into rescale_grad.
        grouped_allreduce_(_grad_batch(index, grad), average=False,
                           prescale_factor=1.0 / self._predivide)

    def update(self, index, weight, grad, state):
        self._sync_gradients(index, grad)
        self._base.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._sync_gradients(index, grad)
        self._base.update_multi_precision(index, weight, grad, state)

    def create_state_multi_precision(self, index, weight):
        return self._base.create_state_multi_precision(index, weight)

    # mxnet mutates optimizer hyper-parameters through setters; route the
    # mutating surface explicitly so the wrapped instance is the single
    # source of truth.
    def set_learning_rate(self, lr):
        self._base.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._base.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._base.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer for data-parallel training: gradient sync happens in
    ``_allreduce_grads`` (gluon's designated hook) via one grouped
    sum-allreduce over every trainable parameter, and the world average
    rides the trainer's ``_scale`` — the multiplier ``Trainer.step``
    already applies to ``rescale_grad``.

    Wire names are parameter *positions* (mxnet 2.0 dropped unique
    parameter names), so when several trainers coexist in one process each
    MUST be given a distinct ``prefix`` — otherwise their wire names (and
    grouped buffer names) collide.
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor: float = 1.0, prefix=None):
        if isinstance(optimizer, DistributedOptimizer):
            warnings.warn(
                "DistributedTrainer handles the gradient sync itself and "
                "expects a plain mxnet optimizer; got DistributedOptimizer "
                "— it has been unwrapped to its inner optimizer.")
            optimizer = optimizer._base
        super().__init__(params, optimizer, optimizer_params=optimizer_params,
                         kvstore=None)
        self._predivide = float(gradient_predivide_factor)
        self._scale *= _fold_average_into_rescale(self._predivide)
        if prefix is not None and not isinstance(prefix, str):
            raise TypeError(f"prefix must be a str, got {type(prefix)}")
        self._wire_prefix = prefix or ""

    def _allreduce_grads(self):
        # No world-1 short-circuit — see DistributedOptimizer._sync_gradients.
        batch = [(p.list_grad()[0], f"{self._wire_prefix}{pos}")
                 for pos, p in enumerate(self._params)
                 if p.grad_req != "null"]
        grouped_allreduce_(batch, average=False,
                           prescale_factor=1.0 / self._predivide)


def _sync_param_after_init(param, root_rank: int, wire_name: str) -> None:
    """Arrange for a deferred-initialization parameter to be broadcast the
    moment it materializes: shadow the instance's ``_init_impl`` with a
    closure that runs the original and then broadcasts the fresh data.
    (``_init_impl`` is the one post-materialization hook mxnet offers;
    the shadowing closure needs no rebinding since it closes over the
    parameter itself.)"""
    materialize = param._init_impl

    def _init_then_broadcast(*args, **kwargs):
        materialize(*args, **kwargs)
        broadcast_(param.data(), root_rank=root_rank, name=wire_name)

    param._init_impl = _init_then_broadcast


def broadcast_parameters(params, root_rank: int = 0, prefix=None) -> None:
    """Broadcast a mapping of gluon parameters (``Block.collect_params()``,
    a plain dict of NDArrays, or mxnet 1.x's dict-subclass ParameterDict)
    from ``root_rank`` to every process.

    Parameters whose shape is still being inferred (gluon deferred
    initialization) cannot be broadcast yet; they get a
    post-materialization hook instead (see ``_sync_param_after_init``).
    Everything already materialized goes out as one batched broadcast.
    ``prefix`` namespaces wire names across multiple calls.
    """
    if size() == 1:
        return
    if not hasattr(params, "items"):
        raise ValueError(
            f"params must be a mapping (dict / ParameterDict / "
            f"collect_params() result), got {type(params)}")
    if prefix is not None and not isinstance(prefix, str):
        raise TypeError(f"prefix must be a str, got {type(prefix)}")
    tag = prefix or ""

    ready = []
    # Deterministic traversal order: every rank must enqueue the same wire
    # names in the same order for negotiation to line up.
    for key in sorted(params.keys()):
        value = params[key]
        wire_name = tag + str(key)
        if isinstance(value, mx.gluon.parameter.Parameter):
            try:
                ready.append((value.data(), wire_name))
            except mx.gluon.parameter.DeferredInitializationError:
                _sync_param_after_init(value, root_rank, wire_name)
        else:
            ready.append((value, wire_name))

    batched_broadcast_(ready, root_rank)
