"""The wire-plan IR: a collective as an ordered list of per-level legs.

Following HiCCL (arXiv:2408.05962), a collective over a machine hierarchy
is best expressed as a *composition of per-level primitives* rather than a
monolithic hand-written path: an allreduce over a TPU pod is an intra-host
reduce-scatter (ICI), a cross-host reduction (DCN), and an intra-host
all-gather — and a quantized allreduce (EQuARX, arXiv:2506.17615) is the
SAME composition with an int8 wire dtype attribute on the DCN hops, not a
separate code path.

The IR is deliberately tiny:

* a :class:`Leg` names a mesh **level** (``ici`` ring / ``dcn`` cross /
  ``pod`` axis, or ``flat`` for one XLA-decomposed collective over the
  whole axis tuple), a **primitive** (``reduce_scatter`` / ``all_gather``
  / ``all_to_all`` / ``psum``), a **wire dtype** (``payload`` or
  blockwise-``int8`` with an fp32 scale per ``block`` elements and an
  optional error-feedback slot), and a **stream** assignment;
* a :class:`WirePlan` is an ordered leg tuple plus the stream/overlap
  placement for the whole collective.

Plans are *validated data*, not code: :meth:`WirePlan.validate` rejects
illegal compositions (a reduce leg after the gather phase began, int8 on
a non-DCN hop, a non-power-of-two stream count) with actionable messages,
and the compiler (:mod:`horovod_tpu.plan.compiler`) lowers a validated
plan to the existing jax primitives. The planner
(:mod:`horovod_tpu.plan.planner`) derives the default plan from today's
knob set, so every (quantized, zero_stage, overlap, hierarchical) knob
combination is one point in plan space.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Mesh levels a leg can ride. ``flat`` is the degenerate single-leg plan:
# one collective over the whole axis tuple, letting XLA's topology-aware
# decomposition place the ICI/DCN traffic itself.
ICI = "ici"
DCN = "dcn"
POD = "pod"
FLAT = "flat"
LEVELS = (ICI, DCN, POD, FLAT)

# Per-leg primitives (the HiCCL composition alphabet, restricted to what
# the TPU lowerings use). ``send`` is the point-to-point primitive of the
# pipeline wire (docs/pipeline.md): one ``lax.ppermute`` hop carrying an
# inter-stage activation (or activation-grad) along the hvd_pp axis,
# charged to the link class its ``level`` names. The same primitive also
# carries the ``kv_migrate`` plan family (docs/serving.md): one
# prefill→decode KV-page handoff between serving replicas, lowered
# host-side between two engine meshes rather than as an in-program
# collective. ``all_to_all`` is the MoE dispatch/combine primitive
# (docs/moe.md): one tiled ``lax.all_to_all`` row exchange along the
# hvd_ep axis, owned by the ``a2a`` plan family.
REDUCE_SCATTER = "reduce_scatter"
ALL_GATHER = "all_gather"
ALL_TO_ALL = "all_to_all"
PSUM = "psum"
SEND = "send"
PRIMITIVES = (REDUCE_SCATTER, ALL_GATHER, ALL_TO_ALL, PSUM, SEND)

# Wire dtypes. ``payload`` rides whatever dtype the caller handed the
# collective (after any Compression cast); ``int8`` is the blockwise-
# scaled int8 wire with one fp32 scale per ``block`` elements.
PAYLOAD = "payload"
BF16 = "bf16"
INT8 = "int8"
WIRE_DTYPES = (PAYLOAD, BF16, INT8)

# Link class each per-level leg is charged to by the accounting and the
# cost model (docs/cost-model.md). The flat leg decomposes into all of
# them — its accounting/pricing rows carry the hop explicitly.
LEVEL_HOP = {ICI: "ici", DCN: "dcn", POD: "pod"}

# Leg backends. ``xla`` lowers through the stock jax primitives; ``pallas``
# lowers the leg's local compute (blockwise quantize/dequant-accumulate,
# matmul prologue/epilogue tiles) through the fused Pallas TPU kernels of
# ``ops/fused_collective.py`` so it never round-trips HBM between the
# producing op and the wire (docs/fused-kernels.md). The WIRE composition
# is identical either way — backend is an execution attribute, like
# ``stream``.
XLA = "xla"
PALLAS = "pallas"
BACKENDS = (XLA, PALLAS)

_REDUCE_PRIMS = (REDUCE_SCATTER, PSUM)
_GATHER_PRIMS = (ALL_GATHER,)

_COLLECTIVES = ("allreduce", "reduce_scatter", "all_gather", "send",
                "a2a", "kv_migrate")

# Plan families whose legs are point-to-point ``send`` hops rather than
# reduction/gather ladder rungs: the pipeline wire and the serving KV
# handoff share the primitive but differ in who lowers them (in-program
# ppermute vs host-side replica-to-replica transfer).
_SEND_COLLECTIVES = ("send", "kv_migrate")


class PlanError(ValueError):
    """A wire plan failed validation (illegal leg composition)."""


@dataclasses.dataclass(frozen=True)
class Leg:
    """One hop of a wire plan: a primitive at a mesh level.

    ``wire_dtype``/``block`` describe the bytes on THIS hop only (the
    EQuARX rule: dtype transforms are per-hop attributes, and int8 is
    only legal on the slow DCN hop — the ICI leg always rides the
    payload dtype). ``error_feedback`` marks the hop as carrying an
    error-feedback residual slot (the quantization error of what this
    rank sent, re-injected next step). ``stream`` is the comm-stream
    slot the leg's bucket collective is issued on when the plan is
    overlap-scheduled (0-based, < :attr:`WirePlan.streams`).
    ``backend`` selects the lowering of the leg's local compute:
    ``xla`` (default) or ``pallas`` (fused kernel, docs/fused-kernels.md).
    """

    level: str
    primitive: str
    wire_dtype: str = PAYLOAD
    block: Optional[int] = None
    error_feedback: bool = False
    stream: int = 0
    backend: str = XLA

    def describe(self) -> str:
        d = self.wire_dtype
        if self.wire_dtype == INT8 and self.block:
            d = f"int8/{self.block}"
        if self.error_feedback:
            d += "+ef"
        tail = "@pl" if self.backend == PALLAS else ""
        return f"{self.level}.{self.primitive}[{d}]{tail}"


@dataclasses.dataclass(frozen=True)
class WirePlan:
    """An ordered leg composition for one collective.

    ``streams`` is the flight width of the overlap schedule (how many
    bucket collectives sit in the program with no consumer between
    them); ``overlap`` marks the plan for reverse-layer stream placement
    (:func:`horovod_tpu.ops.fusion.stream_order`). Neither changes the
    math — they are placement attributes, which is why overlap-on is
    bit-identical to off (docs/overlap.md).
    """

    collective: str
    legs: Tuple[Leg, ...]
    streams: int = 1
    overlap: bool = False

    # -- structure queries (the compiler and planner dispatch on these) --

    @property
    def is_flat(self) -> bool:
        return len(self.legs) == 1 and self.legs[0].level == FLAT

    @property
    def is_quantized(self) -> bool:
        return any(l.wire_dtype == INT8 for l in self.legs)

    @property
    def is_dcn_quantized(self) -> bool:
        """Int8 on the cross-host (DCN) hop — the wire the 2-level
        quantized lowerings (lower_quantized_allreduce, the ZeRO rs/ag
        legs) compress. A plan whose only int8 legs ride the POD level
        (the quantized pod hop) is NOT dcn-quantized: it lowers through
        the tree ladder, which owns the pod legs."""
        return any(l.wire_dtype == INT8 and l.level == DCN
                   for l in self.legs)

    @property
    def is_tree(self) -> bool:
        """A multi-leg hierarchical (per-level) composition."""
        return not self.is_flat and len(self.legs) > 1

    @property
    def levels(self) -> Tuple[str, ...]:
        return tuple(l.level for l in self.legs)

    @property
    def quant_block(self) -> Optional[int]:
        for l in self.legs:
            if l.wire_dtype == INT8 and l.block:
                return l.block
        return None

    def encode(self) -> str:
        """Compact one-line encoding — legs joined with ``>`` plus the
        stream placement suffix. Stable: the autotuner's CSV/cache plan
        column and the golden-text plan dumps both use it."""
        body = ">".join(l.describe() for l in self.legs)
        tail = f"|s{self.streams}|{'ovl' if self.overlap else 'sync'}"
        return f"{self.collective}:{body}{tail}"

    # -- validation ------------------------------------------------------

    def validate(self) -> "WirePlan":
        """Check the composition; raises :class:`PlanError` with an
        actionable message on the first violation. Returns self so
        ``WirePlan(...).validate()`` chains."""
        if self.collective not in _COLLECTIVES:
            raise PlanError(
                f"unknown collective {self.collective!r}: a wire plan "
                f"compiles one of {_COLLECTIVES}")
        if not self.legs:
            raise PlanError(
                f"empty {self.collective} plan: a plan needs at least "
                f"one leg (use a single flat leg for the XLA-decomposed "
                f"default)")
        if self.streams not in (1, 2, 4):
            raise PlanError(
                f"stream count {self.streams} is invalid: comm streams "
                f"must be a power of two in 1..4 "
                f"(HOROVOD_NUM_COMM_STREAMS contract, docs/overlap.md)")
        for i, leg in enumerate(self.legs):
            where = f"leg {i} ({leg.level}.{leg.primitive})"
            if leg.level not in LEVELS:
                raise PlanError(
                    f"{where}: unknown level {leg.level!r} — levels are "
                    f"{LEVELS} (ici=intra-host ring, dcn=cross-host, "
                    f"pod=cross-pod, flat=whole axis tuple)")
            if leg.primitive not in PRIMITIVES:
                raise PlanError(
                    f"{where}: unknown primitive {leg.primitive!r} — "
                    f"primitives are {PRIMITIVES}")
            if leg.wire_dtype not in WIRE_DTYPES:
                raise PlanError(
                    f"{where}: unknown wire dtype {leg.wire_dtype!r} — "
                    f"wire dtypes are {WIRE_DTYPES}")
            if leg.wire_dtype == INT8 and leg.level not in (DCN, POD):
                raise PlanError(
                    f"{where}: blockwise-int8 wire dtype on a non-DCN "
                    f"hop — compression belongs on the slow cross-host "
                    f"links only; the ICI leg always rides the payload "
                    f"dtype (HiCCL placement rule, docs/wire-plan.md)")
            if leg.wire_dtype == INT8 and leg.primitive == PSUM:
                raise PlanError(
                    f"{where}: blockwise-int8 on a psum leg — int8 "
                    f"blocks with per-block scales are not closed under "
                    f"addition, so the exact psum has no quantized "
                    f"lowering; spell a quantized hop as the "
                    f"reduce_scatter[int8] > all_gather[int8] pair "
                    f"(the quantized pod hop, docs/fused-kernels.md)")
            if leg.backend not in BACKENDS:
                raise PlanError(
                    f"{where}: unknown backend {leg.backend!r} — "
                    f"backends are {BACKENDS} (xla = stock primitives, "
                    f"pallas = fused kernels, docs/fused-kernels.md)")
            if leg.backend == PALLAS and leg.level == FLAT:
                raise PlanError(
                    f"{where}: backend='pallas' on a flat leg — the "
                    f"flat plan is one XLA-decomposed collective with "
                    f"no leg-local compute to fuse a kernel into; "
                    f"kernel-backed legs live on the per-level "
                    f"compositions (docs/fused-kernels.md)")
            if ((leg.primitive == SEND)
                    != (self.collective in _SEND_COLLECTIVES)):
                if leg.primitive == SEND:
                    raise PlanError(
                        f"{where}: a send leg only belongs to a 'send' "
                        f"or 'kv_migrate' plan — the point-to-point hop "
                        f"does not compose with reduction/gather "
                        f"ladders (docs/pipeline.md, docs/serving.md)")
                raise PlanError(
                    f"{where}: a {self.collective} plan carries only "
                    f"send legs, got {leg.primitive!r} — the point-to-"
                    f"point wire is one hop per direction "
                    f"(docs/pipeline.md, docs/serving.md)")
            if leg.primitive == SEND and leg.level == FLAT:
                raise PlanError(
                    f"{where}: a send leg names the LINK CLASS the "
                    f"pipeline hop crosses (ici/dcn/pod) — there is no "
                    f"flat decomposition of a point-to-point hop")
            if leg.primitive == SEND and leg.backend == PALLAS:
                raise PlanError(
                    f"{where}: backend='pallas' on a send leg — the "
                    f"pipeline hop has no leg-local compute to fuse "
                    f"beyond the int8 quantize pair, which the compiler "
                    f"places itself (docs/pipeline.md)")
            if (leg.primitive == ALL_TO_ALL) != (self.collective == "a2a"):
                if leg.primitive == ALL_TO_ALL:
                    raise PlanError(
                        f"{where}: an all_to_all leg only belongs to an "
                        f"'a2a' plan — the MoE dispatch/combine exchange "
                        f"is a permutation, not a reduction/gather "
                        f"ladder (docs/moe.md)")
                raise PlanError(
                    f"{where}: an a2a plan carries only all_to_all "
                    f"legs, got {leg.primitive!r} — the MoE wire is one "
                    f"tiled row exchange per direction (docs/moe.md)")
            if leg.primitive == ALL_TO_ALL and leg.level == FLAT:
                raise PlanError(
                    f"{where}: an a2a leg names the LINK CLASS the "
                    f"expert-parallel hop crosses (ici/dcn/pod) — there "
                    f"is no flat decomposition of the hvd_ep row "
                    f"exchange (docs/moe.md)")
            if (leg.primitive == ALL_TO_ALL and leg.backend == PALLAS
                    and leg.wire_dtype != INT8):
                raise PlanError(
                    f"{where}: backend='pallas' on a payload-dtype a2a "
                    f"leg — an exact exchange has no leg-local compute; "
                    f"the fused kernels back the blockwise int8 "
                    f"quantize/dequant pair only (docs/fused-kernels.md)")
            if leg.backend == PALLAS and leg.primitive == PSUM:
                raise PlanError(
                    f"{where}: backend='pallas' on a psum leg — the "
                    f"exact psum has no kernel body; the fused kernels "
                    f"back the quantize/dequant rs/ag legs and the "
                    f"matmul prologue/epilogue legs "
                    f"(docs/fused-kernels.md)")
            if leg.error_feedback and leg.level not in (DCN, POD):
                raise PlanError(
                    f"{where}: error-feedback slot on a non-DCN hop — "
                    f"EF accumulates the quantization error of the "
                    f"compressed cross-host wire; exact ICI legs have "
                    f"no error to feed back")
            if leg.block is not None and leg.wire_dtype != INT8:
                raise PlanError(
                    f"{where}: scale block {leg.block} without an int8 "
                    f"wire dtype — block is the int8 scale granularity")
            if leg.block is not None and leg.block < 1:
                raise PlanError(
                    f"{where}: scale block must be >= 1, got {leg.block}")
            if not (0 <= leg.stream < self.streams):
                raise PlanError(
                    f"{where}: stream {leg.stream} out of range for a "
                    f"{self.streams}-stream plan (streams are 0-based "
                    f"flight slots)")
            if leg.level == FLAT and len(self.legs) > 1:
                raise PlanError(
                    f"{where}: a flat leg is the WHOLE plan (one "
                    f"XLA-decomposed collective over the full axis "
                    f"tuple) — it cannot compose with per-level legs")
        self._validate_order()
        return self

    def _validate_order(self) -> None:
        prims = [(l.level, l.primitive) for l in self.legs]
        if self.collective == "allreduce":
            # Reduce phase (reduce_scatter / psum / all_to_all) first,
            # gather phase (all_gather) after; every level scattered must
            # be re-gathered in mirror (LIFO) order.
            gather_started = False
            scattered: list = []
            gathered: list = []
            for i, (level, prim) in enumerate(prims):
                if prim in _GATHER_PRIMS:
                    gather_started = True
                    gathered.append(level)
                elif gather_started:
                    raise PlanError(
                        f"illegal leg order in {self.encode()}: leg {i} "
                        f"({level}.{prim}) is a reduce leg after the "
                        f"gather phase began — an allreduce plan must "
                        f"finish its reduction ladder before re-"
                        f"gathering (scatter down, gather back up)")
                if prim == REDUCE_SCATTER and level != FLAT:
                    scattered.append(level)
            if scattered and gathered != list(reversed(scattered)):
                raise PlanError(
                    f"unbalanced allreduce plan {self.encode()}: levels "
                    f"reduce-scattered {scattered} must be re-gathered "
                    f"in mirror order, got gathers {gathered} — the "
                    f"output would not be the full replicated sum")
        elif self.collective == "reduce_scatter":
            for i, (level, prim) in enumerate(prims):
                if prim in _GATHER_PRIMS:
                    raise PlanError(
                        f"illegal leg in {self.encode()}: leg {i} "
                        f"({level}.{prim}) — a reduce_scatter plan ends "
                        f"holding 1/world shards; an all_gather leg "
                        f"belongs to the all_gather plan (the ZeRO wire "
                        f"splits the allreduce in half around the "
                        f"optimizer update)")
        elif self.collective == "send":
            if len(self.legs) != 1:
                raise PlanError(
                    f"illegal send plan {self.encode()}: a send plan is "
                    f"exactly ONE hop (one ppermute leg on one link "
                    f"class) — the pipeline schedule composes hops by "
                    f"issuing one plan per direction, docs/pipeline.md")
        elif self.collective == "kv_migrate":
            if len(self.legs) != 1:
                raise PlanError(
                    f"illegal kv_migrate plan {self.encode()}: a KV "
                    f"migration is exactly ONE hop (one send leg on the "
                    f"link class the prefill→decode handoff crosses) — "
                    f"the migrator streams a whole slot's pages through "
                    f"one wire, docs/serving.md")
        elif self.collective == "a2a":
            if len(self.legs) != 1:
                raise PlanError(
                    f"illegal a2a plan {self.encode()}: an a2a plan is "
                    f"exactly ONE exchange (one all_to_all leg on one "
                    f"link class) — the MoE layer composes the wire by "
                    f"issuing one plan per direction (dispatch, then "
                    f"combine), docs/moe.md")
        elif self.collective == "all_gather":
            for i, (level, prim) in enumerate(prims):
                if prim not in _GATHER_PRIMS and level != FLAT:
                    raise PlanError(
                        f"illegal leg in {self.encode()}: leg {i} "
                        f"({level}.{prim}) — an all_gather plan only "
                        f"concatenates shards; reductions belong to the "
                        f"reduce_scatter/allreduce plans")
