"""The planner: derive a wire plan from today's knob set.

Every knob combination the collective stack used to hand-compose —
``quantized`` × ``zero_stage`` × ``overlap`` × ``hierarchical`` × stream
count — is one point in plan space:

==============================  =======================================
knobs                            gradient wire plan
==============================  =======================================
(defaults)                       ``allreduce: flat.psum`` (XLA
                                 decomposes over ICI/DCN itself)
``hierarchical=True``            ``allreduce: ici.rs > dcn.psum >
                                 ici.ag`` (+ ``pod.psum`` on a 3-level
                                 mesh)
``quantized=True``               ``allreduce: ici.rs > dcn.rs[int8] >
                                 dcn.ag[int8] > ici.ag``
``zero_stage>0``                 split in half around the optimizer
                                 update: a ``reduce_scatter`` plan for
                                 the gradients + an ``all_gather`` plan
                                 for the updates (stage 3 moves the
                                 gather to the next forward)
``overlap`` / ``streams``        placement attributes on any of the
                                 above (reverse-layer issue order,
                                 flight width) — never the math
==============================  =======================================

:func:`describe_plan` is the debug API (``hvd.describe_plan(**knobs)``):
it resolves unset knobs exactly like ``DistributedOptimizer`` would (env
config included) and returns a :class:`StepPlan` whose :meth:`~StepPlan.
table` renders legs, hops, wire dtypes, streams, and predicted per-device
wire bytes from the trace-time cost model — ``bench.py --dump-plan``
prints it, and golden-text tests pin it so plan regressions show up as
readable diffs.

:func:`encode_tuned` / :func:`decode_tuned` are the autotuner's compact
plan encoding (leg order, per-hop dtype, stream placement): the GP
searches this space instead of three disconnected relaxed-categorical
booleans, and configurations that compile to the SAME wire (e.g.
``hierarchical`` under ZeRO, where the rs/ag split ignores it) collapse
to one plan — one trial, not two recompiles.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

from ..common import basics
from ..common.config import _env_bool, _env_int
from .ir import (ALL_GATHER, ALL_TO_ALL, DCN, FLAT, ICI, INT8, PALLAS,
                 PAYLOAD, POD, PSUM, REDUCE_SCATTER, SEND, XLA, Leg,
                 PlanError, WirePlan)

_AXIS_LEVEL = {basics.LOCAL_AXIS: ICI, basics.CROSS_AXIS: DCN,
               basics.POD_AXIS: POD}


def _resolve_fused(fused: Optional[bool]) -> bool:
    """Per-call arg > init-time Config > HOROVOD_FUSED_KERNELS env —
    whether kernel-eligible legs lower through the fused Pallas backend
    (docs/fused-kernels.md)."""
    if fused is not None:
        return bool(fused)
    cfg = basics.config() if basics.is_initialized() else None
    return (cfg.fused_kernels if cfg is not None
            else _env_bool("HOROVOD_FUSED_KERNELS", False))


def _resolve_quantized_pod(quantized_pod: Optional[bool]) -> bool:
    """Per-call arg > Config > HOROVOD_QUANTIZED_POD env — whether the
    3-level tree plan's pod hop rides the blockwise-int8 rs+ag pair
    instead of the exact psum."""
    if quantized_pod is not None:
        return bool(quantized_pod)
    cfg = basics.config() if basics.is_initialized() else None
    return (cfg.quantized_pod if cfg is not None
            else _env_bool("HOROVOD_QUANTIZED_POD", False))


def _backend(fused: bool) -> str:
    return PALLAS if fused else XLA


def levels_of(axes_t) -> Optional[Tuple[str, ...]]:
    """Map a bound axis tuple onto plan levels, or None when the tuple
    names non-Horovod axes (custom ``axes=`` — always lowered flat)."""
    try:
        return tuple(_AXIS_LEVEL[a] for a in axes_t)
    except KeyError:
        return None


# ---------------------------------------------------------------------------
# Canonical plan constructors.
# ---------------------------------------------------------------------------


def flat_plan(collective: str, *, streams: int = 1,
              overlap: bool = False) -> WirePlan:
    prim = {"allreduce": PSUM, "reduce_scatter": REDUCE_SCATTER,
            "all_gather": ALL_GATHER}[collective]
    return WirePlan(collective, (Leg(FLAT, prim),), streams=streams,
                    overlap=overlap).validate()


def tree_allreduce_plan(*, pod: bool = False, streams: int = 1,
                        overlap: bool = False,
                        quantized_pod: bool = False,
                        block: Optional[int] = None,
                        fused: bool = False) -> WirePlan:
    legs = [Leg(ICI, REDUCE_SCATTER), Leg(DCN, PSUM)]
    if pod and quantized_pod:
        # The quantized pod hop (docs/fused-kernels.md): the pod level as
        # the int8 rs+ag pair — the EQuARX decomposition on the slowest
        # link of a 3-level mesh — instead of the exact psum.
        legs.append(Leg(POD, REDUCE_SCATTER, INT8, block=block,
                        backend=_backend(fused)))
        legs.append(Leg(POD, ALL_GATHER, INT8, block=block,
                        backend=_backend(fused)))
    elif pod:
        legs.append(Leg(POD, PSUM))
    legs.append(Leg(ICI, ALL_GATHER))
    return WirePlan("allreduce", tuple(legs), streams=streams,
                    overlap=overlap).validate()


def quantized_allreduce_plan(*, block: Optional[int] = None,
                             error_feedback: bool = False,
                             streams: int = 1,
                             overlap: bool = False,
                             fused: bool = False) -> WirePlan:
    legs = (
        Leg(ICI, REDUCE_SCATTER),
        Leg(DCN, REDUCE_SCATTER, INT8, block=block,
            error_feedback=error_feedback, backend=_backend(fused)),
        Leg(DCN, ALL_GATHER, INT8, block=block,
            error_feedback=error_feedback, backend=_backend(fused)),
        Leg(ICI, ALL_GATHER),
    )
    return WirePlan("allreduce", legs, streams=streams,
                    overlap=overlap).validate()


def zero_reduce_scatter_plan(*, quantized: bool = False,
                             block: Optional[int] = None,
                             error_feedback: bool = False,
                             streams: int = 1,
                             overlap: bool = False,
                             fused: bool = False) -> WirePlan:
    """The ZeRO gradient wire (the reduce half of the quantized
    allreduce, stopped before the optimizer update)."""
    dcn = (Leg(DCN, REDUCE_SCATTER, INT8, block=block,
               error_feedback=error_feedback,
               backend=_backend(fused)) if quantized
           else Leg(DCN, REDUCE_SCATTER, PAYLOAD,
                    error_feedback=error_feedback))
    return WirePlan("reduce_scatter",
                    (Leg(ICI, REDUCE_SCATTER), dcn),
                    streams=streams, overlap=overlap).validate()


def zero_all_gather_plan(*, quantized: bool = False,
                         block: Optional[int] = None,
                         error_feedback: bool = False,
                         streams: int = 1,
                         overlap: bool = False,
                         fused: bool = False) -> WirePlan:
    """The ZeRO update broadcast (the gather half)."""
    if quantized:
        legs = (Leg(DCN, ALL_GATHER, INT8, block=block,
                    error_feedback=error_feedback,
                    backend=_backend(fused)),
                Leg(ICI, ALL_GATHER))
        return WirePlan("all_gather", legs, streams=streams,
                        overlap=overlap).validate()
    return flat_plan("all_gather", streams=streams, overlap=overlap)


def send_plan(level: str = DCN, *, quantized: bool = False,
              block: Optional[int] = None,
              error_feedback: bool = False) -> WirePlan:
    """The pipeline's inter-stage activation wire (docs/pipeline.md): a
    single point-to-point ``send`` leg on the link class the hvd_pp hop
    crosses. ``quantized`` rides it blockwise-int8 with error feedback —
    legal on the DCN/pod hops only (the EQuARX placement rule; an ICI
    send always rides the payload dtype)."""
    if quantized:
        leg = Leg(level, SEND, INT8, block=block,
                  error_feedback=error_feedback)
    else:
        leg = Leg(level, SEND, PAYLOAD)
    return WirePlan("send", (leg,)).validate()


def pp_send_level(mesh_shape) -> str:
    """The link class an hvd_pp hop crosses: the pp axis leads the mesh
    (consecutive stages sit a whole data-mesh apart in device order), so
    the hop rides the SLOWEST link class present — pod on a multi-pod
    mesh, dcn across hosts, ici on a single host."""
    nl, nc, npod = _mesh_sizes(mesh_shape)
    if npod > 1:
        return POD
    return DCN if nc > 1 else ICI


def derive_send(*, mesh_shape, quantized: bool = False,
                block: Optional[int] = None,
                error_feedback: Optional[bool] = None) -> WirePlan:
    """Derive the pipeline send plan for a mesh: the level comes from
    :func:`pp_send_level`; ``quantized`` is forced off on an ICI hop
    (int8 is illegal there — compression belongs on slow links)."""
    level = pp_send_level(mesh_shape)
    q = bool(quantized) and level in (DCN, POD)
    ef = q if error_feedback is None else (error_feedback and q)
    return send_plan(level, quantized=q, block=block, error_feedback=ef)


def kv_migrate_plan(level: str = DCN, *, quantized: bool = False,
                    block: Optional[int] = None,
                    error_feedback: bool = False) -> WirePlan:
    """The disaggregated-serving KV handoff wire (docs/serving.md): a
    single point-to-point ``send`` leg carrying one finished prefill's
    KV pages from a prefill replica to its decode replica. ``quantized``
    rides it blockwise-int8 (DCN/pod hops only, the EQuARX placement
    rule). ``error_feedback`` on a migration leg means the RESIDUAL
    pass: a one-shot transfer has no next step to feed the error into,
    so the compiler ships a second int8 pass over the first pass's
    quantization error on the same wire — 2x the quantized bytes,
    error collapsed to ~(absmax/127)^2, argmax-safe for decode."""
    if quantized:
        leg = Leg(level, SEND, INT8, block=block,
                  error_feedback=error_feedback)
    else:
        leg = Leg(level, SEND, PAYLOAD)
    return WirePlan("kv_migrate", (leg,)).validate()


def kv_migrate_level(mesh_shape) -> str:
    """The link class a prefill→decode handoff crosses: replica groups
    partition the device list contiguously (docs/serving.md), so the
    hop between two replicas rides the SLOWEST link class present —
    the same geometry argument as the pipeline/expert hops."""
    return pp_send_level(mesh_shape)


def derive_kv_migrate(*, mesh_shape, quantized: bool = False,
                      block: Optional[int] = None,
                      error_feedback: Optional[bool] = None) -> WirePlan:
    """Derive the KV migration plan for a mesh: the level comes from
    :func:`kv_migrate_level`; ``quantized`` is forced off on an ICI hop
    (int8 is illegal there), and a quantized migration defaults to the
    residual (error-feedback) pass so the handoff stays argmax-safe."""
    level = kv_migrate_level(mesh_shape)
    q = bool(quantized) and level in (DCN, POD)
    ef = q if error_feedback is None else (error_feedback and q)
    return kv_migrate_plan(level, quantized=q, block=block,
                           error_feedback=ef)


def predict_kv_migrate_bytes(plan: WirePlan, n: int,
                             itemsize: float) -> List[dict]:
    """Per-leg predicted wire bytes of ONE migration of an ``n``-element
    KV payload — the same formula :func:`~horovod_tpu.plan.compiler.
    lower_kv_migrate` charges at transfer time (the residual pass rides
    the same wire again), so predicted == accounted by construction.
    Row schema matches :func:`predict_leg_bytes`."""
    (leg,) = plan.legs
    hop = {ICI: "ici", DCN: "dcn", POD: "pod"}[leg.level]
    fp = float(n) * itemsize
    if leg.wire_dtype == INT8:
        from .compiler import quant_wire_bytes

        wire = quant_wire_bytes(n, leg.block or 256)
        if leg.error_feedback:
            wire *= 2.0
    else:
        wire = fp
    return [{"leg": leg, "hop": hop, "bytes": wire, "fp_bytes": fp}]


def a2a_plan(level: str = DCN, *, quantized: bool = False,
             block: Optional[int] = None,
             error_feedback: bool = False,
             fused: bool = False) -> WirePlan:
    """The MoE dispatch/combine wire (docs/moe.md): a single tiled
    ``all_to_all`` row exchange on the link class the hvd_ep hop
    crosses. ``quantized`` rides it blockwise-int8 with optional error
    feedback — legal on the DCN/pod hops only (the EQuARX placement
    rule, exactly like the pipeline send leg); ``fused`` backs the int8
    quantize/dequant pair with the Pallas kernels."""
    if quantized:
        leg = Leg(level, ALL_TO_ALL, INT8, block=block,
                  error_feedback=error_feedback,
                  backend=_backend(fused))
    else:
        leg = Leg(level, ALL_TO_ALL, PAYLOAD)
    return WirePlan("a2a", (leg,)).validate()


def ep_a2a_level(mesh_shape) -> str:
    """The link class an hvd_ep hop crosses: identical geometry to the
    pipeline hop — the ep axis leads the mesh, so one hop jumps a whole
    data mesh and rides the SLOWEST link class present (docs/moe.md)."""
    return pp_send_level(mesh_shape)


def derive_a2a(*, mesh_shape, quantized: bool = False,
               block: Optional[int] = None,
               error_feedback: Optional[bool] = None,
               fused: Optional[bool] = None) -> WirePlan:
    """Derive the MoE a2a plan for a data mesh: the level comes from
    :func:`ep_a2a_level`; ``quantized`` is forced off on an ICI hop
    (int8 is illegal there — compression belongs on slow links)."""
    level = ep_a2a_level(mesh_shape)
    q = bool(quantized) and level in (DCN, POD)
    ef = q if error_feedback is None else (error_feedback and q)
    return a2a_plan(level, quantized=q, block=block, error_feedback=ef,
                    fused=_resolve_fused(fused) and q)


def predict_a2a_bytes(plan: WirePlan, n: int, itemsize: float,
                      ep: int) -> List[dict]:
    """Per-leg predicted wire bytes of ONE a2a exchange of an
    ``n``-element buffer over ``ep`` expert groups — the same formula
    :func:`~horovod_tpu.plan.compiler.lower_a2a` charges at trace time
    (``ep - 1`` of the ``ep`` destination row blocks cross the wire),
    so predicted == accounted by construction. Row schema matches
    :func:`predict_leg_bytes`."""
    (leg,) = plan.legs
    hop = {ICI: "ici", DCN: "dcn", POD: "pod"}[leg.level]
    ep = max(1, int(ep))
    seg = n // ep
    fp = float(seg) * (ep - 1) * itemsize
    if leg.wire_dtype == INT8:
        from .compiler import quant_wire_bytes

        wire = quant_wire_bytes(seg, leg.block or 256) * (ep - 1)
    else:
        wire = fp
    return [{"leg": leg, "hop": hop, "bytes": wire, "fp_bytes": fp}]


def pp_bubble_bound(stages: int, microbatches: int) -> float:
    """The no-overlap GPipe analytic bubble bound ``(S-1)/(M+S-1)`` —
    the fraction the perf gate holds every measured pipeline schedule
    strictly under (docs/pipeline.md)."""
    s, m = int(stages), max(1, int(microbatches))
    return (s - 1) / (m + s - 1) if s > 1 else 0.0


def fused_matmul_rs_plan(*, streams: int = 1,
                         overlap: bool = False) -> WirePlan:
    """The wire of :func:`~horovod_tpu.ops.fused_collective.
    fused_matmul_reduce_scatter`: a kernel-backed ring reduce-scatter —
    same bytes as the per-level rs legs, matmul epilogue riding inside."""
    return WirePlan("reduce_scatter",
                    (Leg(ICI, REDUCE_SCATTER, backend=PALLAS),
                     Leg(DCN, REDUCE_SCATTER, backend=PALLAS)),
                    streams=streams, overlap=overlap).validate()


def fused_ag_matmul_plan(*, streams: int = 1,
                         overlap: bool = False) -> WirePlan:
    """The wire of :func:`~horovod_tpu.ops.fused_collective.
    fused_all_gather_matmul`: a kernel-backed ring all-gather whose
    arriving shards feed the matmul prologue."""
    return WirePlan("all_gather",
                    (Leg(DCN, ALL_GATHER, backend=PALLAS),
                     Leg(ICI, ALL_GATHER, backend=PALLAS)),
                    streams=streams, overlap=overlap).validate()


# ---------------------------------------------------------------------------
# Knob → plan derivation (what the entry points call per trace).
# ---------------------------------------------------------------------------


def derive_allreduce(*, levels, quantized: bool, hierarchical: bool,
                     block: Optional[int] = None,
                     error_feedback: bool = False,
                     streams: int = 1, overlap: bool = False,
                     fused: Optional[bool] = None,
                     quantized_pod: Optional[bool] = None) -> WirePlan:
    """Today's allreduce knob combination as a plan. ``levels`` is the
    bound-axis level tuple (None for custom axes → flat). ``fused``
    (default: HOROVOD_FUSED_KERNELS) puts the Pallas backend on the
    kernel-eligible legs; ``quantized_pod`` (HOROVOD_QUANTIZED_POD)
    rides the 3-level tree plan's pod hop as the int8 rs+ag pair."""
    lvls = set(levels or ())
    fused = _resolve_fused(fused)
    if quantized and lvls == {ICI, DCN}:
        return quantized_allreduce_plan(block=block,
                                        error_feedback=error_feedback,
                                        streams=streams, overlap=overlap,
                                        fused=fused)
    if hierarchical and {ICI, DCN} <= lvls:
        return tree_allreduce_plan(
            pod=POD in lvls, streams=streams, overlap=overlap,
            quantized_pod=(POD in lvls
                           and _resolve_quantized_pod(quantized_pod)),
            block=block, fused=fused)
    return flat_plan("allreduce", streams=streams, overlap=overlap)


def derive_reduce_scatter(*, levels, quantized: bool,
                          error_feedback: bool = False,
                          block: Optional[int] = None,
                          streams: int = 1,
                          overlap: bool = False,
                          fused: Optional[bool] = None) -> WirePlan:
    lvls = set(levels or ())
    if lvls == {ICI, DCN} and (quantized or error_feedback):
        return zero_reduce_scatter_plan(
            quantized=quantized, block=block,
            error_feedback=error_feedback, streams=streams,
            overlap=overlap, fused=_resolve_fused(fused) and quantized)
    return flat_plan("reduce_scatter", streams=streams, overlap=overlap)


def derive_all_gather(*, levels, quantized: bool,
                      error_feedback: bool = False,
                      block: Optional[int] = None,
                      streams: int = 1, overlap: bool = False,
                      fused: Optional[bool] = None) -> WirePlan:
    lvls = set(levels or ())
    if quantized and lvls == {ICI, DCN}:
        return zero_all_gather_plan(
            quantized=True, block=block, error_feedback=error_feedback,
            streams=streams, overlap=overlap,
            fused=_resolve_fused(fused))
    return flat_plan("all_gather", streams=streams, overlap=overlap)


# ---------------------------------------------------------------------------
# Cost model: predicted per-device wire bytes per leg (the same formulas
# the compiler's trace-time accounting charges — docs/wire-plan.md).
# ---------------------------------------------------------------------------


def _mesh_sizes(mesh_shape) -> Tuple[int, int, int]:
    """(local, cross, pod) sizes of a (cross, local[, pods]) shape."""
    if len(mesh_shape) == 3:
        nc, nl, npod = mesh_shape
    else:
        (nc, nl), npod = mesh_shape, 1
    return int(nl), int(nc), int(npod)


def _quant_unit(seg: int, blk: int) -> float:
    pad_seg = (-seg) % blk + seg
    return pad_seg + (pad_seg // blk) * 4.0


def predict_leg_bytes(plan: WirePlan, n: int, itemsize: int,
                      mesh_shape, *, ep: int = 0) -> List[dict]:
    """Per-leg predicted wire bytes for a payload of ``n`` elements.
    Each row: ``{leg, hop, bytes, fp_bytes}`` where ``hop`` is the link
    class charged (``ici``/``dcn``/``pod``/``-``) and ``fp_bytes`` the
    same traffic at the payload dtype (differs only on int8 legs).
    ``ep`` is the expert-group exchange width of an ``a2a`` plan (the
    hvd_ep axis size — not derivable from the data ``mesh_shape``);
    a2a rows are zero without it."""
    if plan.collective == "a2a":
        return predict_a2a_bytes(plan, n, itemsize, ep)
    if plan.collective == "kv_migrate":
        return predict_kv_migrate_bytes(plan, n, itemsize)
    nl, nc, npod = _mesh_sizes(mesh_shape)
    world = nl * nc * npod
    isz = itemsize
    blk = plan.quant_block or 256
    sn = n // nl if nl else n
    seg_w = n // world if world else n
    rows: List[dict] = []

    def row(leg, hop, b, fp=None):
        rows.append({"leg": leg, "hop": hop, "bytes": b,
                     "fp_bytes": b if fp is None else fp})

    if plan.collective == "send":
        # One cyclic ppermute issue of the full [n] payload: every rank
        # sends its activation once (the interleaved schedule's ring);
        # same formula compiler.lower_send charges per issue at trace
        # time, so predicted == accounted by construction.
        (leg,) = plan.legs
        hop = {ICI: "ici", DCN: "dcn", POD: "pod"}[leg.level]
        if leg.wire_dtype == INT8:
            from .compiler import quant_wire_bytes

            row(leg, hop, quant_wire_bytes(n, leg.block or blk),
                float(n) * isz)
        else:
            row(leg, hop, float(n) * isz)
        return rows

    if plan.is_flat:
        leg = plan.legs[0]
        if plan.collective == "reduce_scatter":
            b = n * (nl - 1) / nl * isz
            d = (n / nl) * (nc - 1) / nc * isz
            p = (n / nl / nc) * (npod - 1) / npod * isz
        else:  # allreduce, or all_gather of the full [n] masked buffer
            b = 2.0 * n * (nl - 1) / nl * isz
            d = 2.0 * (n / nl) * (nc - 1) / nc * isz
            p = 2.0 * (n / nl / nc) * (npod - 1) / npod * isz
        row(leg, "ici", b)
        row(leg, "dcn", d)
        if npod > 1:
            row(leg, "pod", p)
        return rows

    ring = all(l.backend == PALLAS and l.wire_dtype == PAYLOAD
               for l in plan.legs)
    if ring and plan.collective in ("reduce_scatter", "all_gather"):
        # Fused matmul ring (fused_matmul_rs_plan / fused_ag_matmul_plan):
        # world-1 hops of the 1/world tile = (w-1)/w * n total per device
        # (a TRUE ring gather — no masked-psum doubling), of which 1/nl
        # of the directed links cross a host boundary (the same model
        # ops/fused_collective.py charges at trace time).
        total = n * (world - 1) / max(1, world) * isz
        for leg in plan.legs:
            if leg.level == ICI:
                row(leg, "ici", total * (1.0 - 1.0 / nl))
            else:
                row(leg, "dcn", total / nl)
        return rows

    for leg in plan.legs:
        if leg.level == ICI and leg.primitive == REDUCE_SCATTER:
            row(leg, "ici", n * (nl - 1) / nl * isz)
        elif leg.level == ICI and leg.primitive == ALL_GATHER:
            row(leg, "ici", 2.0 * n * (nl - 1) / nl * isz)
        elif leg.level in (DCN, POD) and leg.primitive == PSUM:
            k = nc if leg.level == DCN else npod
            hop = "dcn" if leg.level == DCN else "pod"
            row(leg, hop, 2.0 * (n / nl) * (k - 1) / k * isz)
        elif leg.level == POD and leg.primitive == REDUCE_SCATTER:
            # Quantized pod hop: rs[int8] on the post-ICI shard [sn].
            segp = sn // npod if npod else sn
            q = _quant_unit(segp, leg.block or blk) * npod
            row(leg, "pod", q * (npod - 1) / max(1, npod),
                float(sn) * (npod - 1) / max(1, npod) * isz)
        elif leg.level == POD and leg.primitive == ALL_GATHER:
            segp = sn // npod if npod else sn
            q = _quant_unit(segp, leg.block or blk) * npod
            row(leg, "pod", 2.0 * q * (npod - 1) / max(1, npod),
                2.0 * float(sn) * (npod - 1) / max(1, npod) * isz)
        elif leg.level == DCN and leg.primitive == REDUCE_SCATTER:
            if leg.wire_dtype == INT8:
                seg = (seg_w if plan.collective == "reduce_scatter"
                       else sn // nc)
                q = _quant_unit(seg, leg.block or blk) * nc
                row(leg, "dcn", q * (nc - 1) / nc,
                    float(sn) * (nc - 1) / nc * isz)
            else:
                row(leg, "dcn", sn * (nc - 1) / nc * isz)
        elif leg.level == DCN and leg.primitive == ALL_GATHER:
            if leg.wire_dtype != INT8:
                row(leg, "dcn", 2.0 * sn * (nc - 1) / nc * isz)
            elif plan.collective == "all_gather":
                # each rank gathers its owned 1/world segment of the
                # full [n] payload
                q = _quant_unit(seg_w, leg.block or blk)
                row(leg, "dcn", 2.0 * q * nc * (nc - 1) / nc,
                    2.0 * float(seg_w) * nc * (nc - 1) / nc * isz)
            else:
                q = _quant_unit(sn // nc, leg.block or blk) * nc
                row(leg, "dcn", 2.0 * q * (nc - 1) / nc,
                    2.0 * float(sn) * (nc - 1) / nc * isz)
        else:  # pragma: no cover - validation rejects other shapes
            row(leg, "-", 0.0)
    return rows


def predict_fused_hbm_saved(plan: WirePlan, n: int, itemsize: int,
                            mesh_shape) -> float:
    """Predicted HBM round-trip bytes the plan's kernel-backed legs avoid
    vs their separate-op lowering, for a payload of ``n`` elements — the
    same model the kernels charge at trace time
    (ops/fused_collective.py: ``quant_hbm_saved``/``dequant_hbm_saved``),
    rendered by the ``--dump-plan`` table's ``fused:`` line."""
    from ..ops import fused_collective as _fused

    nl, nc, npod = _mesh_sizes(mesh_shape)
    blk = plan.quant_block or 256
    sn = n // nl if nl else n
    saved = 0.0
    for leg in plan.legs:
        if leg.backend != PALLAS or leg.wire_dtype != INT8:
            continue
        k = npod if leg.level == POD else nc
        seg = (n // (nl * nc * npod) if plan.collective != "allreduce"
               and leg.level == DCN else sn // max(1, k))
        b = leg.block or blk
        nb = (seg + b - 1) // b
        if leg.primitive == REDUCE_SCATTER:
            saved += _fused.quant_hbm_saved(k, nb, b)
            saved += _fused.dequant_hbm_saved(k, nb, b)
        elif leg.primitive == ALL_GATHER:
            saved += _fused.quant_hbm_saved(1, nb, b)
    return saved


# ---------------------------------------------------------------------------
# StepPlan: the resolved wire plans of one training step + the knob
# record they were derived from. ``hvd.describe_plan(**knobs)`` builds it.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class StepPlan:
    """Resolved plans of a training step's gradient wire.

    ``gradient`` is the gradient collective's plan (an ``allreduce``
    plan, or the ``reduce_scatter`` half under ZeRO); ``gather`` is the
    update/parameter ``all_gather`` plan (None outside ZeRO — and under
    stage 3 it runs at the HEAD of the next forward, not the update
    tail). Thread a StepPlan into ``DistributedOptimizer(plan=...)`` /
    ``hvd.value_and_grad(plan=...)`` to replace the boolean knobs (which
    remain as aliases)."""

    mesh_shape: Tuple[int, ...]
    quantized: bool
    quant_block: int
    zero_stage: int
    overlap: bool
    hierarchical: bool
    num_comm_streams: int
    fusion_threshold_bytes: int
    gradient: WirePlan
    gather: Optional[WirePlan]
    fused: bool = False
    quantized_pod: bool = False
    # Pipeline parallelism (docs/pipeline.md): the inter-stage
    # activation wire (a validated send plan; None with pp off) plus the
    # schedule knobs it compiles under. ``pp_microbatches`` is the
    # per-step microbatch count M, ``pp_interleave`` the virtual-stage
    # degree v of the interleaved-1F1B schedule.
    send: Optional[WirePlan] = None
    pp_stages: int = 0
    pp_microbatches: int = 0
    pp_schedule: str = "interleaved_1f1b"
    pp_interleave: int = 1
    # Expert parallelism (docs/moe.md): the MoE dispatch/combine wire (a
    # validated a2a plan; None with MoE off) plus the routing knobs it
    # compiles under. ``moe_experts`` is the expert-group count E (the
    # hvd_ep axis size), ``moe_topk`` the per-token expert count K,
    # ``moe_capacity_factor`` the dispatch-buffer headroom.
    moe: Optional[WirePlan] = None
    moe_experts: int = 0
    moe_topk: int = 0
    moe_capacity_factor: float = 0.0
    moe_quantized: bool = False

    def encode(self) -> str:
        parts = [self.gradient.encode()]
        if self.gather is not None:
            where = "fwd" if self.zero_stage == 3 else "tail"
            parts.append(f"{where}@{self.gather.encode()}")
        if self.send is not None:
            parts.append(
                f"pp{self.pp_stages}v{self.pp_interleave}"
                f"m{self.pp_microbatches}.{self.pp_schedule}"
                f"@{self.send.encode()}")
        if self.moe is not None:
            parts.append(
                f"ep{self.moe_experts}.k{self.moe_topk}"
                f"@{self.moe.encode()}")
        return " + ".join(parts)

    @property
    def plans(self) -> Tuple[WirePlan, ...]:
        return ((self.gradient,) if self.gather is None
                else (self.gradient, self.gather))

    def table(self, payload_bytes: int = 4 * 1024 * 1024,
              itemsize: int = 4, model=None) -> str:
        """Render the step plan as a fixed-width text table (legs, hops,
        wire dtypes, streams, predicted per-device wire bytes AND
        predicted milliseconds for a ``payload_bytes`` gradient payload)
        — the ``--dump-plan`` / golden-test format.

        The ``model ms`` column is the pure bytes-at-modeled-bandwidth
        number (the trace-time WireStats model, HOROVOD_BENCH_*_GBPS);
        ``pred ms`` adds the cost model's launch-latency and
        quantize-kernel terms (docs/cost-model.md). ``model`` is a
        :class:`~horovod_tpu.plan.cost.CostModel` (default: the static
        env triples, so golden text stays deterministic; ``--dump-plan``
        passes the calibrated model when one is stored)."""
        from . import cost as _cost

        model = model or _cost.CostModel.from_env()
        n = payload_bytes // itemsize
        mesh = "x".join(str(v) for v in self.mesh_shape)
        lines = [
            f"wire plan  mesh={mesh}  payload={payload_bytes}B "
            f"(itemsize {itemsize})",
            f"knobs: quantized={_onoff(self.quantized)} "
            f"block={self.quant_block} zero_stage={self.zero_stage} "
            f"overlap={_onoff(self.overlap)} "
            f"hierarchical={_onoff(self.hierarchical)} "
            f"streams={self.num_comm_streams} "
            f"fusion_threshold={self.fusion_threshold_bytes} "
            f"fused={_onoff(self.fused)} "
            f"quantized_pod={_onoff(self.quantized_pod)}",
            f"{'collective':<16} {'leg':>3} {'level':<5} "
            f"{'primitive':<14} {'wire':<10} {'ef':<3} {'backend':<7} "
            f"{'stream':>6} {'bytes/dev':>12} {'model ms':>9} "
            f"{'pred ms':>8}",
        ]
        tot = {"ici": 0.0, "dcn": 0.0, "pod": 0.0, "fp": 0.0,
               "pod_fp": 0.0}
        hbm_saved = 0.0
        for plan in self.plans:
            rows = predict_leg_bytes(plan, n, itemsize, self.mesh_shape)
            plan_cost = _cost.price_plan(plan, n, itemsize,
                                         self.mesh_shape, model)
            hbm_saved += predict_fused_hbm_saved(plan, n, itemsize,
                                                 self.mesh_shape)
            for r in rows:
                if r["hop"] in tot:
                    tot[r["hop"]] += r["bytes"]
                if r["hop"] == "dcn":
                    tot["fp"] += r["fp_bytes"]
                elif r["hop"] == "pod":
                    tot["pod_fp"] += r["fp_bytes"]
            for li, leg in enumerate(plan.legs, start=1):
                b = sum(r["bytes"] for r in rows if r["leg"] is leg)
                modeled_ms, pred_ms = plan_cost.by_leg(leg)
                wire = leg.wire_dtype
                if leg.wire_dtype == INT8:
                    wire = f"int8/{leg.block or self.quant_block}"
                lines.append(
                    f"{plan.collective:<16} {li:>3} {leg.level:<5} "
                    f"{leg.primitive:<14} {wire:<10} "
                    f"{'yes' if leg.error_feedback else '-':<3} "
                    f"{leg.backend:<7} "
                    f"{leg.stream:>6} {int(round(b)):>12} "
                    f"{modeled_ms:>9.4f} {pred_ms:>8.4f}")
        if self.send is not None:
            # The pipeline wire, priced PER SEND ISSUE (one activation
            # microbatch over one hop; the schedule issues 2 x ticks of
            # these per step — bench reports the step total).
            rows = predict_leg_bytes(self.send, n, itemsize,
                                     self.mesh_shape)
            plan_cost = _cost.price_plan(self.send, n, itemsize,
                                         self.mesh_shape, model)
            for li, leg in enumerate(self.send.legs, start=1):
                b = sum(r["bytes"] for r in rows if r["leg"] is leg)
                modeled_ms, pred_ms = plan_cost.by_leg(leg)
                wire = leg.wire_dtype
                if leg.wire_dtype == INT8:
                    wire = f"int8/{leg.block or self.quant_block}"
                lines.append(
                    f"{'send':<16} {li:>3} {leg.level:<5} "
                    f"{leg.primitive:<14} {wire:<10} "
                    f"{'yes' if leg.error_feedback else '-':<3} "
                    f"{leg.backend:<7} "
                    f"{leg.stream:>6} {int(round(b)):>12} "
                    f"{modeled_ms:>9.4f} {pred_ms:>8.4f}")
        if self.moe is not None:
            # The MoE wire, priced PER A2A ISSUE (one dispatch-buffer
            # exchange over the hvd_ep axis; every MoE layer issues two
            # of these per step — dispatch, then combine).
            rows = predict_leg_bytes(self.moe, n, itemsize,
                                     self.mesh_shape,
                                     ep=self.moe_experts)
            plan_cost = _cost.price_plan(self.moe, n, itemsize,
                                         self.mesh_shape, model,
                                         ep=self.moe_experts)
            for li, leg in enumerate(self.moe.legs, start=1):
                b = sum(r["bytes"] for r in rows if r["leg"] is leg)
                modeled_ms, pred_ms = plan_cost.by_leg(leg)
                wire = leg.wire_dtype
                if leg.wire_dtype == INT8:
                    wire = f"int8/{leg.block or self.quant_block}"
                lines.append(
                    f"{'a2a':<16} {li:>3} {leg.level:<5} "
                    f"{leg.primitive:<14} {wire:<10} "
                    f"{'yes' if leg.error_feedback else '-':<3} "
                    f"{leg.backend:<7} "
                    f"{leg.stream:>6} {int(round(b)):>12} "
                    f"{modeled_ms:>9.4f} {pred_ms:>8.4f}")
        red = (tot["fp"] / tot["dcn"]) if tot["dcn"] else None
        totline = (f"totals: ici={int(round(tot['ici']))} "
                   f"dcn={int(round(tot['dcn']))} "
                   f"pod={int(round(tot['pod']))}")
        if red is not None:
            totline += (f" dcn_fp_equiv={int(round(tot['fp']))} "
                        f"dcn_reduction={red:.2f}x")
        if tot["pod"]:
            pred = tot["pod_fp"] / tot["pod"]
            totline += (f" pod_fp_equiv={int(round(tot['pod_fp']))} "
                        f"pod_reduction={pred:.2f}x")
        lines.append(totline)
        if hbm_saved:
            lines.append(
                f"fused: predicted hbm round-trip saved "
                f"{int(round(hbm_saved))} bytes/dev vs unfused "
                f"(docs/fused-kernels.md)")
        if self.send is not None:
            bound = pp_bubble_bound(self.pp_stages, self.pp_microbatches)
            lines.append(
                f"pp: stages={self.pp_stages} "
                f"interleave={self.pp_interleave} "
                f"microbatches={self.pp_microbatches} "
                f"schedule={self.pp_schedule} "
                f"gpipe_bubble_bound={bound:.4f} "
                f"(send rows priced per issue, docs/pipeline.md)")
        if self.moe is not None:
            lines.append(
                f"moe: experts={self.moe_experts} "
                f"topk={self.moe_topk} "
                f"capacity_factor={self.moe_capacity_factor:g} "
                f"quantized={_onoff(self.moe_quantized)} "
                f"(a2a rows priced per issue — dispatch + combine = 2 "
                f"per layer, docs/moe.md)")
        sc = _cost.price_step(self, payload_bytes, itemsize=itemsize,
                              mesh_shape=self.mesh_shape, model=model)
        lines.append(
            f"predicted: {sc.predicted_ms:.4f} ms step wire = bytes "
            f"{sc.wire_ms:.4f} + latency {sc.alpha_ms:.4f} + quant "
            f"{sc.quant_ms:.4f} - hidden {sc.hidden_ms:.4f} "
            f"(modeled {sc.modeled_ms:.4f} ms, {sc.buckets} bucket"
            f"{'s' if sc.buckets != 1 else ''}) "
            f"[cost model: {model.source}]")
        lines.append(f"encoding: {self.encode()}")
        return "\n".join(lines)


def _onoff(v) -> str:
    return "on" if v else "off"


def describe_plan(
    *,
    quantized: Optional[bool] = None,
    zero_stage: Optional[int] = None,
    zero: Optional[bool] = None,
    overlap: Optional[bool] = None,
    hierarchical: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    quant_block: Optional[int] = None,
    fusion_threshold_bytes: Optional[int] = None,
    mesh_shape: Optional[Tuple[int, ...]] = None,
    error_feedback: Optional[bool] = None,
    tuned_params=None,
    fused: Optional[bool] = None,
    quantized_pod: Optional[bool] = None,
    pp_stages: Optional[int] = None,
    pp_microbatches: Optional[int] = None,
    pp_schedule: Optional[str] = None,
    pp_interleave: Optional[int] = None,
    pp_quantized: Optional[bool] = None,
    moe_experts: Optional[int] = None,
    moe_topk: Optional[int] = None,
    moe_capacity: Optional[float] = None,
    moe_quantized: Optional[bool] = None,
) -> StepPlan:
    """Resolve today's knob combination into its :class:`StepPlan` — the
    debug view of what the gradient wire will compile to.

    Unset knobs resolve exactly like ``DistributedOptimizer`` resolves
    them (``tuned_params`` override first, then the init-time Config /
    ``HOROVOD_*`` env). ``mesh_shape`` defaults to the live mesh
    (``(cross, local[, pods])``), or ``(1, 1)`` before init."""
    if tuned_params is not None:
        if fusion_threshold_bytes is None:
            fusion_threshold_bytes = tuned_params.fusion_threshold_bytes
        if hierarchical is None:
            hierarchical = tuned_params.hierarchical_allreduce
        if zero_stage is None:
            zero_stage = tuned_params.zero_stage
        if overlap is None:
            overlap = tuned_params.overlap
        if num_comm_streams is None:
            num_comm_streams = tuned_params.num_comm_streams
        if quant_block is None:
            quant_block = tuned_params.quant_block
        if fused is None:
            fused = getattr(tuned_params, "fused", None)
        if pp_microbatches is None:
            pp_microbatches = getattr(tuned_params, "pp_microbatches",
                                      None) or None
        if pp_interleave is None:
            pp_interleave = getattr(tuned_params, "pp_interleave",
                                    None) or None
        if moe_capacity is None:
            moe_capacity = getattr(tuned_params, "moe_capacity_factor",
                                   0.0) or None
        if moe_quantized is None and getattr(
                tuned_params, "moe_capacity_factor", 0.0):
            moe_quantized = getattr(tuned_params, "moe_quantized", None)
    cfg = basics.config() if basics.is_initialized() else None
    if quantized is None:
        quantized = (cfg.quantized_allreduce if cfg is not None
                     else _env_bool("HOROVOD_QUANTIZED_ALLREDUCE", False))
    if zero_stage is None and zero is not None:
        zero_stage = 2 if zero else 0
    if zero_stage is None:
        from ..parallel.optimizer import _resolve_zero_stage_config

        zero_stage = _resolve_zero_stage_config()
    if zero_stage not in (0, 1, 2, 3):
        raise PlanError(f"zero_stage must be 0..3, got {zero_stage!r}")
    if overlap is None:
        overlap = (cfg.overlap if cfg is not None
                   else _env_bool("HOROVOD_OVERLAP", False))
    if hierarchical is None:
        hierarchical = (cfg.hierarchical_allreduce if cfg is not None
                        else _env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE",
                                       False))
    if num_comm_streams is None:
        num_comm_streams = (cfg.num_comm_streams if cfg is not None
                            else _env_int("HOROVOD_NUM_COMM_STREAMS", 1))
    if quant_block is None:
        quant_block = (cfg.quant_block if cfg is not None
                       else _env_int("HOROVOD_QUANT_BLOCK", 256))
    if fusion_threshold_bytes is None:
        fusion_threshold_bytes = (
            cfg.fusion_threshold_bytes if cfg is not None
            else _env_int("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024))
    if mesh_shape is None:
        if basics.is_initialized() and basics.mesh() is not None:
            # The DATA mesh: a pipeline mesh's leading hvd_pp dim feeds
            # pp_stages below, never the collective level ladder.
            mesh_shape = basics.data_mesh_shape()
        else:
            mesh_shape = (1, 1)
    if pp_stages is None:
        if basics.is_initialized() and basics.mesh() is not None:
            pp_stages = basics.pp_size()
        else:
            pp_stages = (cfg.pp_stages if cfg is not None
                         else _env_int("HOROVOD_PP_STAGES", 0))
    pp_stages = int(pp_stages or 0)
    if pp_schedule is None:
        pp_schedule = (cfg.pp_schedule if cfg is not None
                       else "interleaved_1f1b")
    if pp_interleave is None:
        pp_interleave = (cfg.pp_interleave if cfg is not None else 1) or 1
    if pp_microbatches is None:
        pp_microbatches = (cfg.pp_microbatches if cfg is not None else 0)
    if not pp_microbatches:
        pp_microbatches = 2 * pp_stages  # schedule default (pow2-ish)
    if pp_quantized is None:
        pp_quantized = (cfg.pp_quantized if cfg is not None
                        else _env_bool("HOROVOD_PP_QUANTIZED", False))
    if moe_experts is None:
        if basics.is_initialized() and basics.mesh() is not None \
                and basics.ep_size() > 1:
            moe_experts = basics.ep_size()
        else:
            moe_experts = (cfg.moe_experts if cfg is not None
                           else _env_int("HOROVOD_MOE_EXPERTS", 0))
    moe_experts = int(moe_experts or 0)
    if moe_topk is None:
        moe_topk = (cfg.moe_topk if cfg is not None
                    else _env_int("HOROVOD_MOE_TOPK", 2))
    if moe_capacity is None:
        moe_capacity = (cfg.moe_capacity_factor if cfg is not None
                        else 1.25)
    if moe_quantized is None:
        moe_quantized = (cfg.moe_quantized if cfg is not None
                         else _env_bool("HOROVOD_MOE_QUANTIZED", False))
    fused = _resolve_fused(fused)
    quantized_pod = _resolve_quantized_pod(quantized_pod)
    nl, nc, npod = _mesh_sizes(mesh_shape)
    # The level ladder is structural, not size-gated: a 1-host mesh still
    # derives the 2-level plan (its DCN legs lower to no-ops at size 1).
    levels = [ICI, DCN] + ([POD] if npod > 1 else [])
    ef = quantized if error_feedback is None else error_feedback
    streams = max(1, int(num_comm_streams)) if overlap else 1
    overlap = bool(overlap)

    if zero_stage > 0:
        gradient = derive_reduce_scatter(
            levels=levels, quantized=quantized, error_feedback=ef,
            block=quant_block if quantized else None, streams=streams,
            overlap=overlap, fused=fused)
        gather = derive_all_gather(
            levels=levels, quantized=quantized, error_feedback=ef,
            block=quant_block if quantized else None, streams=streams,
            overlap=overlap, fused=fused)
    else:
        gradient = derive_allreduce(
            levels=levels, quantized=quantized,
            hierarchical=hierarchical,
            block=quant_block if (quantized or quantized_pod) else None,
            error_feedback=ef, streams=streams, overlap=overlap,
            fused=fused, quantized_pod=quantized_pod)
        gather = None
    send = None
    if pp_stages > 1:
        send = derive_send(mesh_shape=mesh_shape,
                           quantized=bool(pp_quantized),
                           block=quant_block if pp_quantized else None)
    moe = None
    if moe_experts > 1:
        moe = derive_a2a(mesh_shape=mesh_shape,
                         quantized=bool(moe_quantized),
                         block=quant_block if moe_quantized else None,
                         fused=fused)
    return StepPlan(
        moe=moe,
        moe_experts=moe_experts if moe_experts > 1 else 0,
        moe_topk=int(moe_topk) if moe_experts > 1 else 0,
        moe_capacity_factor=(float(moe_capacity)
                             if moe_experts > 1 else 0.0),
        moe_quantized=(bool(moe_quantized) and moe is not None
                       and moe.is_quantized),
        send=send,
        pp_stages=pp_stages if pp_stages > 1 else 0,
        pp_microbatches=int(pp_microbatches) if pp_stages > 1 else 0,
        pp_schedule=str(pp_schedule),
        pp_interleave=max(1, int(pp_interleave)),
        mesh_shape=tuple(int(v) for v in mesh_shape),
        quantized=bool(quantized),
        quant_block=int(quant_block),
        zero_stage=int(zero_stage),
        overlap=overlap,
        hierarchical=bool(hierarchical),
        num_comm_streams=int(num_comm_streams),
        fusion_threshold_bytes=int(fusion_threshold_bytes),
        gradient=gradient,
        gather=gather,
        fused=bool(fused),
        quantized_pod=bool(quantized_pod),
    )


# ---------------------------------------------------------------------------
# Autotune plan encoding: the compact search-space string the GP proposes
# over (cache schema v5, docs/autotune.md). Round-trips through
# decode_tuned; tolerant of absence in pre-v5 logs/caches.
# ---------------------------------------------------------------------------

_PLAN_RE = re.compile(
    r"^(?P<grad>ar\.flat|ar\.tree|rs\+ag\.z[123])\|"
    r"(?P<wire>fp|int8/\d+)\|s(?P<streams>\d+)\|(?P<sched>sync|ovl)"
    r"(?P<fused>\|pl)?(\|pp(?P<ppm>\d+)/(?P<ppv>\d+)(?P<ppzb>\|zb1)?)?"
    r"(\|moe(?P<moecap>[0-9.]+)/(?P<moeq>q8|fp))?"
    r"(\|sv(?P<svk>\d+)/(?P<svq>q8|fp))?$")


def encode_tuned(params, *, quantized: bool = False,
                 pp: bool = False, moe: bool = False,
                 serve: bool = False) -> str:
    """Compact plan encoding of a ``TunedParams``-like knob set: gradient
    leg order | DCN hop wire dtype | stream count | placement
    [| kernel backend]. E.g. ``ar.tree|int8/256|s2|ovl`` or
    ``rs+ag.z2|int8/256|s1|sync|pl`` (schema v6: the trailing ``|pl``
    marks the fused Pallas backend on the int8 legs; absent for v5
    readers and for every plan with no kernel-eligible leg). Knob sets
    that compile to the same wire encode identically (``hierarchical``
    is dead under ZeRO's rs+ag split; ``fused`` is dead on an
    unquantized wire — no int8 leg to back with a kernel — and both
    drop out)."""
    stage = int(getattr(params, "zero_stage", 0) or 0)
    if stage > 0:
        grad = f"rs+ag.z{stage}"
    elif getattr(params, "hierarchical_allreduce", False):
        grad = "ar.tree"
    else:
        grad = "ar.flat"
    wire = (f"int8/{int(getattr(params, 'quant_block', 256))}"
            if quantized else "fp")
    streams = int(getattr(params, "num_comm_streams", 1) or 1)
    sched = "ovl" if getattr(params, "overlap", False) else "sync"
    if sched == "sync":
        streams = 1  # dead knob with overlap off: same wire, one trial
    enc = f"{grad}|{wire}|s{streams}|{sched}"
    if quantized and getattr(params, "fused", False):
        enc += "|pl"  # dead knob without an int8 leg: drops out above
    if pp:
        # Schema v8 (docs/pipeline.md): the pipeline schedule knobs —
        # microbatch count / interleave degree — join the plan encoding
        # only when the session's step is pipelined; with pp off both
        # are dead knobs and drop out (one trial, not four).
        m = int(getattr(params, "pp_microbatches", 0) or 0)
        v = max(1, int(getattr(params, "pp_interleave", 1) or 1))
        enc += f"|pp{m}/{v}"
        # Schema v11 (docs/pipeline.md): the zero-bubble family marker —
        # present only when the tuned schedule is zb1 (so every v10
        # encoding is also a valid v11 encoding); with pp off the
        # schedule is a dead knob and collapses to interleaved-1F1B.
        if str(getattr(params, "pp_schedule", "") or "") == "zb1":
            enc += "|zb1"
    if moe:
        # Schema v9 (docs/moe.md): the MoE routing knobs — dispatch
        # capacity factor / a2a wire dtype — join the plan encoding only
        # when the session's step carries an MoE layer; with moe off
        # both are dead knobs and drop out (one trial, not four).
        cap = float(getattr(params, "moe_capacity_factor", 0.0) or 0.0)
        if cap <= 0.0:
            cap = 1.25  # the config default: moe on needs a capacity
        q = "q8" if getattr(params, "moe_quantized", False) else "fp"
        enc += f"|moe{cap:g}/{q}"
    if serve:
        # Schema v10 (docs/serving.md): the disaggregated-serving knobs —
        # speculative draft length / KV-migration wire dtype — join the
        # plan encoding only when the session tunes a serving engine;
        # in a training session both are dead knobs and drop out.
        k = int(getattr(params, "spec_draft_k", 0) or 0)
        q = "q8" if getattr(params, "kv_migrate_quantized", False) else "fp"
        enc += f"|sv{k}/{q}"
    return enc


# ---------------------------------------------------------------------------
# Plan-space enumeration + analytic shortlist (docs/cost-model.md): the
# legal plan space of a knob set, priced by the cost model into a ranked
# shortlist the GP autotuner warm-starts from.
# ---------------------------------------------------------------------------

# Fusion-threshold candidates: small enough that the alpha term prices
# bucketing, large enough to span the search box (1-256 MiB, log-space).
_DEFAULT_THRESHOLDS = (4 * 1024 * 1024, 16 * 1024 * 1024,
                       64 * 1024 * 1024)
_DEFAULT_BLOCKS = (128, 256, 512)


@dataclasses.dataclass(frozen=True)
class PricedPlan:
    """One shortlist row: a knob setting (``params`` is an
    ``autotune.TunedParams``), the :class:`StepPlan` it derives, and its
    :class:`~horovod_tpu.plan.cost.StepCost`."""

    params: object
    plan: StepPlan
    cost: object

    @property
    def predicted_ms(self) -> float:
        return self.cost.predicted_ms

    def as_dict(self) -> dict:
        return {"plan": self.plan.encode(),
                "predicted_ms": round(self.cost.predicted_ms, 6),
                "modeled_ms": round(self.cost.modeled_ms, 6),
                "params": self.params.as_dict()}


_DEFAULT_MOE_CAPS = (1.0, 1.25, 1.5, 2.0)


def enumerate_tuned(*, quantized: bool = False,
                    tune_hierarchical: bool = True,
                    tune_zero: bool = False,
                    tune_overlap: bool = False,
                    tune_fused: bool = False,
                    tune_pp: bool = False,
                    pp_stages: int = 0,
                    pp_max_interleave: int = 1,
                    tune_moe: bool = False,
                    moe_experts: int = 0,
                    initial=None,
                    thresholds=None,
                    blocks=None) -> list:
    """Enumerate the legal knob space of one tuning session as
    ``TunedParams`` candidates: leg order (flat/tree vs the ZeRO rs+ag
    split) x DCN wire dtype scale block x stream split x fused backend x
    fusion threshold — gated exactly like the autotuner's search
    dimensions (a knob the session's step cannot accept is pinned to the
    initial value), deduplicated on the canonical plan encoding so knob
    sets that compile to the same wire appear once."""
    from ..autotune.parameter_manager import TunedParams

    if initial is None:
        initial = TunedParams()
    thr_opts = sorted(
        {int(t) for t in (thresholds or _DEFAULT_THRESHOLDS)}
        | {int(initial.fusion_threshold_bytes)})
    blk_opts = (sorted({int(b) for b in (blocks or _DEFAULT_BLOCKS)}
                       | {int(initial.quant_block)})
                if quantized else (int(initial.quant_block),))
    stage_opts = (0, 1, 2) if tune_zero else (initial.zero_stage,)
    if tune_pp and pp_stages > 1:
        # Pipeline candidates (docs/pipeline.md): pow2-ish microbatch
        # counts that divide by the stage count, crossed with the legal
        # interleave degrees — the bubble/alpha tradeoff the cost model
        # prices (more microbatches shrink the bubble, cost more send
        # launches).
        ppm_opts = sorted({pp_stages, 2 * pp_stages, 4 * pp_stages}
                          | ({int(initial.pp_microbatches)}
                             if initial.pp_microbatches else set()))
        ppv_opts = sorted({v for v in (1, 2, 4)
                           if v <= max(1, pp_max_interleave)})
        # Schedule family (v11, docs/pipeline.md): the zero-bubble B/W
        # split trades more send launches per tick grid for a strictly
        # smaller bubble — a real candidate axis, not a dead knob.
        ppsched_opts = ("interleaved_1f1b", "zb1")
    else:
        ppm_opts = (initial.pp_microbatches,)
        ppv_opts = (initial.pp_interleave,)
        ppsched_opts = (str(getattr(initial, "pp_schedule",
                                    "interleaved_1f1b")
                            or "interleaved_1f1b"),)
    if tune_moe and moe_experts > 1:
        # MoE candidates (docs/moe.md): the capacity/wire tradeoff the
        # cost model prices — a higher capacity factor drops fewer
        # tokens but moves a proportionally bigger dispatch buffer; the
        # int8 a2a wire buys bytes at quantize-kernel cost.
        init_cap = float(getattr(initial, "moe_capacity_factor", 0.0)
                         or 0.0)
        cap_opts = sorted(set(_DEFAULT_MOE_CAPS)
                          | ({init_cap} if init_cap > 0 else set()))
        moeq_opts = (False, True)
    else:
        cap_opts = (getattr(initial, "moe_capacity_factor", 0.0),)
        moeq_opts = (getattr(initial, "moe_quantized", False),)
    out, seen = [], set()
    for thr in thr_opts:
        for blk in blk_opts:
            for stage in stage_opts:
                if stage == 0:
                    hier_opts = ((False, True) if tune_hierarchical
                                 else (initial.hierarchical_allreduce,))
                else:
                    hier_opts = (False,)  # dead under the rs+ag split
                for hier in hier_opts:
                    ovl_opts = ((False, True) if tune_overlap
                                else (bool(initial.overlap),))
                    for ovl in ovl_opts:
                        if not ovl:
                            stream_opts = (1,)
                        elif tune_overlap:
                            stream_opts = (1, 2, 4)
                        else:
                            stream_opts = (
                                max(1, initial.num_comm_streams),)
                        for s in stream_opts:
                            fz_opts = ((False, True)
                                       if tune_fused and quantized
                                       else (initial.fused
                                             if quantized else False,))
                            for fz in fz_opts:
                                for ppm in ppm_opts:
                                    for ppv in ppv_opts:
                                        for pps in ppsched_opts:
                                            for cap in cap_opts:
                                                for mq in moeq_opts:
                                                    p = TunedParams(
                                                        fusion_threshold_bytes=thr,
                                                        quant_block=blk,
                                                        hierarchical_allreduce=hier,
                                                        zero_stage=stage,
                                                        overlap=ovl,
                                                        num_comm_streams=s,
                                                        fused=fz,
                                                        pp_microbatches=ppm,
                                                        pp_interleave=ppv,
                                                        pp_schedule=pps,
                                                        moe_capacity_factor=cap,
                                                        moe_quantized=mq)
                                                    key = (thr, blk,
                                                           encode_tuned(
                                                               p,
                                                               quantized=quantized,
                                                               pp=tune_pp,
                                                               moe=tune_moe))
                                                    if key in seen:
                                                        continue
                                                    seen.add(key)
                                                    out.append(p)
    return out


def shortlist(payload_bytes: float, *, itemsize: float = 4.0,
              mesh_shape=None, model=None, compute_ms=None,
              quantized: bool = False, k: Optional[int] = None,
              tune_hierarchical: bool = True, tune_zero: bool = False,
              tune_overlap: bool = False, tune_fused: bool = False,
              tune_pp: bool = False, pp_stages: int = 0,
              pp_max_interleave: int = 1,
              tune_moe: bool = False, moe_experts: int = 0,
              initial=None, thresholds=None, blocks=None) -> list:
    """Enumerate, validate, and PRICE the legal plan space for a knob
    set, returning :class:`PricedPlan` rows ranked by predicted step-
    wire milliseconds (ties broken by the stable plan encoding).

    Every candidate is filtered through ``WirePlan.validate`` (via
    :func:`describe_plan`'s constructors); ``model`` defaults to the
    calibrated cost model when a matching-geometry sweep is stored,
    else the static env triples (:func:`horovod_tpu.plan.cost.resolve`).
    ``k`` truncates to the top-K (None = the full ranked space) — the
    autotuner's warm-start seeds (docs/cost-model.md)."""
    from . import cost as _cost

    if mesh_shape is None:
        if basics.is_initialized() and basics.mesh() is not None:
            mesh_shape = basics.data_mesh_shape()
        else:
            mesh_shape = (1, 1)
    model = model or _cost.resolve(mesh_shape)
    priced = []
    seen = set()
    for p in enumerate_tuned(quantized=quantized,
                             tune_hierarchical=tune_hierarchical,
                             tune_zero=tune_zero,
                             tune_overlap=tune_overlap,
                             tune_fused=tune_fused,
                             tune_pp=tune_pp, pp_stages=pp_stages,
                             pp_max_interleave=pp_max_interleave,
                             tune_moe=tune_moe, moe_experts=moe_experts,
                             initial=initial,
                             thresholds=thresholds, blocks=blocks):
        try:
            sp = describe_plan(tuned_params=p, quantized=quantized,
                               mesh_shape=mesh_shape,
                               quantized_pod=False,
                               pp_stages=(pp_stages if tune_pp
                                          else None),
                               moe_experts=(moe_experts if tune_moe
                                            else 0),
                               moe_quantized=(p.moe_quantized
                                              if tune_moe else None))
        except PlanError:
            continue  # illegal composition: not a candidate
        # Dedup on the DERIVED wire (plus the threshold, ZeRO stage,
        # and MoE capacity factor, which the encoding does not carry —
        # stages 1/2 share a wire but restructure the accumulator, and
        # the capacity factor reshapes the dispatch buffer): knobs dead
        # in this knob set's derivation (e.g. hierarchical under a
        # quantized 2-level wire) must not spend two shortlist rows on
        # one compiled program.
        key = (sp.encode(), int(p.fusion_threshold_bytes),
               int(p.zero_stage),
               float(p.moe_capacity_factor) if tune_moe else 0.0)
        if key in seen:
            continue
        seen.add(key)
        sc = _cost.price_step(sp, payload_bytes, itemsize=itemsize,
                              mesh_shape=mesh_shape, model=model,
                              compute_ms=compute_ms)
        priced.append(PricedPlan(p, sp, sc))
    priced.sort(key=lambda pp: (pp.predicted_ms, pp.plan.encode()))
    return priced[:k] if k else priced


def decode_tuned(encoding: str) -> dict:
    """Parse a plan encoding back to the knob dict it derives from.
    Raises :class:`PlanError` on malformed input (tolerant readers catch
    it and fall back to the explicit knob columns)."""
    m = _PLAN_RE.match(encoding.strip())
    if not m:
        raise PlanError(
            f"unparseable plan encoding {encoding!r} — expected "
            f"'<ar.flat|ar.tree|rs+ag.zN>|<fp|int8/B>|sK|<sync|ovl>"
            f"[|pl]'")
    grad = m.group("grad")
    out = {
        "zero_stage": int(grad[-1]) if grad.startswith("rs+ag") else 0,
        "hierarchical_allreduce": grad == "ar.tree",
        "quantized": m.group("wire") != "fp",
        "overlap": m.group("sched") == "ovl",
        "num_comm_streams": int(m.group("streams")),
        "fused": m.group("fused") is not None,
        "pp_microbatches": int(m.group("ppm") or 0),
        "pp_interleave": int(m.group("ppv") or 1),
        # v11: |zb1 rides the pp segment — absent (or pp off) decodes
        # to the interleaved-1F1B default, so zb collapses to 1f1b
        # whenever the pipeline knobs are dead.
        "pp_schedule": ("zb1" if m.group("ppzb")
                        else "interleaved_1f1b"),
        "moe_capacity_factor": float(m.group("moecap") or 0.0),
        "moe_quantized": m.group("moeq") == "q8",
        "spec_draft_k": int(m.group("svk") or 0),
        "kv_migrate_quantized": m.group("svq") == "q8",
    }
    if out["quantized"]:
        out["quant_block"] = int(m.group("wire").split("/", 1)[1])
    return out
