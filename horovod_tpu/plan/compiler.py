"""The wire-plan compiler: lower a validated :class:`~.ir.WirePlan` to
the existing jax primitives, leg by leg.

This file is the single home of every collective leg-composition in the
repo — the bodies that used to live as bespoke paths in
``ops/collective_ops.py`` (the quantized hierarchical allreduce, the
quantized DCN reduce-scatter/all-gather legs of the ZeRO wire, the
hierarchical psum) are now **leg lowering rules** invoked by plan family:

======================  ==============================================
lowering rule            composition it implements
======================  ==============================================
:func:`_leg_flat_psum`   one XLA-decomposed psum over the axis tuple
:func:`_lower_tree_psum` ici reduce-scatter → dcn psum [→ pod psum] →
                         ici all-gather (NCCLHierarchicalAllreduce
                         shape, nccl_operations.cc:190-380)
:func:`_leg_quant_rs`    quantized DCN reduce-scatter: blockwise int8 +
                         fp32 scales over a tiled all_to_all,
                         dequantize-accumulate at the receiver
:func:`_leg_quant_ag`    quantized DCN all-gather: requantize the owned
                         segment, masked int8 psum (disjoint support ⇒
                         exact sum, replicated BY CONSTRUCTION)
:func:`_leg_ici_gather`  ici gather as a psum of disjointly-placed
                         shards (the repo's replication-by-construction
                         idiom)
======================  ==============================================

Every rule accounts its wire bytes through
:mod:`horovod_tpu.plan.accounting` at trace time, so every plan is
instrumented for free. The compiler works on the WIRE composition only:
op semantics (Average scaling, pre/post scale, compression casts,
replicated short-circuits, eager fallbacks) stay in the public entry
points of ``ops/collective_ops.py``, which derive a plan
(:mod:`horovod_tpu.plan.planner`) and call in here.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import basics
from ..common.basics import CROSS_AXIS, LOCAL_AXIS, POD_AXIS
from ..ops import compression as _compression
from . import ir
from .accounting import (_acct, _acct_a2a, _acct_enabled, _acct_kv,
                         _acct_pp, moe_span, pp_span)

# Mesh axis carried by each plan level.
LEVEL_AXIS = {ir.ICI: LOCAL_AXIS, ir.DCN: CROSS_AXIS, ir.POD: POD_AXIS}


def _axis_size(name) -> int:
    return basics._axis_size(name)


def quant_wire_bytes(seg: int, blk: int) -> float:
    """Bytes of one quantized segment on the wire: int8 payload plus one
    fp32 scale per ``blk`` elements, after padding ``seg`` up to a block
    multiple (the unit every quantized-leg cost formula is built from)."""
    pad_seg = (-seg) % blk + seg
    return pad_seg + (pad_seg // blk) * 4.0


# ---------------------------------------------------------------------------
# Flat legs (one XLA-decomposed collective over the whole axis tuple).
# ---------------------------------------------------------------------------


def _acct_psum_flat(x, axes) -> None:
    """Account a flat psum over ``axes`` with the topology-aware model:
    ICI leg on the full payload, DCN leg on the 1/local shard, pod leg on
    the 1/(local*cross) shard (DCN-class wire physically, charged to its
    own ``pod`` link class so 3-level meshes can model an asymmetric
    HOROVOD_BENCH_POD_GBPS bandwidth)."""
    if not _acct_enabled():
        return
    n = float(np.prod(x.shape)) if x.ndim else 1.0
    isz = jnp.dtype(x.dtype).itemsize
    if LOCAL_AXIS in axes:
        nl = _axis_size(LOCAL_AXIS)
        _acct("ici", 2.0 * n * (nl - 1) / nl * isz)
        n /= nl
    if CROSS_AXIS in axes:
        nc = _axis_size(CROSS_AXIS)
        _acct("dcn", 2.0 * n * (nc - 1) / nc * isz)
        n /= nc
    if POD_AXIS in axes:
        npod = _axis_size(POD_AXIS)
        _acct("pod", 2.0 * n * (npod - 1) / npod * isz)


def _leg_flat_psum(x, axes):
    _acct_psum_flat(x, axes)
    return lax.psum(x, axes)


# ---------------------------------------------------------------------------
# Tree (hierarchical) psum: per-level reduction ladder in the payload
# dtype. Lowering rule for the [ici.rs > dcn.psum (> pod.psum) > ici.ag]
# plan (reference algorithm: NCCLHierarchicalAllreduce,
# nccl_operations.cc:190-380, including the non-divisible remainder
# handled separately — here via the flat-psum fallback, matching the
# reference's root reduce/bcast remainder leg).
# ---------------------------------------------------------------------------


def _lower_tree_psum(plan: ir.WirePlan, x, axes: Tuple[str, ...]):
    local_axis, cross_axis = LOCAL_AXIS, CROSS_AXIS
    cross_levels = [l.level for l in plan.legs
                    if l.primitive == ir.PSUM and l.level != ir.FLAT]
    # Quantized pod hop (docs/fused-kernels.md): the pod level spelled as
    # the rs[int8] > ag[int8] pair instead of the exact psum.
    qpod = [l for l in plan.legs
            if l.level == ir.POD and l.wire_dtype == ir.INT8]
    nl = _axis_size(local_axis)
    npod = _axis_size(POD_AXIS) if qpod else 1
    if x.ndim >= 1 and x.shape[0] % nl == 0 and x.shape[0] > 0:
        n_elems = int(np.prod(x.shape, dtype=np.int64))
        sn = n_elems // nl
        # The quantized pod pair needs the post-ICI shard to split into
        # whole per-pod segments; otherwise it falls back to the exact
        # pod psum (the same remainder contract as the tree plan itself).
        use_qpod = bool(qpod) and npod > 1 and sn % npod == 0
        if _acct_enabled():
            n = float(n_elems)
            isz = jnp.dtype(x.dtype).itemsize
            _acct("ici", n * (nl - 1) / nl * isz)        # psum_scatter
            for lvl in cross_levels:                      # cross psum(s)
                k = _axis_size(LEVEL_AXIS[lvl])
                _acct("pod" if lvl == ir.POD else "dcn",
                      2.0 * (n / nl) * (k - 1) / k * isz)
            if use_qpod:
                blk = int(qpod[0].block or 256)
                seg = sn // npod
                q_unit = quant_wire_bytes(seg, blk) * npod
                _acct("pod", q_unit * (npod - 1) / npod,   # rs[int8]
                      float(sn) * (npod - 1) / npod * isz)
                _acct("pod", 2.0 * q_unit * (npod - 1) / npod,  # ag[int8]
                      2.0 * float(sn) * (npod - 1) / npod * isz)
            elif qpod:
                _acct("pod", 2.0 * (n / nl) * (npod - 1) / npod * isz)
            _acct("ici", 2.0 * n * (nl - 1) / nl * isz)  # gather-leg psum
        shard = lax.psum_scatter(x, local_axis, scatter_dimension=0,
                                 tiled=True)
        for lvl in cross_levels:
            shard = lax.psum(shard, LEVEL_AXIS[lvl])
        if qpod:
            if use_qpod:
                blk = int(qpod[0].block or 256)
                seg = sn // npod
                shape = shard.shape
                segs = shard.reshape(npod, seg).astype(jnp.float32)
                red, _ = _leg_quant_rs(segs, blk, POD_AXIS,
                                       backend=qpod[0].backend)
                vals, _ = _leg_quant_ag(red, blk, POD_AXIS,
                                        backend=qpod[-1].backend)
                shard = vals.reshape(shape).astype(x.dtype)
            else:
                shard = lax.psum(shard, POD_AXIS)
        # Final allgather leg, expressed as a psum of disjointly-placed
        # shards: numerically identical to lax.all_gather but the result is
        # provably replicated for the sharding checker (all_gather output is
        # conservatively treated as device-varying). Note the flat psum
        # below is usually optimal on TPU — XLA already decomposes a global
        # AllReduce over ICI/DCN — so the tree plan is a tuning knob for
        # multi-slice topologies, as in the reference (operations.cc:475-487).
        li = lax.axis_index(local_axis)
        # Fresh zeros (not zeros_like(x)) so the buffer doesn't inherit x's
        # cross-axis varying mark — shard is already cross-reduced.
        full = jnp.zeros(x.shape, x.dtype)
        full = lax.dynamic_update_slice_in_dim(
            full, shard, li * shard.shape[0], 0)
        return lax.psum(full, local_axis)
    return _leg_flat_psum(x, axes)


# ---------------------------------------------------------------------------
# Quantized DCN legs — the EQuARX decomposition placed per HiCCL's rule
# (compress the slow cross-host hop only, never the fast ICI one). These
# two rules are the int8 wire: ``_leg_quant_rs`` is the reduce half,
# ``_leg_quant_ag`` the gather half; the ZeRO wire runs the optimizer
# update between them, the quantized allreduce runs them back-to-back.
# ---------------------------------------------------------------------------


def _quantize_blocks(blocks, backend: str):
    """Blockwise int8 quantize of ``blocks [rows, nb, blk]`` →
    ``(q, scales, err)``. The ``pallas`` backend runs the fused one-pass
    VMEM kernel (ops/fused_collective.py — interpret mode off-TPU);
    ``xla`` is the original separate-op composition. Same wire format
    either way; values agree to the last ulp of the scale division."""
    if backend == ir.PALLAS:
        from ..ops import fused_collective as _fused

        return _fused.quantize_blockwise(blocks.astype(jnp.float32))
    scales = _compression._block_scales(blocks)
    q = jnp.clip(jnp.round(blocks / scales[..., None]),
                 -127, 127).astype(jnp.int8)
    err = blocks - q.astype(jnp.float32) * scales[..., None]
    return q, scales, err


def _dequant_accumulate(qT, sT, backend: str):
    """``sum_r qT[r] * sT[r]`` over the contributor axis — the fused
    kernel never expands the int8 payload to fp32 in HBM."""
    if backend == ir.PALLAS:
        from ..ops import fused_collective as _fused

        return _fused.dequantize_accumulate(qT, sT)
    return jnp.sum(qT.astype(jnp.float32) * sT[..., None], axis=0)


def _leg_quant_rs(segs, blk: int, cross_axis, backend: str = ir.XLA):
    """Quantized DCN reduce-scatter leg: ``segs`` is this rank's
    ICI-scattered shard viewed ``[nc, seg]`` in fp32, row ``j`` destined
    to cross rank ``j``. Each row quantizes to int8 with one fp32 scale
    per ``blk`` elements, a tiled ``all_to_all`` moves int8 + scales,
    receivers dequantize-accumulate in fp32. Returns
    ``(reduced_seg [seg] fp32, err [nc, seg] fp32)`` where ``err`` is
    this rank's quantization error on everything it sent. ``backend``
    selects the quantize/dequant lowering (``pallas`` = fused kernels,
    docs/fused-kernels.md); the wire composition is identical."""
    nc, seg = segs.shape
    pad = (-seg) % blk
    if pad:
        segs = jnp.concatenate(
            [segs, jnp.zeros((nc, pad), jnp.float32)], axis=1)
    nb = segs.shape[1] // blk
    blocks = segs.reshape(nc, nb, blk)
    q, scales, err = _quantize_blocks(blocks, backend)
    qT = lax.all_to_all(q, cross_axis, split_axis=0, concat_axis=0,
                        tiled=True)
    sT = lax.all_to_all(scales, cross_axis, split_axis=0, concat_axis=0,
                        tiled=True)
    acc = _dequant_accumulate(qT, sT, backend)
    return (acc.reshape(nb * blk)[:seg],
            err.reshape(nc, nb * blk)[:, :seg])


def _leg_quant_ag(seg_vals, blk: int, cross_axis, backend: str = ir.XLA):
    """Quantized DCN all-gather leg: quantize this rank's owned segment
    ``[seg]`` (fp32) and rebroadcast it as a masked int8 psum — disjoint
    support makes the sum exact and the result replicated over
    ``cross_axis`` BY CONSTRUCTION. Returns
    ``(vals [nc, seg] fp32, err [seg] fp32)``."""
    nc = _axis_size(cross_axis)
    seg = seg_vals.shape[0]
    pad = (-seg) % blk
    padded = (jnp.concatenate([seg_vals, jnp.zeros((pad,), jnp.float32)])
              if pad else seg_vals)
    nb = padded.shape[0] // blk
    q3, s2, e3 = _quantize_blocks(padded.reshape(1, nb, blk), backend)
    q2, s2, err = q3[0], s2[0], e3[0]
    err = err.reshape(nb * blk)[:seg]
    ci = lax.axis_index(cross_axis)
    qfull = lax.dynamic_update_slice_in_dim(
        jnp.zeros((nc, nb, blk), jnp.int8), q2[None], ci, 0)
    sfull = lax.dynamic_update_slice_in_dim(
        jnp.zeros((nc, nb), jnp.float32), s2[None], ci, 0)
    qg = lax.psum(qfull, cross_axis)
    sg = lax.psum(sfull, cross_axis)
    vals = (qg.astype(jnp.float32) * sg[..., None]).reshape(
        nc, nb * blk)[:, :seg]
    return vals, err


def _int8_leg_backend(plan: ir.WirePlan, primitive: str) -> str:
    """Backend of the first int8 leg with ``primitive`` (xla when the
    plan has none — the exact fallback paths)."""
    for leg in plan.legs:
        if leg.wire_dtype == ir.INT8 and leg.primitive == primitive:
            return leg.backend
    return ir.XLA


def _leg_ici_gather(shard_flat, n: int, offset, local_axis=LOCAL_AXIS):
    """ICI all-gather leg as a psum of disjointly-placed flat shards —
    the replication-by-construction gather every tree plan closes with."""
    full = jnp.zeros((n,), shard_flat.dtype)
    full = lax.dynamic_update_slice_in_dim(full, shard_flat, offset, 0)
    return lax.psum(full, local_axis)


# ---------------------------------------------------------------------------
# Send leg — the pipeline wire (docs/pipeline.md). One point-to-point
# ``lax.ppermute`` hop along ``axis`` (the hvd_pp axis), charged to the
# link class the leg's level names. The int8 wire dtype quantizes the
# payload blockwise before the hop and dequantizes after — the EQuARX
# per-hop rule applied to the activation wire — with an optional
# error-feedback residual (the quantization error of what THIS rank
# sent, re-injected into its next send).
# ---------------------------------------------------------------------------


def lower_send(plan: ir.WirePlan, x, *, axis, perm, residual=None,
               repeats: int = 1):
    """Lower a validated send plan over payload ``x``; returns
    ``(received, new_residual)`` (``new_residual`` is None without EF).

    ``perm`` is the ``lax.ppermute`` permutation (pairs); ``repeats`` is
    the number of times the caller's schedule issues this hop per traced
    program (a ``lax.scan`` body traces ONCE — the pipeline passes its
    tick count so the trace-time accounting charges the true per-step
    wire bytes, garbage bubble sends included: masked SPMD sends move
    real bytes)."""
    (leg,) = plan.legs
    hop = ir.LEVEL_HOP[leg.level]
    k = 1
    for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
        k *= _axis_size(a)
    n = int(np.prod(x.shape, dtype=np.int64)) if x.ndim else 1
    isz = jnp.dtype(x.dtype).itemsize
    frac = len(perm) / max(1, k)  # fraction of ranks sending per issue
    if leg.wire_dtype != ir.INT8:
        if _acct_enabled():
            _acct_pp(hop, float(n) * isz * frac * repeats,
                     sends=repeats)
        with pp_span("SEND"):
            out = lax.ppermute(x, axis, perm)
        return out, (None if residual is None
                     else jnp.zeros_like(residual))

    blk = int(leg.block or 256)
    corrected = (x if residual is None
                 else x + residual.reshape(x.shape).astype(x.dtype))
    flat = jnp.ravel(corrected).astype(jnp.float32)
    pad = (-n) % blk
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    nb = flat.shape[0] // blk
    q, scales, err = _quantize_blocks(flat.reshape(1, nb, blk),
                                      backend=ir.XLA)
    if _acct_enabled():
        wire = quant_wire_bytes(n, blk)
        _acct_pp(hop, wire * frac * repeats,
                 float(n) * isz * frac * repeats, sends=repeats)
    with pp_span("SEND"):
        qg = lax.ppermute(q, axis, perm)
        sg = lax.ppermute(scales, axis, perm)
    out = (qg.astype(jnp.float32) * sg[..., None]).reshape(
        nb * blk)[:n].reshape(x.shape).astype(x.dtype)
    if residual is None:
        return out, None
    new_res = err.reshape(nb * blk)[:n].reshape(residual.shape)
    return out, new_res.astype(residual.dtype)


# ---------------------------------------------------------------------------
# kv_migrate leg — the serving KV handoff wire (docs/serving.md). Unlike
# every other lowering here this one runs HOST-side: a prefill replica
# and its decode replica are two separate engine meshes with no shared
# program, so the migrator gathers a finished slot's KV pages on the
# source, pushes them through this wire (the encode→transfer→decode
# composition the plan names), and scatters the received pages on the
# destination between its decode steps. The wire composition is the
# plan's, exactly like the in-program legs: payload dtype passes
# through; int8 quantizes blockwise with one fp32 scale per block, and
# the error-feedback slot means the RESIDUAL pass — a second int8
# payload over the first pass's quantization error on the same hop
# (one-shot transfers have no next step to feed the error into), which
# collapses the reconstruction error to ~(absmax/127)^2.
# ---------------------------------------------------------------------------


def _host_quant_blocks(flat: np.ndarray, blk: int):
    """Host-side mirror of :func:`_quantize_blocks` over a flat fp32
    payload: ``(dequantized, err)`` after one blockwise int8
    round-trip. Same scale rule (absmax/127 per block, floored away
    from zero) so the wire format matches the device kernels."""
    n = flat.shape[0]
    pad = (-n) % blk
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), np.float32)])
    blocks = flat.reshape(-1, blk)
    scales = np.abs(blocks).max(axis=1) / 127.0
    scales = np.maximum(scales, 1e-12).astype(np.float32)
    q = np.clip(np.round(blocks / scales[:, None]), -127, 127)
    deq = (q.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return deq, flat.reshape(-1)[:n] - deq


def lower_kv_migrate(plan: ir.WirePlan, x: np.ndarray, *,
                     transfers: int = 0) -> Tuple[np.ndarray, float]:
    """Lower a validated kv_migrate plan over host payload ``x`` (one
    chunk of a slot's gathered KV pages, any shape/float dtype);
    returns ``(received, wire_bytes)`` — the array the decode replica
    scatters into its pools, plus the bytes this chunk put on the
    plan's hop (charged to ``comm.kv.bytes{hop}`` and the per-hop
    totals via :func:`~horovod_tpu.plan.accounting._acct_kv`).
    ``transfers=1`` on the LAST chunk of a slot marks the whole-slot
    migration complete in the transfer counter."""
    (leg,) = plan.legs
    hop = ir.LEVEL_HOP[leg.level]
    n = int(x.size)
    isz = np.dtype(x.dtype).itemsize
    if leg.wire_dtype != ir.INT8:
        wire = float(n) * isz
        if _acct_enabled():
            _acct_kv(hop, wire, transfers=transfers)
        return np.array(x, copy=True), wire
    blk = int(leg.block or 256)
    flat = np.asarray(x, np.float32).reshape(-1)
    deq, err = _host_quant_blocks(flat, blk)
    wire = quant_wire_bytes(n, blk)
    if leg.error_feedback:
        # Residual pass: quantize the first pass's error and ship it on
        # the same wire — 2x the quantized bytes, argmax-safe decode.
        deq_err, _ = _host_quant_blocks(err, blk)
        deq = deq + deq_err
        wire *= 2.0
    if _acct_enabled():
        _acct_kv(hop, wire, float(n) * isz, transfers=transfers)
    return deq.reshape(x.shape).astype(x.dtype), wire


# ---------------------------------------------------------------------------
# a2a leg — the MoE wire (docs/moe.md). One tiled ``lax.all_to_all`` row
# exchange along ``axis`` (the hvd_ep axis): ``x`` is ``[k*m, ...]`` with
# row block ``j`` (of ``m`` rows) destined to ep rank ``j``; the output
# has the same shape, block ``j`` holding what rank ``j`` sent this
# rank. The int8 wire dtype quantizes the k-1 foreign row blocks
# blockwise before the exchange and dequantizes after — the EQuARX
# per-hop rule applied to the expert dispatch/combine traffic — with an
# optional error-feedback residual (this rank's quantization error on
# everything it sent, re-injected into its next exchange).
# ---------------------------------------------------------------------------


def lower_a2a(plan: ir.WirePlan, x, *, axis, residual=None,
              kind: str = "DISPATCH"):
    """Lower a validated a2a plan over buffer ``x [k*m, ...]``; returns
    ``(received, new_residual)`` (``new_residual`` is None without EF).

    The exchange is the canonical row form (``split_axis=0,
    concat_axis=0, tiled=True``); callers reshape dispatch semantics
    around it (horovod_tpu/moe/layer.py). ``kind`` names the
    ``MOE:<kind>`` span bracketing the exchange."""
    (leg,) = plan.legs
    hop = ir.LEVEL_HOP[leg.level]
    k = 1
    for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
        k *= _axis_size(a)
    if x.shape[0] % k:
        raise ValueError(
            f"a2a buffer leading dim {x.shape[0]} does not divide by "
            f"the {k}-rank exchange axis {axis!r}")
    n = int(np.prod(x.shape, dtype=np.int64))
    seg = n // k                       # elements per destination row
    isz = jnp.dtype(x.dtype).itemsize
    if k == 1:
        # Degenerate world: nothing moves; still consume the residual so
        # the EF state threading is world-size independent.
        return x, (None if residual is None
                   else jnp.zeros_like(residual))
    if leg.wire_dtype != ir.INT8:
        if _acct_enabled():
            _acct_a2a(hop, float(seg) * (k - 1) * isz)
        with moe_span(kind):
            out = lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                 tiled=True)
        return out, (None if residual is None
                     else jnp.zeros_like(residual))

    blk = int(leg.block or 256)
    corrected = (x if residual is None
                 else x + residual.reshape(x.shape).astype(x.dtype))
    rows = jnp.reshape(corrected, (k, seg)).astype(jnp.float32)
    pad = (-seg) % blk
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((k, pad), jnp.float32)], axis=1)
    nb = rows.shape[1] // blk
    backend = leg.backend

    def _exchange_int8(blocks):
        """One int8 row exchange of ``blocks [k, nb, blk]``; returns
        ``(vals, err)`` — dequantized received blocks (a permutation,
        not a reduction: each block scales back independently) and this
        rank's quantization error on what it sent."""
        q, scales, err = _quantize_blocks(blocks, backend)
        if _acct_enabled():
            _acct_a2a(hop, quant_wire_bytes(seg, blk) * (k - 1),
                      float(seg) * (k - 1) * isz)
        with moe_span(kind):
            qT = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
            sT = lax.all_to_all(scales, axis, split_axis=0,
                                concat_axis=0, tiled=True)
        return (qT.astype(jnp.float32) * sT[..., None]), err

    # The transpose of the tiled (split 0, concat 0) row exchange is
    # ITSELF — (sender r, block j) swaps with (sender j, block r) — so
    # the backward pass rides the SAME int8 wire instead of a silent
    # fp fallback (and instead of autodiff's zero-gradient round):
    # cotangents quantize blockwise, exchange, dequantize. The EF
    # residual is forward-only state (no cotangent).
    @jax.custom_vjp
    def quantized_a2a(blocks):
        vals, err = _exchange_int8(blocks)
        return vals, err

    def _fwd(blocks):
        return _exchange_int8(blocks), None

    def _bwd(_, cots):
        g_vals, _g_err = cots
        g_back, _ = _exchange_int8(g_vals)
        return (g_back,)

    quantized_a2a.defvjp(_fwd, _bwd)

    vals3, err = quantized_a2a(rows.reshape(k, nb, blk))
    vals = vals3.reshape(k, nb * blk)[:, :seg]
    out = vals.reshape(x.shape).astype(x.dtype)
    if residual is None:
        return out, None
    new_res = err.reshape(k, nb * blk)[:, :seg].reshape(residual.shape)
    return out, jax.lax.stop_gradient(new_res).astype(residual.dtype)


# ---------------------------------------------------------------------------
# Allreduce lowerings.
# ---------------------------------------------------------------------------


def lower_psum(plan: ir.WirePlan, x, axes: Tuple[str, ...]):
    """Lower an exact (payload-dtype) allreduce-SUM plan."""
    if plan.is_flat:
        return _leg_flat_psum(x, axes)
    return _lower_tree_psum(plan, x, axes)


def lower_quantized_allreduce(plan: ir.WirePlan, x, *, residual=None,
                              block: int,
                              local_axis=LOCAL_AXIS,
                              cross_axis=CROSS_AXIS):
    """Lower the quantized allreduce-SUM plan
    ``[ici.rs > dcn.rs[int8] > dcn.ag[int8] > ici.ag]`` with optional
    error feedback.

    1. intra-host reduce-scatter (ICI, payload dtype);
    2. :func:`_leg_quant_rs` — cross-host quantized reduce-scatter;
    3. :func:`_leg_quant_ag` — cross-host quantized all-gather;
    4. :func:`_leg_ici_gather` — intra-host gather, payload dtype.

    Returns ``(sum, new_residual)``. With ``residual`` (error feedback),
    the residual is added to ``x`` before hop 1 and the returned residual
    holds this rank's quantization error — hop 2's error on the whole
    shard it contributed plus hop 3's requantization error on the segment
    it owns — written at the exact buffer positions where the next step's
    reduce-scatter re-collects each component exactly once.

    Falls back to an exact flat psum (consuming the residual, returning it
    as zeros) when there is no cross axis or the flattened size does not
    shard evenly over ``local_size * cross_size``.
    """
    nl = _axis_size(local_axis)
    nc = _axis_size(cross_axis)
    blk = int(block)
    corrected = x if residual is None else x + residual.astype(x.dtype)
    n = int(np.prod(x.shape, dtype=np.int64)) if x.ndim else 0
    if nc == 1 or n == 0 or n % nl or (n // nl) % nc:
        axes = (cross_axis, local_axis)
        out = _leg_flat_psum(corrected, axes)
        return out, (None if residual is None else jnp.zeros_like(residual))

    flat = jnp.ravel(corrected)
    sn = n // nl        # shard elements per device after the ICI leg
    seg = sn // nc      # segment elements per cross rank within a shard
    isz = jnp.dtype(x.dtype).itemsize
    if _acct_enabled():
        q_unit = quant_wire_bytes(seg, blk) * nc  # padded shard wire bytes
        _acct("ici", n * (nl - 1) / nl * isz)              # psum_scatter
        _acct("dcn", q_unit * (nc - 1) / nc,               # hop-2 all_to_all
              float(sn) * (nc - 1) / nc * isz)
        _acct("dcn", 2.0 * q_unit * (nc - 1) / nc,         # hop-3 masked psum
              2.0 * float(sn) * (nc - 1) / nc * isz)
        _acct("ici", 2.0 * n * (nl - 1) / nl * isz)        # ICI gather leg

    rs_backend = _int8_leg_backend(plan, ir.REDUCE_SCATTER)
    ag_backend = _int8_leg_backend(plan, ir.ALL_GATHER)

    # Leg 1 — ICI reduce-scatter in the payload dtype.
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0,
                             tiled=True)

    # Leg 2 — quantized DCN reduce-scatter (all_to_all of int8 + scales).
    segs = shard.reshape(nc, seg).astype(jnp.float32)
    red_seg, err1 = _leg_quant_rs(segs, blk, cross_axis,
                                  backend=rs_backend)     # [seg], [nc, seg]

    # Leg 3 — requantize the reduced segment; masked int8 psum gathers the
    # shard with replication by construction (disjoint segment support).
    vals, err2 = _leg_quant_ag(red_seg, blk, cross_axis,
                               backend=ag_backend)        # [nc, seg], [seg]
    shard_red = vals.reshape(sn).astype(x.dtype)

    # Leg 4 — ICI gather (psum of disjointly-placed shards).
    li = lax.axis_index(local_axis)
    out = _leg_ici_gather(shard_red, n, li * sn,
                          local_axis).reshape(x.shape)
    if residual is None:
        return out, None

    # Error feedback: leg-2 error on every segment this rank contributed,
    # plus leg-3's requantization error on the one segment it owns.
    ci = lax.axis_index(cross_axis)
    rows = jnp.arange(nc)[:, None]
    err_sh = (err1 + jnp.where(rows == ci, err2[None], 0.0)).reshape(sn)
    res_full = lax.dynamic_update_slice_in_dim(
        jnp.zeros((n,), jnp.float32), err_sh, li * sn, 0)
    return out, res_full.reshape(x.shape).astype(residual.dtype)


# ---------------------------------------------------------------------------
# Reduce-scatter / all-gather lowerings — the ZeRO wire pair. Rank-major
# layout: the bucket viewed [nc, nl, seg] so rank r = cross*local + local
# owns contiguous flat elements [r*seg, (r+1)*seg) — how P(HVD_AXES)
# splits a leading dim.
# ---------------------------------------------------------------------------


def lower_reduce_scatter(plan: ir.WirePlan, flat, *, residual=None,
                         block: int, axes: Tuple[str, ...], world: int):
    """Lower a reduce-scatter plan over a flat [n] bucket; returns
    ``(shard [n/world], new_residual)``.

    Flat plan: one ``lax.psum_scatter`` over the axis tuple (XLA
    decomposes it topology-aware; piece order over an axis tuple is lex
    = rank-major order). Tree plan (``[ici.rs > dcn.rs[int8|payload]]``):
    rank-major ICI scatter, then the DCN leg in the plan's wire dtype —
    ``residual`` is the error-feedback accumulator of the int8 leg,
    sized ``[n / local_size]`` (this rank's post-ICI shard)."""
    n = int(flat.shape[0])
    seg = n // world
    isz = jnp.dtype(flat.dtype).itemsize
    if plan.is_flat:
        if _acct_enabled():
            rem = float(n)
            if LOCAL_AXIS in axes:
                nl = _axis_size(LOCAL_AXIS)
                _acct("ici", rem * (nl - 1) / nl * isz)
                rem /= nl
            if CROSS_AXIS in axes:
                nc = _axis_size(CROSS_AXIS)
                _acct("dcn", rem * (nc - 1) / nc * isz)
                rem /= nc
            if POD_AXIS in axes:
                npod = _axis_size(POD_AXIS)
                _acct("pod", rem * (npod - 1) / npod * isz)
        shard = lax.psum_scatter(flat, axes, scatter_dimension=0,
                                 tiled=True)
        new_res = None if residual is None else jnp.zeros_like(residual)
        return shard, new_res

    quantized = plan.is_quantized
    nl = _axis_size(LOCAL_AXIS)
    nc = _axis_size(CROSS_AXIS)
    sn = n // nl
    blk = int(block)
    if _acct_enabled():
        _acct("ici", n * (nl - 1) / nl * isz)          # ICI psum_scatter
        if nc > 1:
            if quantized:
                q_unit = quant_wire_bytes(seg, blk) * nc
                _acct("dcn", q_unit * (nc - 1) / nc,
                      float(sn) * (nc - 1) / nc * isz)
            else:
                _acct("dcn", sn * (nc - 1) / nc * isz)
    # ICI leg, rank-major: view [nc, nl, seg], scatter the nl dim.
    h = lax.psum_scatter(flat.reshape(nc, nl, seg), LOCAL_AXIS,
                         scatter_dimension=1, tiled=True)
    h = h.reshape(nc, seg)
    new_res = None
    if residual is not None:
        if residual.shape != (sn,):
            raise ValueError(
                f"reduce_scatter residual must be the post-ICI shard "
                f"[{sn}] (= n/local_size), got {residual.shape}")
        h = h + residual.reshape(nc, seg).astype(h.dtype)
    if nc == 1:
        shard = h.reshape(seg)
        if residual is not None:
            new_res = jnp.zeros_like(residual)
    elif quantized:
        red, err = _leg_quant_rs(
            h.astype(jnp.float32), blk, CROSS_AXIS,
            backend=_int8_leg_backend(plan, ir.REDUCE_SCATTER))
        shard = red.astype(flat.dtype)
        if residual is not None:
            new_res = err.reshape(sn).astype(residual.dtype)
    else:
        shard = lax.psum_scatter(h, CROSS_AXIS, scatter_dimension=0,
                                 tiled=True).reshape(seg)
        if residual is not None:
            new_res = jnp.zeros_like(residual)
    return shard, new_res


def lower_all_gather(plan: ir.WirePlan, shard, *, residual=None,
                     block: int, axes: Tuple[str, ...], world: int,
                     rank):
    """Lower an all-gather plan over a flat [seg] shard; returns
    ``(full [seg*world], new_residual)`` — replicated BY CONSTRUCTION
    (masked-psum idiom on every path).

    Flat plan: one masked psum over the axis tuple. Quantized plan
    (``[dcn.ag[int8] > ici.ag]``): the DCN leg re-broadcasts this rank's
    owned segment as blockwise int8 (``residual`` is the EF accumulator
    over that segment), then the ICI leg places the cross-gathered
    column at this rank's local index of the rank-major
    ``[nc, nl, seg]`` layout and psums the disjoint contributions."""
    seg = int(shard.shape[0])
    n = seg * world
    if plan.is_quantized:
        nl = _axis_size(LOCAL_AXIS)
        nc = _axis_size(CROSS_AXIS)
        blk = int(block)
        isz = jnp.dtype(shard.dtype).itemsize
        if _acct_enabled():
            q_unit = quant_wire_bytes(seg, blk)
            _acct("dcn", 2.0 * q_unit * nc * (nc - 1) / nc,
                  2.0 * float(seg) * nc * (nc - 1) / nc * isz)
            _acct("ici", 2.0 * n * (nl - 1) / nl * isz)
        x = shard.astype(jnp.float32)
        new_res = None
        if residual is not None:
            if residual.shape != (seg,):
                raise ValueError(
                    f"all_gather residual must match the shard [{seg}], "
                    f"got {residual.shape}")
            x = x + residual.astype(jnp.float32)
        vals, err = _leg_quant_ag(
            x, blk, CROSS_AXIS,
            backend=_int8_leg_backend(plan, ir.ALL_GATHER))  # [nc, seg]
        if residual is not None:
            new_res = err.astype(residual.dtype)
        # ICI leg: place this rank's cross-gathered column at local index
        # li of the rank-major [nc, nl, seg] layout, psum-of-disjoint.
        li = lax.axis_index(LOCAL_AXIS)
        fullb = jnp.zeros((nc, nl, seg), jnp.float32)
        fullb = lax.dynamic_update_slice(fullb, vals[:, None, :], (0, li, 0))
        full = lax.psum(fullb, LOCAL_AXIS).reshape(n).astype(shard.dtype)
        return full, new_res

    # Exact path: one masked psum over all axes (disjoint contributions;
    # XLA decomposes it over ICI/DCN topology-aware).
    x = shard
    new_res = None
    if residual is not None:
        x = x + residual.astype(x.dtype)  # exact wire: consume the residual
        new_res = jnp.zeros_like(residual)
    buf = jnp.zeros((n,), x.dtype)
    buf = lax.dynamic_update_slice_in_dim(buf, x, rank * seg, 0)
    _acct_psum_flat(buf, axes)
    return lax.psum(buf, axes), new_res
