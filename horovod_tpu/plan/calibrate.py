"""Link-class calibration: measure (bandwidth, latency, quant-rate) per
level with a microbenchmark sweep and persist it beside the autotune
cache.

The cost model (:mod:`~horovod_tpu.plan.cost`) prices plans from
per-link ``(bandwidth_gbps, latency_us, quant_rate_gbps)`` triples. The
static defaults are honest nominal numbers, but HiCCL's premise is that
the triples should be *measured*: :func:`calibrate_links` times a
per-level ``lax.ppermute`` at 3–4 payload sizes (one directed ring hop =
one link traversal, the cleanest alpha-beta probe a compiled mesh
offers), fits ``t(n) = alpha + n/beta`` by least squares, and times the
blockwise int8 quantize + dequant-accumulate kernel pair the same way
for the quant rate.

Persistence contract (the part training depends on):

* the calibration lives in ONE JSON file next to the autotune cache
  (``HOROVOD_CALIBRATION_CACHE``, default ``link_calibration.json``
  beside ``HOROVOD_AUTOTUNE_CACHE``), keyed by the mesh **geometry
  fingerprint** (shape × world × device kind,
  :func:`horovod_tpu.common.basics.mesh_geometry`) — a sweep from a
  different topology or chip is never trusted;
* a geometry-key miss means re-sweep (or static defaults), never a
  silently wrong model;
* a corrupted, unreadable, or missing file falls back to the static
  ``HOROVOD_BENCH_*`` defaults with a logged warning — calibration is an
  optimization and must NEVER abort training.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..common import basics
from .cost import CostModel, LinkClass

log = logging.getLogger("horovod_tpu.plan")

# Bump when the sweep methodology changes enough to invalidate stored
# fits (sizes, fit form, kernel pair).
CALIBRATION_VERSION = 1

# Default sweep payloads, fp32 elements per device: 16 KiB – 4 MiB of
# wire per hop — small enough that a CPU-mesh sweep finishes in seconds,
# wide enough (256x) that the least-squares slope is bandwidth, not
# launch jitter.
DEFAULT_SWEEP_ELEMS = (4096, 32768, 262144, 1048576)


def calibration_path() -> str:
    """The calibration store: ``HOROVOD_CALIBRATION_CACHE``, defaulting
    to ``link_calibration.json`` beside the shared autotune cache."""
    explicit = os.environ.get("HOROVOD_CALIBRATION_CACHE")
    if explicit:
        return explicit
    from ..ops import kernel_autotune

    return os.path.join(os.path.dirname(kernel_autotune._cache_path()),
                        "link_calibration.json")


def geometry_key(mesh_shape=None) -> str:
    """Store key for one mesh geometry:
    ``linkcal|<mesh_geometry>|v<CALIBRATION_VERSION>``."""
    return (f"linkcal|{basics.mesh_geometry(mesh_shape=mesh_shape)}"
            f"|v{CALIBRATION_VERSION}")


@dataclasses.dataclass(frozen=True)
class Calibration:
    """One stored sweep: the fitted per-link triples plus the raw
    ``(bytes, seconds)`` points they were fitted from (kept for
    drift forensics — scripts/obs_report.py can re-fit)."""

    geometry: str
    links: Dict[str, LinkClass]
    points: Dict[str, List[Tuple[float, float]]]
    created_unix: float
    version: int = CALIBRATION_VERSION

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "geometry": self.geometry,
            "links": {k: v.as_dict() for k, v in self.links.items()},
            "points": {k: [[float(b), float(s)] for b, s in pts]
                       for k, pts in self.points.items()},
            "created_unix": self.created_unix,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Calibration":
        return cls(
            geometry=str(d["geometry"]),
            links={k: LinkClass.from_dict(v)
                   for k, v in d["links"].items()},
            points={k: [(float(b), float(s)) for b, s in pts]
                    for k, pts in d.get("points", {}).items()},
            created_unix=float(d.get("created_unix", 0.0)),
            version=int(d.get("version", 1)),
        )

    def cost_model(self) -> CostModel:
        """The calibrated :class:`~horovod_tpu.plan.cost.CostModel`;
        link classes the sweep could not measure (absent mesh levels)
        keep the static defaults."""
        static = CostModel.from_env()
        return CostModel(
            ici=self.links.get("ici", static.ici),
            dcn=self.links.get("dcn", static.dcn),
            pod=self.links.get("pod", static.pod),
            source="calibrated",
            geometry=self.geometry,
        )


def alpha_beta_fit(points: Sequence[Tuple[float, float]],
                   *, fallback_gbps: float,
                   fallback_lat_us: float) -> Tuple[float, float]:
    """Least-squares ``t = alpha + bytes/beta`` over ``(bytes, secs)``
    points; returns ``(bandwidth_gbps, latency_us)``. A non-positive or
    degenerate slope (timer noise at CPU speeds) falls back to the
    static values — a calibration must never produce a nonsensical
    model."""
    pts = [(float(b), float(s)) for b, s in points]
    n = len(pts)
    if n < 2:
        return fallback_gbps, fallback_lat_us
    sx = sum(b for b, _ in pts)
    sy = sum(s for _, s in pts)
    sxx = sum(b * b for b, _ in pts)
    sxy = sum(b * s for b, s in pts)
    denom = n * sxx - sx * sx
    if denom <= 0:
        return fallback_gbps, fallback_lat_us
    slope = (n * sxy - sx * sy) / denom       # seconds per byte
    intercept = (sy - slope * sx) / n          # seconds
    if slope <= 0 or not (slope < float("inf")):
        return fallback_gbps, fallback_lat_us
    bandwidth_gbps = 1.0 / (slope * 1e9)
    latency_us = max(0.0, intercept * 1e6)
    return bandwidth_gbps, latency_us


def _time_call(fn, *args, reps: int = 3) -> float:
    """Min-of-reps wall time of a blocking jitted call (first call
    compiles and is discarded)."""
    import jax

    jax.block_until_ready(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _sweep_level(axis: str, sizes: Sequence[int],
                 reps: int) -> List[Tuple[float, float]]:
    """(bytes, seconds) of one directed ``lax.ppermute`` ring hop over
    ``axis`` at each payload size — n fp32 elements per device travel
    exactly one link of that class."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh = basics.mesh()
    k = mesh.shape[axis]
    perm = [(i, (i + 1) % k) for i in range(k)]
    world_axes = basics.world_axes()
    pts: List[Tuple[float, float]] = []
    for n in sizes:
        x = jnp.arange(basics.size() * int(n), dtype=jnp.float32)

        def hop(xs):
            return lax.ppermute(xs, axis, perm)

        fn = jax.jit(basics.shard_map(
            hop, mesh=mesh, in_specs=P(world_axes),
            out_specs=P(world_axes)))
        pts.append((float(n) * 4.0, _time_call(fn, x, reps=reps)))
    return pts


def _sweep_quant(sizes: Sequence[int],
                 reps: int) -> List[Tuple[float, float]]:
    """(fp bytes, seconds) of the blockwise int8 quantize +
    dequant-accumulate kernel pair (the XLA composition — the rate the
    cost model charges; the Pallas backend is modeled at 2x it)."""
    import jax
    import jax.numpy as jnp

    from .compiler import _dequant_accumulate, _quantize_blocks
    from . import ir as _ir

    blk = 256
    pts: List[Tuple[float, float]] = []
    for n in sizes:
        nb = max(1, int(n) // blk)
        x = jnp.arange(nb * blk, dtype=jnp.float32).reshape(1, nb, blk)

        def pair(blocks):
            q, scales, _ = _quantize_blocks(blocks, _ir.XLA)
            return _dequant_accumulate(q, scales, _ir.XLA)

        fn = jax.jit(pair)
        pts.append((float(nb * blk) * 4.0, _time_call(fn, x, reps=reps)))
    return pts


def calibrate_links(*, sizes: Sequence[int] = DEFAULT_SWEEP_ELEMS,
                    reps: int = 3, store: bool = True) -> Calibration:
    """Run the microbenchmark sweep on the LIVE mesh (``hvd.init`` must
    have run) and return (and by default persist) the fitted
    :class:`Calibration`.

    Levels the mesh does not have (no cross hosts, no pods) are skipped
    — their link classes keep the static defaults, which is correct:
    they carry no traffic on this geometry."""
    if not basics.is_initialized():
        raise RuntimeError(
            "calibrate_links() needs an initialized mesh — call "
            "horovod_tpu.init() first")
    static = CostModel.from_env()
    geometry = basics.mesh_geometry()
    levels = {"ici": basics.LOCAL_AXIS, "dcn": basics.CROSS_AXIS}
    if basics.pod_size() > 1:
        levels["pod"] = basics.POD_AXIS
    mesh = basics.mesh()
    points: Dict[str, List[Tuple[float, float]]] = {}
    links: Dict[str, LinkClass] = {}
    t0 = time.perf_counter()
    for hop, axis in levels.items():
        if mesh.shape[axis] < 2:
            continue  # a size-1 level has no link to measure
        pts = _sweep_level(axis, sizes, reps)
        fb = static.link(hop)
        bw, lat = alpha_beta_fit(pts, fallback_gbps=fb.bandwidth_gbps,
                                 fallback_lat_us=fb.latency_us)
        points[hop] = pts
        links[hop] = LinkClass(bw, lat, fb.quant_rate_gbps)
    qpts = _sweep_quant(sizes, reps)
    qrate, _ = alpha_beta_fit(
        qpts, fallback_gbps=static.dcn.quant_rate_gbps,
        fallback_lat_us=0.0)
    points["quant"] = qpts
    links = {hop: dataclasses.replace(lk, quant_rate_gbps=qrate)
             for hop, lk in links.items()}
    calib = Calibration(geometry=geometry, links=links, points=points,
                        created_unix=time.time())
    log.warning(
        "horovod_tpu calibrate: %s swept %d link class(es) x %d sizes "
        "in %.1fs -> %s", geometry, len(links), len(sizes),
        time.perf_counter() - t0,
        {h: f"{lk.bandwidth_gbps:.2f}GB/s@{lk.latency_us:.1f}us"
         for h, lk in links.items()})
    if store:
        store_calibration(calib)
    return calib


# ---------------------------------------------------------------------------
# Persistence — same read-merge-write + atomic-replace discipline as the
# autotune cache it lives beside (ops/kernel_autotune.py).
# ---------------------------------------------------------------------------


def store_calibration(calib: Calibration) -> None:
    path = calibration_path()
    key = f"linkcal|{calib.geometry}|v{calib.version}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        import fcntl

        with open(path + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            disk: dict = {}
            try:
                with open(path) as f:
                    disk = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError, ValueError):
                pass
            if not isinstance(disk, dict):
                disk = {}
            disk[key] = calib.to_dict()
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(disk, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        log.info("calibration stored under %s in %s", key, path)
    except OSError as e:  # persistence is an optimization, never fatal
        log.warning("calibration write to %s failed (%s); the sweep "
                    "stays in-process only", path, e)


def load_calibration(mesh_shape=None) -> Optional[Calibration]:
    """The stored calibration for this geometry, or None when the file
    is missing/corrupted (logged warning) or holds no entry for this
    geometry key (a mismatched mesh/world/chip forces a re-sweep)."""
    path = calibration_path()
    key = geometry_key(mesh_shape)
    try:
        with open(path) as f:
            disk = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, ValueError) as e:
        log.warning(
            "horovod_tpu calibrate: calibration file %s unreadable "
            "(%s: %s) — falling back to the static HOROVOD_BENCH_* "
            "link model", path, type(e).__name__, e)
        return None
    entry = disk.get(key) if isinstance(disk, dict) else None
    if entry is None:
        log.info("calibration %s has no entry for %s (geometry changed "
                 "or never swept) — re-sweep or static defaults apply",
                 path, key)
        return None
    try:
        calib = Calibration.from_dict(entry)
    except (KeyError, TypeError, ValueError) as e:
        log.warning(
            "horovod_tpu calibrate: calibration entry %s in %s is "
            "malformed (%s: %s) — falling back to the static "
            "HOROVOD_BENCH_* link model", key, path,
            type(e).__name__, e)
        return None
    return calib


def get_cost_model(mesh_shape=None, *,
                   calibrate_missing: bool = False) -> CostModel:
    """The best available cost model for this geometry: calibrated when
    a matching sweep is stored, optionally sweeping on a miss
    (``calibrate_missing``, needs a live mesh), else the static env
    defaults. Never raises."""
    try:
        calib = load_calibration(mesh_shape)
        if calib is not None:
            return calib.cost_model()
        if calibrate_missing and basics.is_initialized() \
                and mesh_shape is None:
            return calibrate_links().cost_model()
    except Exception as e:  # never let pricing break training
        log.warning(
            "horovod_tpu calibrate: cost-model resolution failed "
            "(%s: %s) — using the static HOROVOD_BENCH_* link model",
            type(e).__name__, e)
    return CostModel.from_env()
