"""horovod_tpu.plan: the composable wire-plan IR (docs/wire-plan.md).

A collective is a :class:`WirePlan` — an ordered list of :class:`Leg`\\ s,
each naming a mesh level (ICI ring / DCN cross / pod axis), a primitive
(reduce-scatter, all-gather, all-to-all, psum), a wire dtype (payload /
blockwise-int8 with error-feedback slot), and a stream assignment — plus:

* a **compiler** (:mod:`~horovod_tpu.plan.compiler`) lowering a validated
  plan to the existing jax primitives, with trace-time wire accounting
  and overlap instrumentation built into every leg
  (:mod:`~horovod_tpu.plan.accounting`);
* a **planner** (:mod:`~horovod_tpu.plan.planner`) deriving the default
  plan from (mesh shape, quantized, zero_stage, overlap, hierarchical),
  so today's knob combinations are points in one plan space —
  :func:`describe_plan` is the debug view, and :func:`encode_tuned` /
  :func:`decode_tuned` the autotuner's compact search encoding.

Every public collective (``hvd.allreduce`` / ``reduce_scatter`` /
``all_gather`` and their ``*_stream`` variants) routes through this
compiler; the bespoke hand-composed paths it replaced live on only as
leg lowering rules in :mod:`~horovod_tpu.plan.compiler`.

The plan space is also a **priced design space** (docs/cost-model.md):
:mod:`~horovod_tpu.plan.cost` gives every link class a calibrated
``(bandwidth, latency, quant-rate)`` triple
(:mod:`~horovod_tpu.plan.calibrate` measures them with a
microbenchmark sweep stored beside the autotune cache) and prices any
validated plan analytically; :func:`shortlist` enumerates + prices the
legal plan space for a knob set into the ranked candidate list the GP
autotuner warm-starts from (``autotune_session(warm_start=K)``).
"""

from .ir import (  # noqa: F401
    ALL_GATHER,
    ALL_TO_ALL,
    BACKENDS,
    DCN,
    FLAT,
    ICI,
    INT8,
    PALLAS,
    PAYLOAD,
    POD,
    PSUM,
    REDUCE_SCATTER,
    SEND,
    XLA,
    Leg,
    PlanError,
    WirePlan,
)
from .accounting import (  # noqa: F401
    WireStats,
    bench_gbps,
    fused_span,
    kv_span,
    modeled_wire_ms,
    moe_span,
    record_wire_stats,
)
from .planner import (  # noqa: F401
    PricedPlan,
    StepPlan,
    a2a_plan,
    decode_tuned,
    derive_a2a,
    derive_all_gather,
    derive_allreduce,
    derive_reduce_scatter,
    describe_plan,
    encode_tuned,
    enumerate_tuned,
    ep_a2a_level,
    flat_plan,
    fused_ag_matmul_plan,
    fused_matmul_rs_plan,
    derive_kv_migrate,
    derive_send,
    kv_migrate_level,
    kv_migrate_plan,
    pp_bubble_bound,
    pp_send_level,
    predict_a2a_bytes,
    predict_kv_migrate_bytes,
    predict_fused_hbm_saved,
    predict_leg_bytes,
    quantized_allreduce_plan,
    send_plan,
    shortlist,
    tree_allreduce_plan,
    zero_all_gather_plan,
    zero_reduce_scatter_plan,
)
from .cost import (  # noqa: F401
    CostModel,
    LinkClass,
    PlanCost,
    StepCost,
    price_a2a,
    price_kv_migrate,
    price_plan,
    price_send,
    price_step,
)
from .calibrate import (  # noqa: F401
    Calibration,
    calibrate_links,
    get_cost_model,
    load_calibration,
)
from . import compiler  # noqa: F401
