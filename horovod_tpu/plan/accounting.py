"""Trace-time wire accounting + overlap-stream instrumentation.

This is the instrumentation half of the plan compiler: every lowered leg
accounts the bytes it puts on each link class at TRACE time (collectives
are traced once per compile, so static per-step byte counts cost nothing
at runtime), and every overlap-scheduled bucket collective is bracketed
with an ``OVERLAP:*`` timeline span plus per-bucket byte/latency
histograms. Because the lowering rules live in ONE place
(:mod:`horovod_tpu.plan.compiler`), every plan is instrumented for free —
no per-path bookkeeping to forget.

The cost model is per-device bytes SENT under ring/topology-aware
schedules: reduce-scatter or all-gather of n elements over k ranks moves
``n*(k-1)/k``, a full allreduce ``2*n*(k-1)/k``; a flat psum over the
mesh axes is modeled as XLA's topology-aware decomposition (ICI leg on
the full payload, DCN leg on the 1/local_size shard, pod leg on the
1/(local*cross) shard). ``dcn_bytes_fp`` tracks what the SAME traffic
pattern would cost at the payload's uncompressed dtype, so
``dcn_bytes_fp / dcn_bytes`` is the wire-representation reduction of the
quantized path (EQuARX's "~4x wire bytes" accounting).

Public surface is re-exported through ``ops.collective_ops``
(``record_wire_stats``/``WireStats``) for compatibility.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

from ..common import basics
from ..monitor import registry as _metrics


class WireStats:
    """Accumulated per-device wire bytes for one traced program."""

    def __init__(self) -> None:
        self.ici_bytes = 0.0
        self.dcn_bytes = 0.0
        self.dcn_bytes_fp = 0.0
        # Cross-POD hop bytes — DCN-class wire physically, but its own
        # link class so 3-level meshes can model an asymmetric pod
        # bandwidth (HOROVOD_BENCH_POD_GBPS) instead of the uniform-DCN
        # assumption (docs/wire-plan.md).
        self.pod_bytes = 0.0
        self.pod_bytes_fp = 0.0
        # Bytes issued through the overlap stream schedule (the
        # allreduce_stream / reduce_scatter_stream / all_gather_stream
        # entry points, docs/overlap.md) — wire traffic positioned so the
        # latency-hiding scheduler can run it under independent compute.
        self.overlap_bytes = 0.0
        self.streamed_buckets = 0
        # HBM round-trip bytes the fused Pallas kernels avoided vs the
        # separate-op lowering (docs/fused-kernels.md), plus how many
        # fused kernel calls the traced program contains.
        self.fused_hbm_saved_bytes = 0.0
        self.fused_calls = 0
        # Pipeline wire (docs/pipeline.md): bytes moved by send legs —
        # the inter-stage activation/activation-grad ppermutes of the
        # hvd_pp axis. Counted ON TOP of the per-hop ici/dcn/pod totals
        # (a send leg charges both), so the pipeline's share of each
        # link class is separable. ``pp_sends`` counts ppermute issues
        # (schedule ticks x directions).
        self.pp_bytes = 0.0
        self.pp_bytes_fp = 0.0
        self.pp_sends = 0
        # MoE wire (docs/moe.md): bytes moved by a2a legs — the expert
        # dispatch/combine row exchanges of the hvd_ep axis. Same
        # double-charging discipline as the pipeline wire: an a2a leg
        # charges its hop's per-hop total AND these counters, so the
        # MoE share of each link class is separable. ``a2a_calls``
        # counts exchange issues (layers x directions).
        self.a2a_bytes = 0.0
        self.a2a_bytes_fp = 0.0
        self.a2a_calls = 0
        # T3-style pipeline-bubble filling (docs/pipeline.md): bytes of
        # streamed bucket collectives issued inside a ``bubble_fill``
        # window — ZeRO-3 forward-order gathers / grad reduce-scatters
        # positioned so the latency-hiding scheduler runs them in the
        # schedule's idle ticks. A subset of ``overlap_bytes`` (a filled
        # flight is still overlap-scheduled); ``filled_ticks`` counts
        # how many of the schedule's idle ticks took a flight, capped at
        # the PPSchedule's per-rank idle-tick capacity.
        self.bubble_hidden_bytes = 0.0
        self.filled_ticks = 0
        # Serving KV-migration wire (docs/serving.md): bytes moved by
        # kv_migrate send legs — prefill→decode page handoffs between
        # replica groups. Same double-charging discipline as the
        # pipeline/MoE wires: a migration charges its hop's per-hop
        # total AND these counters, so the handoff share of each link
        # class is separable. ``kv_transfers`` counts whole-slot
        # migrations (not chunks).
        self.kv_bytes = 0.0
        self.kv_bytes_fp = 0.0
        self.kv_transfers = 0

    @property
    def dcn_reduction(self) -> Optional[float]:
        """fp-equivalent / actual bytes on the DCN hop (None if no DCN)."""
        return (self.dcn_bytes_fp / self.dcn_bytes) if self.dcn_bytes else None

    @property
    def hidden_fraction(self) -> float:
        """Fraction of this program's wire bytes issued through the
        overlap stream schedule (0.0 with overlap off; collectives
        outside the gradient bucket wire — loss allreduce, batch-stats —
        keep it below 1.0). The bench's ``comm_hidden_fraction``."""
        total = self.ici_bytes + self.dcn_bytes + self.pod_bytes
        return (self.overlap_bytes / total) if total else 0.0


_wire_recorders: list = []


def _acct_enabled() -> bool:
    """Wire accounting is live: an explicit ``record_wire_stats`` recorder
    is installed, or the metrics registry (enabled by default,
    docs/observability.md) is counting trace-time wire bytes. Still a
    trace-time-only cost — nothing here runs in the compiled step."""
    return bool(_wire_recorders) or _metrics.metrics_enabled()


@contextlib.contextmanager
def record_wire_stats():
    """Record wire bytes of every collective traced inside the context.
    Trace-time only: wrap ``jit(...).lower(...)`` (or the first call), not
    the steady-state execution loop. On exit the recorded profile is also
    published to the metrics registry (``comm.wire.*`` gauges — the last
    traced program's per-device wire bytes, hidden fraction included)."""
    ws = WireStats()
    _wire_recorders.append(ws)
    try:
        yield ws
    finally:
        _wire_recorders.remove(ws)
        _publish_wire_stats(ws)


def _publish_wire_stats(ws: "WireStats") -> None:
    if not _metrics.metrics_enabled():
        return
    r = _metrics.default_registry()
    r.counter("comm.traces").inc()
    r.gauge("comm.wire.ici_bytes").set(ws.ici_bytes)
    r.gauge("comm.wire.dcn_bytes").set(ws.dcn_bytes)
    r.gauge("comm.wire.dcn_bytes_fp").set(ws.dcn_bytes_fp)
    r.gauge("comm.wire.pod_bytes").set(ws.pod_bytes)
    r.gauge("comm.wire.overlap_bytes").set(ws.overlap_bytes)
    r.gauge("comm.wire.streamed_buckets").set(ws.streamed_buckets)
    r.gauge("comm.wire.hidden_fraction").set(ws.hidden_fraction)
    r.gauge("comm.wire.fused_hbm_saved_bytes").set(ws.fused_hbm_saved_bytes)
    r.gauge("comm.wire.pp_bytes").set(ws.pp_bytes)
    r.gauge("comm.wire.pp_sends").set(ws.pp_sends)
    r.gauge("comm.wire.bubble_hidden_bytes").set(ws.bubble_hidden_bytes)
    r.gauge("comm.wire.filled_ticks").set(ws.filled_ticks)
    r.gauge("comm.wire.a2a_bytes").set(ws.a2a_bytes)
    r.gauge("comm.wire.a2a_calls").set(ws.a2a_calls)
    r.gauge("comm.wire.kv_bytes").set(ws.kv_bytes)
    r.gauge("comm.wire.kv_transfers").set(ws.kv_transfers)


def _acct(kind: str, wire_bytes: float, fp_bytes: Optional[float] = None):
    """Account ``wire_bytes`` per-device bytes on one link class.
    ``kind`` is ``"ici"`` for intra-host links, ``"dcn"`` for the
    cross-host hop, ``"pod"`` for the cross-pod hop of a 3-level mesh
    (DCN-class wire physically, but modeled at its own bandwidth)."""
    if _metrics.metrics_enabled():
        _metrics.counter("comm.bytes", hop=kind).inc(wire_bytes)
        if kind in ("dcn", "pod"):
            _metrics.counter("comm.bytes_fp_equiv", hop=kind).inc(
                wire_bytes if fp_bytes is None else fp_bytes)
    for ws in _wire_recorders:
        if kind == "dcn":
            ws.dcn_bytes += wire_bytes
            ws.dcn_bytes_fp += wire_bytes if fp_bytes is None else fp_bytes
        elif kind == "pod":
            ws.pod_bytes += wire_bytes
            ws.pod_bytes_fp += wire_bytes if fp_bytes is None else fp_bytes
        else:
            ws.ici_bytes += wire_bytes


def bench_gbps() -> tuple:
    """(ici, dcn, pod) modeled link bandwidths in GB/s — the
    HOROVOD_BENCH_{ICI,DCN,POD}_GBPS knobs behind every modeled-time
    number (bench.py step_time_breakdown, the per-bucket latency
    histograms). The pod knob defaults to the DCN value, so 2-level
    meshes and unset-knob runs behave exactly as before."""
    ici = float(os.environ.get("HOROVOD_BENCH_ICI_GBPS", "100"))
    dcn = float(os.environ.get("HOROVOD_BENCH_DCN_GBPS", "25"))
    pod = float(os.environ.get("HOROVOD_BENCH_POD_GBPS", str(dcn)))
    return ici, dcn, pod


def modeled_wire_ms(ici_bytes: float, dcn_bytes: float,
                    pod_bytes: float = 0.0) -> float:
    """Modeled transfer time of a payload at the bench's (env-overridable)
    link bandwidths — the same HOROVOD_BENCH_ICI_GBPS/DCN_GBPS/POD_GBPS
    model behind bench.py's step_time_breakdown. On the compiled path this
    is the only per-bucket latency that exists at trace time (XLA owns the
    runtime schedule); the eager path measures wall time instead. Applied
    to a :class:`WireStats` record this is the "measured" side of the
    cost-model drift gate (docs/cost-model.md): what the traced program's
    actual wire bytes cost at the modeled bandwidths."""
    ici, dcn, pod = bench_gbps()
    return (ici_bytes / (ici * 1e9) + dcn_bytes / (dcn * 1e9)
            + pod_bytes / (pod * 1e9)) * 1e3


# Back-compat private alias (pre-cost-model spelling).
_modeled_wire_ms = modeled_wire_ms


# Active bubble-fill windows (docs/pipeline.md): a stack because
# nesting is legal (an inner window narrows the budget). Each entry is
# a mutable dict: remaining fill capacity in ticks, flights credited,
# bytes credited, and the window's label.
_fill_windows: list = []


@contextlib.contextmanager
def bubble_fill(capacity_ticks: int, kind: str = "zero3"):
    """T3-style pipeline-bubble fill window (docs/pipeline.md).

    While the window is active, every streamed bucket collective that
    closes (:func:`overlap_stream` — the ZeRO-3 forward-order
    ``all_gather_stream`` flights, the grad reduce-scatter flights) is
    ADDITIONALLY credited as bubble-filled: one flight consumes one of
    the schedule's idle ticks (``PPSchedule.idle_ticks_per_rank`` — the
    fill capacity is rank-uniform by construction), its bytes land on
    ``WireStats.bubble_hidden_bytes``, and the ``comm.pp.filled_ticks``
    / ``comm.pp.bubble_hidden_bytes`` counters bump. Flights beyond the
    capacity get NO credit — the bubble cannot hide more flights than
    it has ticks.

    Trace-time only, like all accounting here: the wrapped collectives
    are issued uniformly on every rank (SPMD collectives cannot be
    per-rank-conditional), positioned adjacent to the schedule scan so
    the latency-hiding scheduler runs them in the idle ticks; this
    window is the accounting contract that prices the placement.
    Yields the window record so callers can read ``filled``/``bytes``.
    """
    tl = basics._state.timeline if basics.is_initialized() else None
    activity = "PP:FILL"
    win = {"remaining": max(0, int(capacity_ticks)), "filled": 0,
           "bytes": 0.0, "kind": str(kind)}
    _fill_windows.append(win)
    if tl is not None:
        tl.begin("pp", activity)
    try:
        yield win
    finally:
        _fill_windows.remove(win)
        if tl is not None:
            tl.end("pp", activity)


def _credit_bubble_fill(delta: float, outer: list) -> None:
    """One streamed flight closed under an active fill window: consume
    an idle tick and credit its bytes as bubble-hidden (every window on
    the stack narrows independently, so nested budgets both count)."""
    credited = False
    for win in _fill_windows:
        if win["remaining"] > 0:
            win["remaining"] -= 1
            win["filled"] += 1
            win["bytes"] += delta
            credited = True
            if _metrics.metrics_enabled():
                _metrics.counter("comm.pp.filled_ticks",
                                 kind=win["kind"]).inc()
                _metrics.counter("comm.pp.bubble_hidden_bytes",
                                 kind=win["kind"]).inc(delta)
    if credited:
        for ws in outer:
            ws.bubble_hidden_bytes += delta
            ws.filled_ticks += 1


@contextlib.contextmanager
def overlap_stream(kind: str, bucket_id):
    """Bracket one streamed bucket collective: emit an ``OVERLAP:<kind>``
    timeline span (host trace time), account the bytes the wrapped
    collective records as overlap-scheduled, and feed the per-bucket
    bytes / modeled-latency histograms of the metrics registry. Inside
    an active :func:`bubble_fill` window the closing flight is also
    credited against the pipeline bubble's idle-tick budget."""
    tl = basics._state.timeline if basics.is_initialized() else None
    tid = f"bucket{bucket_id}"
    activity = f"OVERLAP:{kind}"
    own = WireStats()  # this bucket's bytes, recorder-independent
    _wire_recorders.append(own)
    outer = [ws for ws in _wire_recorders if ws is not own]
    if tl is not None:
        tl.begin(tid, activity)
    try:
        yield
    finally:
        _wire_recorders.remove(own)
        delta = own.ici_bytes + own.dcn_bytes + own.pod_bytes
        for ws in outer:
            ws.overlap_bytes += delta
            ws.streamed_buckets += 1
        if _fill_windows:
            _credit_bubble_fill(delta, outer)
        if _metrics.metrics_enabled():
            r = _metrics.default_registry()
            r.counter("comm.streamed_buckets", kind=kind).inc()
            r.histogram("comm.bucket.bytes").observe(delta)
            # µs, not ms: the log2 buckets need the resolution (a small
            # bucket's modeled transfer is far under a millisecond).
            r.histogram("comm.bucket.latency_us").observe(
                modeled_wire_ms(own.ici_bytes, own.dcn_bytes,
                                own.pod_bytes) * 1e3)
        if tl is not None:
            tl.end(tid, activity)


def _acct_pp(hop: str, wire_bytes: float, fp_bytes: Optional[float] = None,
             sends: int = 1) -> None:
    """Account a pipeline send leg: charges ``wire_bytes`` to the ``hop``
    link class exactly like any other leg (so ``comm.bytes{hop}`` and
    the per-hop WireStats totals include it), and ADDITIONALLY to the
    pipeline's own counters so bench/obs can separate the inter-stage
    wire from the gradient wire (docs/pipeline.md)."""
    _acct(hop, wire_bytes, fp_bytes)
    if _metrics.metrics_enabled():
        _metrics.counter("comm.pp.bytes", hop=hop).inc(wire_bytes)
        _metrics.counter("comm.pp.sends", hop=hop).inc(sends)
    for ws in _wire_recorders:
        ws.pp_bytes += wire_bytes
        ws.pp_bytes_fp += wire_bytes if fp_bytes is None else fp_bytes
        ws.pp_sends += sends


def _acct_a2a(hop: str, wire_bytes: float,
              fp_bytes: Optional[float] = None, calls: int = 1) -> None:
    """Account a MoE a2a leg: charges ``wire_bytes`` to the ``hop`` link
    class exactly like any other leg (so ``comm.bytes{hop}`` and the
    per-hop WireStats totals include it), and ADDITIONALLY to the MoE
    wire's own counters so bench/obs can separate the expert
    dispatch/combine traffic from the gradient wire (docs/moe.md)."""
    _acct(hop, wire_bytes, fp_bytes)
    if _metrics.metrics_enabled():
        _metrics.counter("comm.moe.bytes", hop=hop).inc(wire_bytes)
        _metrics.counter("comm.moe.calls", hop=hop).inc(calls)
    for ws in _wire_recorders:
        ws.a2a_bytes += wire_bytes
        ws.a2a_bytes_fp += wire_bytes if fp_bytes is None else fp_bytes
        ws.a2a_calls += calls


def _acct_kv(hop: str, wire_bytes: float,
             fp_bytes: Optional[float] = None,
             transfers: int = 0) -> None:
    """Account a KV-migration send leg: charges ``wire_bytes`` to the
    ``hop`` link class exactly like any other leg (so
    ``comm.bytes{hop}`` and the per-hop WireStats totals include it),
    and ADDITIONALLY to the serving handoff's own counters so bench/obs
    can separate prefill→decode migration traffic from the training and
    pipeline wires (docs/serving.md). ``transfers`` bumps only when a
    whole slot finished migrating — chunked transfers charge bytes per
    chunk but one transfer per slot."""
    _acct(hop, wire_bytes, fp_bytes)
    if _metrics.metrics_enabled():
        _metrics.counter("comm.kv.bytes", hop=hop).inc(wire_bytes)
        if transfers:
            _metrics.counter("comm.kv.transfers", hop=hop).inc(transfers)
    for ws in _wire_recorders:
        ws.kv_bytes += wire_bytes
        ws.kv_bytes_fp += wire_bytes if fp_bytes is None else fp_bytes
        ws.kv_transfers += transfers


@contextlib.contextmanager
def kv_span(kind: str = "MIGRATE", tid: str = "serve"):
    """Bracket one KV-handoff wire event in a ``SERVE:KV_<kind>``
    timeline span (kinds today: ``MIGRATE`` — one chunk of a
    prefill→decode page transfer crossing the wire). Host-time span:
    unlike the trace-time collective spans, migrations run eagerly
    between engine steps (docs/serving.md)."""
    tl = basics._state.timeline if basics.is_initialized() else None
    activity = f"SERVE:KV_{kind}"
    if tl is not None:
        tl.begin(tid, activity)
    try:
        yield
    finally:
        if tl is not None:
            tl.end(tid, activity)


@contextlib.contextmanager
def moe_span(kind: str, tid: str = "moe"):
    """Bracket one MoE wire event in a ``MOE:<kind>`` timeline span
    (kinds today: ``DISPATCH`` — the token→expert a2a exchange;
    ``COMBINE`` — the expert→token return exchange). Trace-time only,
    like every span here (docs/moe.md)."""
    tl = basics._state.timeline if basics.is_initialized() else None
    activity = f"MOE:{kind}"
    if tl is not None:
        tl.begin(tid, activity)
    try:
        yield
    finally:
        if tl is not None:
            tl.end(tid, activity)


@contextlib.contextmanager
def pp_span(kind: str, tid: str = "pp"):
    """Bracket one pipeline event in a ``PP:<kind>`` timeline span
    (kinds today: ``SEND`` — one lowered send leg; ``F``/``B`` — a
    schedule slot's forward/backward chunk, emitted per rank by
    :func:`emit_schedule_spans`). Trace-time only, like every span
    here."""
    tl = basics._state.timeline if basics.is_initialized() else None
    activity = f"PP:{kind}"
    if tl is not None:
        tl.begin(tid, activity)
    try:
        yield
    finally:
        if tl is not None:
            tl.end(tid, activity)


# ---------------------------------------------------------------------------
# Fused-kernel instrumentation (docs/fused-kernels.md): every fused
# Pallas kernel call brackets itself in a FUSED:* span at trace time and
# accounts the HBM round-trip it avoided vs the separate-op lowering.
# Like the wire accounting this is trace-time-only — a compiled step
# re-executes with zero instrumentation cost.
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def fused_span(kind: str, hbm_saved_bytes: float = 0.0):
    """Bracket one fused kernel call: emit a ``FUSED:<kind>`` timeline
    span (kinds: ``MATMUL_RS``, ``AG_MATMUL``, ``QUANT``, ``DEQUANT``),
    bump the ``comm.fused.*`` metrics, and credit ``hbm_saved_bytes``
    (the modeled HBM round-trip the fusion avoids — the epilogue/
    prologue's intermediate that never materializes) to every active
    :func:`record_wire_stats` recorder."""
    tl = basics._state.timeline if basics.is_initialized() else None
    activity = f"FUSED:{kind}"
    if tl is not None:
        tl.begin("fused", activity)
    try:
        yield
    finally:
        if _metrics.metrics_enabled():
            r = _metrics.default_registry()
            r.counter("comm.fused.calls", kind=kind).inc()
            r.counter("comm.fused.hbm_saved_bytes", kind=kind).inc(
                float(hbm_saved_bytes))
        for ws in _wire_recorders:
            ws.fused_calls += 1
            ws.fused_hbm_saved_bytes += float(hbm_saved_bytes)
        if tl is not None:
            tl.end("fused", activity)
