"""Analytic plan pricing: predicted wire milliseconds for any legal plan.

HiCCL (arXiv:2408.05962) prices a hierarchical collective analytically
from per-link ``(bandwidth, latency)`` parameters instead of measuring
every composition; EQuARX (arXiv:2506.17615) shows the quantize-rate
tradeoff is itself a priceable term (compression buys wire bytes at the
cost of quantize/dequantize kernel time). This module is that model for
the wire-plan IR: every link class carries a measured

    ``(bandwidth_gbps, latency_us, quant_rate_gbps)``

triple — static env defaults (the ``HOROVOD_BENCH_*_GBPS`` knobs every
modeled-time number already uses), or a calibrated fit from the
:mod:`~horovod_tpu.plan.calibrate` microbenchmark sweep — and
:func:`price_plan` / :func:`price_step` turn a validated
:class:`~horovod_tpu.plan.ir.WirePlan` / :class:`~horovod_tpu.plan.
planner.StepPlan` into predicted milliseconds:

* **bytes term** — per-leg wire bytes (the exact
  :func:`~horovod_tpu.plan.planner.predict_leg_bytes` formulas the
  trace-time accounting charges) divided by the link bandwidth;
* **alpha term** — per-leg launch latency: a ring collective over ``k``
  ranks serializes ``k-1`` hops, each paying the link's latency, once
  per fused bucket (so the fusion threshold is priced: more buckets =
  more alphas) amortized over the overlap flight width;
* **quant term** — blockwise int8 quantize + dequant-accumulate kernel
  time on the fp-equivalent payload of every int8 leg at the link's
  ``quant_rate_gbps``; the fused Pallas backend halves it (one-pass VMEM
  kernels never round-trip the expansion through HBM,
  docs/fused-kernels.md);
* **overlap credit** — an overlap-scheduled plan hides its streamed wire
  under backward compute except the final flight's tail
  (``1/buckets`` of the wire, the PR-5 streaming machinery's exposed
  remainder), capped by the available ``compute_ms`` when the caller
  knows it.

The ``modeled_ms`` field of every priced leg is the PURE bytes/bandwidth
number at the static ``HOROVOD_BENCH_*_GBPS`` knobs — exactly what the
trace-time :class:`~horovod_tpu.plan.accounting.WireStats` model would
charge — so ``predicted - modeled`` is the drift surface the perf gate
checks (``scripts/perf_gate.sh cost``, docs/cost-model.md).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, List, Optional, Tuple

from . import ir
from .accounting import bench_gbps

# Static launch-latency defaults (microseconds per ring hop). ICI links
# are on-die/board traces; DCN and pod hops cross host NICs. Override
# with HOROVOD_BENCH_{ICI,DCN,POD}_LAT_US (pod defaults to the DCN
# value, like the bandwidth knob).
DEFAULT_ICI_LAT_US = 1.0
DEFAULT_DCN_LAT_US = 25.0

# Static blockwise int8 quantize+dequant processing rate (GB/s of
# fp-equivalent payload through the kernel pair). Override with
# HOROVOD_BENCH_QUANT_GBPS; the calibration sweep measures it.
DEFAULT_QUANT_GBPS = 50.0

HOPS = ("ici", "dcn", "pod")


@dataclasses.dataclass(frozen=True)
class LinkClass:
    """One link class of the machine hierarchy, as the cost model sees
    it: sustained ``bandwidth_gbps`` (GB/s per device), per-hop launch
    ``latency_us`` (the alpha of the alpha-beta model), and
    ``quant_rate_gbps`` — the rate the blockwise int8 quantize +
    dequant-accumulate kernel pair processes fp-equivalent payload
    destined for this link."""

    bandwidth_gbps: float
    latency_us: float
    quant_rate_gbps: float

    def as_dict(self) -> dict:
        return {"bandwidth_gbps": float(self.bandwidth_gbps),
                "latency_us": float(self.latency_us),
                "quant_rate_gbps": float(self.quant_rate_gbps)}

    @classmethod
    def from_dict(cls, d: dict) -> "LinkClass":
        return cls(bandwidth_gbps=float(d["bandwidth_gbps"]),
                   latency_us=float(d["latency_us"]),
                   quant_rate_gbps=float(d["quant_rate_gbps"]))


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Per-link-class parameters pricing every plan.

    ``source`` records provenance: ``"static"`` (the env-default
    triples) or ``"calibrated"`` (a :mod:`~horovod_tpu.plan.calibrate`
    sweep, in which case ``geometry`` names the mesh fingerprint the
    sweep ran on)."""

    ici: LinkClass
    dcn: LinkClass
    pod: LinkClass
    source: str = "static"
    geometry: Optional[str] = None

    def link(self, hop: str) -> LinkClass:
        if hop not in HOPS:
            raise ValueError(f"unknown link class {hop!r}: one of {HOPS}")
        return getattr(self, hop)

    def as_dict(self) -> dict:
        return {"ici": self.ici.as_dict(), "dcn": self.dcn.as_dict(),
                "pod": self.pod.as_dict(), "source": self.source,
                "geometry": self.geometry}

    @classmethod
    def from_env(cls) -> "CostModel":
        """The static model: bandwidths from the HOROVOD_BENCH_*_GBPS
        knobs (the same numbers behind every modeled-time report),
        latencies/quant rates from their env knobs or defaults."""
        ici_bw, dcn_bw, pod_bw = bench_gbps()
        ici_lat = float(os.environ.get("HOROVOD_BENCH_ICI_LAT_US",
                                       str(DEFAULT_ICI_LAT_US)))
        dcn_lat = float(os.environ.get("HOROVOD_BENCH_DCN_LAT_US",
                                       str(DEFAULT_DCN_LAT_US)))
        pod_lat = float(os.environ.get("HOROVOD_BENCH_POD_LAT_US",
                                       str(dcn_lat)))
        quant = float(os.environ.get("HOROVOD_BENCH_QUANT_GBPS",
                                     str(DEFAULT_QUANT_GBPS)))
        return cls(ici=LinkClass(ici_bw, ici_lat, quant),
                   dcn=LinkClass(dcn_bw, dcn_lat, quant),
                   pod=LinkClass(pod_bw, pod_lat, quant),
                   source="static")


@dataclasses.dataclass(frozen=True)
class LegCost:
    """Predicted cost of one leg for one full (unbucketed) payload.

    ``modeled_ms`` is the bytes/bandwidth number at the STATIC modeled
    bandwidths (the WireStats trace-time model); ``wire_ms`` the same
    bytes at the cost model's (possibly calibrated) bandwidth;
    ``alpha_ms`` the per-bucket launch latency of the leg's ring;
    ``quant_ms`` the int8 quantize/dequant kernel time. ``total_ms`` is
    wire + alpha + quant for a single-bucket issue."""

    leg: ir.Leg
    hop: str
    bytes: float
    modeled_ms: float
    wire_ms: float
    alpha_ms: float
    quant_ms: float

    @property
    def total_ms(self) -> float:
        return self.wire_ms + self.alpha_ms + self.quant_ms


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Aggregated cost of one :class:`~horovod_tpu.plan.ir.WirePlan`."""

    plan: ir.WirePlan
    legs: Tuple[LegCost, ...]

    def _sum(self, field: str) -> float:
        return sum(getattr(l, field) for l in self.legs)

    @property
    def wire_ms(self) -> float:
        return self._sum("wire_ms")

    @property
    def modeled_ms(self) -> float:
        return self._sum("modeled_ms")

    @property
    def alpha_ms(self) -> float:
        return self._sum("alpha_ms")

    @property
    def quant_ms(self) -> float:
        return self._sum("quant_ms")

    @property
    def total_ms(self) -> float:
        return self._sum("total_ms")

    def by_leg(self, leg: ir.Leg) -> Tuple[float, float]:
        """(modeled_ms, predicted_ms) summed over the rows charged to
        ``leg`` — the two --dump-plan table columns."""
        modeled = sum(l.modeled_ms for l in self.legs if l.leg is leg)
        pred = sum(l.total_ms for l in self.legs if l.leg is leg)
        return modeled, pred


@dataclasses.dataclass(frozen=True)
class StepCost:
    """Predicted per-step wire cost of a whole
    :class:`~horovod_tpu.plan.planner.StepPlan` (gradient plan + ZeRO
    gather plan when present), bucketed at the plan's fusion threshold.

    ``predicted_ms`` is the headline number (sync cost minus the overlap
    hiding credit); ``wire_ms``/``alpha_ms``/``quant_ms`` its additive
    terms; ``modeled_ms`` the pure bytes-at-modeled-bandwidth figure the
    drift gate compares against (identical formulas to the trace-time
    WireStats accounting)."""

    plan_costs: Tuple[PlanCost, ...]
    buckets: int
    flights: int
    wire_ms: float
    modeled_ms: float
    alpha_ms: float
    quant_ms: float
    hidden_ms: float
    source: str
    # Pipeline terms (docs/pipeline.md): the inter-stage send wire over
    # the whole schedule (2 x ticks issues) plus the bubble cost when
    # compute_ms is known — zero with pp off.
    pp_ms: float = 0.0
    pp_bubble_ms: float = 0.0
    # T3 bubble-fill credit (docs/pipeline.md): streamed ZeRO wire the
    # schedule's idle ticks absorb — the bubble is busy moving bytes
    # instead of idling, so the step does not pay both. Bounded by
    # pp_bubble_ms at construction; zero without pp + ZeRO-3 + overlap.
    pp_fill_ms: float = 0.0
    # MoE term (docs/moe.md): the expert dispatch/combine a2a wire (2
    # issues per MoE layer of a capacity-factor-scaled buffer) — zero
    # with MoE off.
    moe_ms: float = 0.0

    @property
    def sync_ms(self) -> float:
        return self.wire_ms + self.alpha_ms + self.quant_ms

    @property
    def predicted_ms(self) -> float:
        return (self.sync_ms - self.hidden_ms + self.pp_ms
                + self.pp_bubble_ms - self.pp_fill_ms + self.moe_ms)

    def as_dict(self) -> dict:
        return {
            "predicted_ms": round(self.predicted_ms, 6),
            "wire_ms": round(self.wire_ms, 6),
            "modeled_ms": round(self.modeled_ms, 6),
            "alpha_ms": round(self.alpha_ms, 6),
            "quant_ms": round(self.quant_ms, 6),
            "hidden_ms": round(self.hidden_ms, 6),
            "pp_ms": round(self.pp_ms, 6),
            "pp_bubble_ms": round(self.pp_bubble_ms, 6),
            "pp_fill_ms": round(self.pp_fill_ms, 6),
            "moe_ms": round(self.moe_ms, 6),
            "buckets": self.buckets,
            "model": self.source,
        }


def _ring_size(hop: str, mesh_sizes: Tuple[int, int, int]) -> int:
    nl, nc, npod = mesh_sizes
    return {ir.LEVEL_HOP[ir.ICI]: nl, ir.LEVEL_HOP[ir.DCN]: nc,
            ir.LEVEL_HOP[ir.POD]: npod}.get(hop, 1)


def price_plan(plan: ir.WirePlan, n: int, itemsize: float, mesh_shape,
               model: Optional[CostModel] = None, *,
               buckets: int = 1, ep: int = 0) -> PlanCost:
    """Price one plan for a payload of ``n`` elements: per-leg bytes
    from the exact trace-time formulas, alpha per ring hop per bucket,
    quant kernel time on the int8 legs' fp-equivalent payload. ``ep``
    is the hvd_ep exchange width of an ``a2a`` plan (docs/moe.md)."""
    from . import planner as _planner  # call-time: planner imports cost

    model = model or CostModel.from_env()
    static = CostModel.from_env()
    nl, nc, npod = _planner._mesh_sizes(mesh_shape)
    rows = _planner.predict_leg_bytes(plan, n, itemsize, mesh_shape,
                                      ep=ep)
    legs: List[LegCost] = []
    for r in rows:
        hop, b = r["hop"], float(r["bytes"])
        if hop not in HOPS:
            legs.append(LegCost(r["leg"], hop, b, 0.0, 0.0, 0.0, 0.0))
            continue
        lk = model.link(hop)
        k = _ring_size(hop, (nl, nc, npod))
        wire_ms = b / (lk.bandwidth_gbps * 1e9) * 1e3
        modeled_ms = b / (static.link(hop).bandwidth_gbps * 1e9) * 1e3
        if r["leg"].primitive in (ir.SEND, ir.ALL_TO_ALL):
            # A send leg is ONE point-to-point hop, and a tiled
            # all_to_all lowers to ONE fused exchange — exactly one
            # launch latency per issue (docs/pipeline.md, docs/moe.md).
            alpha_ms = lk.latency_us * buckets / 1e3
        else:
            alpha_ms = lk.latency_us * max(0, k - 1) * buckets / 1e3
        quant_ms = 0.0
        if r["leg"].wire_dtype == ir.INT8:
            # Quantize + dequant-accumulate on the fp-equivalent payload
            # of this hop; the fused one-pass VMEM kernels skip the HBM
            # round-trip of the int8/fp32 expansion — half the cost
            # (docs/fused-kernels.md).
            rate = lk.quant_rate_gbps * 1e9
            quant_ms = float(r["fp_bytes"]) / rate * 1e3
            if r["leg"].backend == ir.PALLAS:
                quant_ms *= 0.5
        legs.append(LegCost(r["leg"], hop, b, modeled_ms, wire_ms,
                            alpha_ms, quant_ms))
    return PlanCost(plan, tuple(legs))


def price_step(step_plan, payload_bytes: float, *,
               itemsize: float = 4.0, mesh_shape=None,
               model: Optional[CostModel] = None,
               compute_ms: Optional[float] = None) -> StepCost:
    """Price a resolved :class:`~horovod_tpu.plan.planner.StepPlan` for
    a gradient payload of ``payload_bytes``.

    The fusion threshold buckets the payload (``ceil(payload /
    threshold)`` collectives per plan); each bucket pays every leg's
    alpha, amortized over the overlap flight width
    (``num_comm_streams`` buckets issue per flight). With ``overlap``
    on, the streamed wire hides under backward compute except the last
    flight's tail — ``compute_ms`` caps the credit when known (pass
    ``None`` to assume ample compute, the shortlist-ranking default)."""
    model = model or CostModel.from_env()
    mesh_shape = mesh_shape if mesh_shape is not None \
        else step_plan.mesh_shape
    n = max(1, int(payload_bytes / max(1e-9, itemsize)))
    thr = max(1, int(step_plan.fusion_threshold_bytes))
    buckets = max(1, int(math.ceil(payload_bytes / thr)))
    streams = max(1, int(step_plan.num_comm_streams)) \
        if step_plan.overlap else 1
    flights = int(math.ceil(buckets / streams))
    plan_costs = tuple(
        price_plan(p, n, itemsize, mesh_shape, model, buckets=1)
        for p in step_plan.plans)
    wire_ms = sum(pc.wire_ms for pc in plan_costs)
    modeled_ms = sum(pc.modeled_ms for pc in plan_costs)
    quant_ms = sum(pc.quant_ms for pc in plan_costs)
    # Alpha: every leg's ring latency once per FLIGHT (buckets in the
    # same flight launch together; their latencies overlap).
    alpha_ms = sum(pc.alpha_ms for pc in plan_costs) * flights
    hidden_ms = 0.0
    if step_plan.overlap and buckets > 1:
        hideable = wire_ms * (1.0 - 1.0 / buckets)
        hidden_ms = (hideable if compute_ms is None
                     else max(0.0, min(hideable, float(compute_ms))))
    moe_ms = 0.0
    moe = getattr(step_plan, "moe", None)
    experts = int(getattr(step_plan, "moe_experts", 0) or 0)
    if moe is not None and experts > 1:
        # MoE pricing (docs/moe.md): one MoE layer issues two a2a
        # exchanges per step (dispatch + combine) of a dispatch buffer
        # sized capacity_factor x the activation payload — approximated
        # against the caller's payload when no activation size is
        # known, which preserves the ranking the shortlist needs: a
        # bigger capacity factor moves proportionally more bytes, the
        # int8 wire moves ~4x fewer at quantize-kernel cost.
        cap = float(getattr(step_plan, "moe_capacity_factor", 0.0)
                    or 1.0)
        buf_n = max(1, int(n * max(0.25, cap)))
        mpc = price_plan(moe, buf_n, itemsize, mesh_shape, model,
                         ep=experts)
        moe_ms = mpc.total_ms * 2
    pp_ms = 0.0
    pp_bubble_ms = 0.0
    pp_fill_ms = 0.0
    send = getattr(step_plan, "send", None)
    stages = int(getattr(step_plan, "pp_stages", 0) or 0)
    if send is not None and stages > 1:
        # Pipeline pricing (docs/pipeline.md): the schedule issues
        # ~2*(M*v + S - 1) send hops per step (one activation + one
        # grad hop per tick) of a per-microbatch activation payload —
        # approximated as payload/M when the caller has no activation
        # size to give — and the interleaved bubble idles
        # (S-1)/(M*v + S - 1) of the step when compute_ms is known.
        M = max(1, int(step_plan.pp_microbatches or 2 * stages))
        v = max(1, int(getattr(step_plan, "pp_interleave", 1) or 1))
        act_n = max(1, n // M)
        spc = price_plan(send, act_n, itemsize, mesh_shape, model)
        ticks = 2 * M * v + 2 * (stages - 1)
        pp_ms = spc.total_ms * ticks
        if compute_ms is not None:
            sched_name = str(getattr(step_plan, "pp_schedule", "") or "")
            if sched_name == "zb1":
                # Zero-bubble: the analytic interleaved bound no longer
                # applies — price the EXACT measured bubble of the zb
                # tables (the same builder the step executes).
                from ..parallel import pipeline as _pipeline  # lazy: cycle

                try:
                    bf = _pipeline.build_interleaved_schedule(
                        M, stages, v, family="zb1").bubble_fraction
                except ValueError:
                    # un-buildable geometry (e.g. M % S with v > 1):
                    # fall back to the analytic interleaved bound
                    bf = (stages - 1) / (M * v + stages - 1)
            else:
                bf = (stages - 1) / (M * v + stages - 1)
            pp_bubble_ms = float(compute_ms) * bf / max(1e-9, 1.0 - bf)
            # T3 fill credit (docs/pipeline.md): with ZeRO-3 + overlap
            # the forward-order bucket gathers issue into the bubble's
            # idle ticks, so the streamed wire NOT already hidden under
            # backward compute is absorbed by the bubble instead —
            # capped at the bubble itself (it cannot hide more wire
            # than it has idle time).
            if (int(getattr(step_plan, "zero_stage", 0) or 0) >= 3
                    and step_plan.overlap
                    and getattr(step_plan, "gather", None) is not None):
                pp_fill_ms = min(pp_bubble_ms,
                                 max(0.0, wire_ms - hidden_ms))
    return StepCost(plan_costs=plan_costs, buckets=buckets,
                    flights=flights, wire_ms=wire_ms,
                    modeled_ms=modeled_ms, alpha_ms=alpha_ms,
                    quant_ms=quant_ms, hidden_ms=hidden_ms,
                    source=model.source, pp_ms=pp_ms,
                    pp_bubble_ms=pp_bubble_ms, pp_fill_ms=pp_fill_ms,
                    moe_ms=moe_ms)


def price_a2a(plan: ir.WirePlan, payload_bytes: float, *,
              ep: int, issues: int = 1, itemsize: float = 4.0,
              mesh_shape=(1, 1),
              model: Optional[CostModel] = None) -> dict:
    """Price ``issues`` identical a2a exchanges of a ``payload_bytes``
    dispatch buffer over ``ep`` expert groups: the per-exchange
    wire/alpha/quant terms times the layer's issue count (two per MoE
    layer — dispatch, then combine) — the predicted side of the bench
    ``--moe`` leg's a2a drift pair (docs/moe.md). ``modeled_ms`` is the
    same bytes at the static modeled bandwidths, exactly what the
    trace-time accounting would charge for the same issues."""
    model = model or CostModel.from_env()
    n = max(1, int(payload_bytes / max(1e-9, itemsize)))
    pc = price_plan(plan, n, itemsize, mesh_shape, model, ep=ep)
    return {
        "predicted_ms": pc.total_ms * issues,
        "modeled_ms": pc.modeled_ms * issues,
        "wire_bytes": sum(l.bytes for l in pc.legs) * issues,
        "model": model.source,
    }


def price_send(plan: ir.WirePlan, payload_bytes: float, *,
               issues: int = 1, itemsize: float = 4.0,
               mesh_shape=(1, 1),
               model: Optional[CostModel] = None) -> dict:
    """Price ``issues`` identical send-plan hops of a ``payload_bytes``
    activation: the per-send wire/alpha/quant terms times the schedule's
    issue count — the predicted side of the bench ``--pp`` leg's
    send-leg drift pair (docs/pipeline.md). ``modeled_ms`` is the same
    bytes at the static modeled bandwidths, exactly what the trace-time
    accounting would charge for the same issues."""
    model = model or CostModel.from_env()
    n = max(1, int(payload_bytes / max(1e-9, itemsize)))
    pc = price_plan(plan, n, itemsize, mesh_shape, model)
    return {
        "predicted_ms": pc.total_ms * issues,
        "modeled_ms": pc.modeled_ms * issues,
        "wire_bytes": sum(l.bytes for l in pc.legs) * issues,
        "model": model.source,
    }


def price_kv_migrate(plan: ir.WirePlan, payload_bytes: float, *,
                     transfers: int = 1, itemsize: float = 4.0,
                     mesh_shape=(1, 1),
                     model: Optional[CostModel] = None) -> dict:
    """Price ``transfers`` prefill→decode KV handoffs of a
    ``payload_bytes`` slot payload each: the per-migration
    wire/alpha/quant terms times the handoff count — the predicted side
    of the bench ``--disagg`` leg's migration drift pair
    (docs/serving.md). ``modeled_ms`` is the same bytes at the static
    modeled bandwidths, exactly what :func:`~horovod_tpu.plan.compiler.
    lower_kv_migrate` charges for the same transfers (residual pass
    included — the leg-byte predictor doubles quantized bytes when the
    plan carries the error-feedback residual slot)."""
    model = model or CostModel.from_env()
    n = max(1, int(payload_bytes / max(1e-9, itemsize)))
    pc = price_plan(plan, n, itemsize, mesh_shape, model)
    return {
        "predicted_ms": pc.total_ms * transfers,
        "modeled_ms": pc.modeled_ms * transfers,
        "wire_bytes": sum(l.bytes for l in pc.legs) * transfers,
        "model": model.source,
    }


def predict_hop_ms(hop: str, nbytes: float,
                   model: Optional[CostModel] = None) -> float:
    """Predicted transfer milliseconds of ``nbytes`` on one link class
    under the resolved (calibrated-else-static) cost model: the
    bytes/bandwidth term plus one launch latency. This is the
    *predicted* side of the monitor layer's link-health score
    (``monitor/straggler.observe_wire``, docs/observability.md): a hop
    whose measured wire-ms persistently exceeds this prediction is
    either degraded or the calibration is stale."""
    model = model or resolve()
    lk = model.link(hop)
    return (float(nbytes) / (lk.bandwidth_gbps * 1e9) * 1e3
            + lk.latency_us / 1e3)


def resolve(mesh_shape=None) -> CostModel:
    """The cost model for ``mesh_shape``: the calibrated triples when a
    matching-geometry sweep is on disk (docs/cost-model.md), else the
    static env defaults. Never raises — pricing must never abort
    training."""
    from . import calibrate as _calibrate

    return _calibrate.get_cost_model(mesh_shape=mesh_shape)
