"""Request scheduling for the continuous-batching engine.

The admission/preemption policy is driven entirely by **page
availability** (kv_cache.PageAllocator): a request is admitted only when
a free batch slot exists AND the allocator can atomically grant the pages
its prompt plus one decode page need; a running sequence that outgrows
its grant when the pool is empty preempts the *youngest* running
sequence (LIFO — it has the least sunk prefill work), returning it to
the head of the queue with its progress folded into the prompt, so
nothing is ever dropped.

:class:`PoissonTrace` generates the deterministic open-loop arrival
pattern the bench/SLO story runs against (exponential inter-arrival
times, seeded).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .kv_cache import PageAllocator, PageConfig, PrefixCache

_req_counter = itertools.count()


@dataclass
class Request:
    """One generation request, mutated as it moves through the system.

    ``prompt`` may grow across preemption/drain cycles: already-generated
    tokens fold into it (``fold_progress``) so a re-admitted request
    replays prefill instead of losing work — the tokens count for
    *throughput* once, but only completed requests count for *goodput*.
    """

    prompt: List[int]
    max_new_tokens: int
    arrival_time: float = 0.0
    req_id: int = field(default_factory=lambda: next(_req_counter))
    # Filled in by the engine:
    generated: List[int] = field(default_factory=list)
    admit_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    finish_reason: Optional[str] = None   # "eos" | "length"
    preemptions: int = 0
    resizes: int = 0

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def latency(self) -> Optional[float]:
        if self.finish_time is None:
            return None
        return self.finish_time - self.arrival_time

    def fold_progress(self) -> None:
        """Fold generated tokens into the prompt (preemption / replica
        drain): the next admission replays them as prefill and generation
        resumes exactly where it stopped."""
        self.max_new_tokens = self.remaining_new_tokens
        self.prompt = list(self.prompt) + list(self.generated)
        self.generated = []


def _check_request(req: Request, cfg: PageConfig) -> None:
    total = len(req.prompt) + req.max_new_tokens
    if total > cfg.tokens_per_slot:
        raise ValueError(
            f"request {req.req_id}: prompt {len(req.prompt)} + "
            f"max_new_tokens {req.max_new_tokens} = {total} exceeds a "
            f"slot's capacity {cfg.tokens_per_slot} "
            f"(pages_per_slot {cfg.pages_per_slot} x page_size "
            f"{cfg.page_size})")
    if not req.prompt:
        raise ValueError(f"request {req.req_id}: empty prompt")


class PoissonTrace:
    """Deterministic Poisson arrival trace of synthetic requests.

    Inter-arrival gaps ~ Exp(rate); prompt lengths and generation budgets
    uniform over the given ranges; token ids uniform over ``vocab_size``
    (never equal to ``eos_id``, so only length-capped termination is
    deterministic). Same seed → same trace on every host.
    """

    def __init__(self, *, rate: float, num_requests: int, seed: int = 0,
                 prompt_len: Sequence[int] = (4, 16),
                 max_new_tokens: Sequence[int] = (4, 16),
                 vocab_size: int = 128, eos_id: int = 1) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0 req/s")
        self.rate = rate
        rng = np.random.RandomState(seed)
        gaps = rng.exponential(1.0 / rate, size=num_requests)
        arrivals = np.cumsum(gaps)
        self.requests: List[Request] = []
        for i in range(num_requests):
            n_prompt = int(rng.randint(prompt_len[0], prompt_len[1] + 1))
            n_new = int(rng.randint(max_new_tokens[0],
                                    max_new_tokens[1] + 1))
            toks = rng.randint(0, vocab_size, size=n_prompt)
            toks = np.where(toks == eos_id, (eos_id + 1) % vocab_size, toks)
            self.requests.append(Request(
                prompt=[int(t) for t in toks], max_new_tokens=n_new,
                arrival_time=float(arrivals[i])))

    def __iter__(self):
        return iter(self.requests)

    def __len__(self):
        return len(self.requests)


class Scheduler:
    """Slot + page bookkeeping between the queue and the engine.

    Owns the :class:`PageAllocator` and the host mirror of the page
    table; the engine asks it to ``admit`` before every step and to
    ``ensure_page``/``evict``/``preempt_for_page`` as sequences grow and
    finish. Pure host code — the engine pushes the resulting table into
    the device cache.
    """

    def __init__(self, cfg: PageConfig,
                 allocator: Optional[PageAllocator] = None, *,
                 prefix_cache: Optional[PrefixCache] = None) -> None:
        self.cfg = cfg
        self.allocator = allocator or PageAllocator(cfg.num_pages)
        self.prefix_cache = prefix_cache
        self.queue: List[Request] = []          # FIFO; preempted go first
        self.running: Dict[int, Request] = {}   # slot -> request
        self._admit_order: List[int] = []       # slots, oldest first
        # Shared-prefix tokens already in cache per freshly-admitted
        # slot (copy-on-write pages, docs/serving.md); the engine pops
        # them via take_prefix_len to seed the slot's consume cursor.
        self._prefix_len: Dict[int, int] = {}
        # Host mirror of KVCache.page_table (engine copies to device).
        self.page_table = np.zeros(
            (cfg.max_slots, cfg.pages_per_slot), np.int32)

    # -- queue ------------------------------------------------------------

    def submit(self, req: Request, *, front: bool = False) -> None:
        _check_request(req, self.cfg)
        if front:
            self.queue.insert(0, req)
        else:
            self.queue.append(req)

    def queue_depth(self) -> int:
        return len(self.queue)

    def free_slots(self) -> List[int]:
        return [s for s in range(self.cfg.max_slots)
                if s not in self.running]

    # -- admission --------------------------------------------------------

    def _pages_for_admission(self, req: Request) -> int:
        # The prompt, plus one page of decode headroom so the first
        # sampled token can never stall a freshly-admitted sequence.
        return self.cfg.pages_for(len(req.prompt) + 1)

    def admit(self, now: float) -> List[int]:
        """Admit queued requests (arrival_time <= now) while a free slot
        and sufficient free pages exist. FIFO — no overtaking: a large
        head-of-line request blocks later ones (predictable tail latency
        beats marginal utilization here). With a prefix cache attached,
        the cached full pages of the prompt come in as copy-on-write
        shared pages (the tenant allocates only the tail privately and
        skips their prefill — ``take_prefix_len``); a short pool first
        evicts reader-less cached pages before giving up. Returns the
        admitted slots."""
        admitted = []
        while self.queue and self.queue[0].arrival_time <= now:
            slots = self.free_slots()
            if not slots:
                break
            req = self.queue[0]
            if self._prefix_pending(req):
                break  # a running tenant-mate is about to register it
            pages, matched = self._admit_pages(req)
            if pages is None:
                break  # admission never exceeds free pages
            self.queue.pop(0)
            slot = slots[0]
            self.running[slot] = req
            self._admit_order.append(slot)
            req.admit_time = now
            self._prefix_len[slot] = matched
            self.page_table[slot, :] = 0
            self.page_table[slot, :len(pages)] = pages
            admitted.append(slot)
        return admitted

    def _prefix_pending(self, req: Request) -> bool:
        """True when ``req``'s shared prefix is not cached YET but a
        RUNNING request with the same leading full page is mid-prefill —
        admitting now would duplicate the prefix pages, while a step or
        two of patience turns the miss into a copy-on-write hit (the
        mate registers its prompt pages the moment its prefill
        completes). Self-clearing: the mate either registers (lookup
        hits) or leaves ``running`` (preempted/finished), so the queue
        head can never defer forever."""
        if self.prefix_cache is None:
            return False
        ps = self.cfg.page_size
        if len(req.prompt) <= ps:
            return False  # no full shared page to wait for
        _, matched = self.prefix_cache.lookup(req.prompt, count=False)
        if matched:
            return False  # already cached: admit with the hit
        head = tuple(req.prompt[:ps])
        return any(r is not req and len(r.prompt) > ps
                   and tuple(r.prompt[:ps]) == head
                   for r in self.running.values())

    def _admit_pages(self, req: Request):
        """Atomic page grant for one admission: ``(pages, prefix_tokens)``
        with the shared prefix pages leading, or ``(None, 0)`` when the
        pool is short even after evicting reader-less cached pages."""
        need_total = self._pages_for_admission(req)
        if self.prefix_cache is None:
            return self.allocator.alloc(req.req_id, need_total), 0
        shared, matched = self.prefix_cache.lookup(req.prompt)
        need = need_total - len(shared)
        pages = self.allocator.alloc(req.req_id, need, shared=shared)
        if pages is None:
            short = need - self.allocator.free_pages
            if self.prefix_cache.evict_unreferenced(short) == 0:
                return None, 0
            # Eviction may have reclaimed reader-less pages of THIS
            # prefix — re-walk so the shared list only names pages
            # still pinned by the cache.
            shared, matched = self.prefix_cache.lookup(
                req.prompt, count=False)
            need = need_total - len(shared)
            pages = self.allocator.alloc(req.req_id, need, shared=shared)
            if pages is None:
                return None, 0
        return pages, matched

    def take_prefix_len(self, slot: int) -> int:
        """Tokens of ``slot``'s prompt already covered by shared prefix
        pages at admission — the engine seeds the slot's consume cursor
        with this (prefill starts after the cached prefix). Pops: one
        read per admission."""
        return self._prefix_len.pop(slot, 0)

    def register_prefix(self, slot: int) -> int:
        """Offer a prefilled slot's full prompt pages to the prefix
        cache (no-op without one). The engine calls this once per slot
        when its prefill completes — the moment the prompt's full pages
        hold final KV. Returns the number of pages newly cached."""
        if self.prefix_cache is None:
            return 0
        req = self.running[slot]
        return self.prefix_cache.insert(
            req.prompt, self.allocator.pages_of(req.req_id))

    # -- growth / preemption ----------------------------------------------

    def ensure_page(self, slot: int, pos: int) -> bool:
        """Make sure the page holding position ``pos`` is granted; grows
        the sequence by one page when ``pos`` crosses into an ungranted
        page. False = pool empty (caller decides to preempt)."""
        req = self.running[slot]
        page_idx = pos // self.cfg.page_size
        have = len(self.allocator.pages_of(req.req_id))
        if page_idx < have:
            return True
        if page_idx >= self.cfg.pages_per_slot:
            raise ValueError(
                f"slot {slot}: position {pos} beyond slot capacity "
                f"{self.cfg.tokens_per_slot}")
        got = self.allocator.extend(req.req_id, 1)
        if got is None and self.prefix_cache is not None \
                and self.prefix_cache.evict_unreferenced(1):
            got = self.allocator.extend(req.req_id, 1)
        if got is None:
            return False
        self.page_table[slot, have] = got[0]
        return True

    def preempt_for_page(self, needy_slot: int) -> Optional[int]:
        """Free pages for ``needy_slot`` by preempting the YOUNGEST other
        running sequence; its request re-queues at the front with progress
        folded in. Returns the preempted slot (None when ``needy_slot`` is
        the only runner — nothing to take from)."""
        for slot in reversed(self._admit_order):
            if slot != needy_slot:
                req = self._release(slot)
                req.preemptions += 1
                req.fold_progress()
                self.submit(req, front=True)
                return slot
        return None

    # -- completion -------------------------------------------------------

    def evict(self, slot: int, now: float, reason: str) -> Request:
        """Finish a sequence: frees exactly its pages, clears the slot."""
        req = self._release(slot)
        req.finish_time = now
        req.finish_reason = reason
        return req

    def drain(self) -> List[Request]:
        """Release every running sequence (replica resize): progress folds
        into the prompt and the requests go back to the queue front in
        admission order — in-flight work is migrated, never dropped."""
        out = []
        for slot in list(self._admit_order):
            req = self._release(slot)
            req.resizes += 1
            req.fold_progress()
            out.append(req)
        for req in reversed(out):
            self.submit(req, front=True)
        return out

    def release(self, slot: int) -> Request:
        """Release a slot WITHOUT finishing its request (the migration
        handoff: the prefill replica lets go once the decode replica owns
        the KV — the request itself finishes over there)."""
        return self._release(slot)

    def _release(self, slot: int) -> Request:
        req = self.running.pop(slot)
        self._admit_order.remove(slot)
        self._prefix_len.pop(slot, None)
        self.allocator.free(req.req_id)
        self.page_table[slot, :] = 0
        return req

    def check_invariants(self) -> None:
        self.allocator.check_invariants()
        readers: Dict[int, int] = {}
        for slot, req in self.running.items():
            pages = self.allocator.pages_of(req.req_id)
            table = [int(p) for p in self.page_table[slot] if p != 0]
            assert table == pages, \
                f"slot {slot}: table {table} != grant {pages}"
            for p in pages:
                readers[p] = readers.get(p, 0) + 1
        for p, k in readers.items():
            if k > 1:
                # Cross-tenant aliasing is legal ONLY through the prefix
                # cache: a multi-reader page must carry the cache's own
                # hold, so a private page can never leak between tenants.
                assert (self.prefix_cache is not None
                        and self.allocator._held.get(p, 0) > 0), \
                    f"live sequences share non-prefix page {p}"
