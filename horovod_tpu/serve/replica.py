"""Elastic replica groups: N independent engines over device partitions.

A replica group is a contiguous slice of the job's devices running one
:class:`~horovod_tpu.serve.engine.GenerationEngine` (attention heads
tensor-parallel inside the group). :class:`ReplicaSet` owns a global
request queue, dispatches to the least-loaded replica, and — the elastic
part — **resizes** the partition mid-trace: every engine drains (in-flight
requests fold their progress into the prompt and return to the global
queue; nothing is dropped), the engines are rebuilt over the new
partition, and the trace continues. This is the serving analogue of the
elastic driver's commit/restore cycle: drain = commit, re-admission =
restore into the new world.

:class:`ReplicaAutoscaler` drives resizes through the **existing
elastic discovery layer** (elastic/discovery.py): a
:class:`~horovod_tpu.elastic.discovery.HostManager` polls a
``HostDiscovery`` exactly as ``ElasticDriver._discover_loop`` does
(driver.py:365-391), and the replica target is
``min(available groups, queue-pressure target)`` — discovery shrinking
the fleet forces a scale-down, discovery re-adding capacity (plus queue
depth beyond ``scale_up_depth``) grows it back.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax

from ..common import basics
from ..elastic.discovery import HostDiscovery, HostManager
from .engine import GenerationEngine, ServeStats, VirtualClock, WallClock
from .kv_cache import PageConfig
from .scheduler import Request


class ReplicaSet:
    """Partition ``devices`` into ``n_replicas`` engine groups sharing one
    queue. Group count must divide the device count, and the model's head
    count must divide by the per-group tp degree."""

    def __init__(self, cfg, params, page_config: PageConfig, *,
                 devices: Optional[Sequence] = None, n_replicas: int = 1,
                 eos_id: int = 1, temperature: float = 0.0,
                 seed: int = 0) -> None:
        self.cfg = cfg
        self.params = params
        self.page_config = page_config
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.queue: List[Request] = []
        self.stats = ServeStats()
        self.resize_events: List[Dict] = []
        self.engines: List[GenerationEngine] = []
        self._build(n_replicas)

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    def _build(self, n_replicas: int) -> None:
        n_dev = len(self.devices)
        if n_replicas < 1 or n_dev % n_replicas:
            raise ValueError(
                f"{n_replicas} replicas do not evenly partition "
                f"{n_dev} devices")
        per = n_dev // n_replicas
        self.engines = [
            GenerationEngine(
                self.cfg, self.params, self.page_config,
                devices=self.devices[i * per:(i + 1) * per],
                eos_id=self.eos_id, temperature=self.temperature,
                seed=self.seed + i, name=f"replica{i}")
            for i in range(n_replicas)]

    # -- dispatch ---------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.queue.append(req)

    def queue_depth(self) -> int:
        return len(self.queue) + sum(e.queue_depth() for e in self.engines)

    def in_flight(self) -> int:
        return sum(e.in_flight() for e in self.engines)

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(e.has_work for e in self.engines)

    def _dispatch(self, now: float) -> None:
        """Feed due arrivals to the least-loaded replica (queue depth +
        in-flight); FIFO within the global queue."""
        while self.queue and self.queue[0].arrival_time <= now:
            req = self.queue.pop(0)
            eng = min(self.engines,
                      key=lambda e: e.queue_depth() + e.in_flight())
            eng.submit(req)

    def step_all(self, now: float) -> int:
        self._dispatch(now)
        return sum(e.step(now) for e in self.engines)

    # -- elastic resize ---------------------------------------------------

    def resize(self, n_replicas: int, now: float = 0.0) -> int:
        """Drain every engine and rebuild over ``n_replicas`` groups.

        In-flight requests fold generated progress into their prompts and
        re-enter the global queue ahead of untouched arrivals — the
        resize migrates work, it never drops it. Returns how many
        requests were migrated."""
        if n_replicas == self.n_replicas:
            return 0
        tl = basics._state.timeline if basics.is_initialized() else None
        migrated: List[Request] = []
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
            migrated.extend(eng.drain())
        in_flight = sum(1 for r in migrated if r.resizes)
        self.queue[:0] = migrated
        old = self.n_replicas
        self._build(n_replicas)
        self.resize_events.append({
            "time": now, "from": old, "to": n_replicas,
            "migrated": len(migrated), "in_flight": in_flight})
        from ..monitor import registry as _metrics

        _metrics.counter("serve.resizes").inc()
        _metrics.counter("serve.migrated_requests").inc(len(migrated))
        _metrics.gauge("serve.replicas").set(n_replicas)
        if tl is not None:
            tl.instant(f"SERVE:RESIZE {old}->{n_replicas} "
                       f"migrated{len(migrated)}", tid="serve")
        return len(migrated)

    # -- trace loop -------------------------------------------------------

    def run(self, requests: Optional[Sequence[Request]] = None, *,
            clock=None, autoscaler: "ReplicaAutoscaler" = None,
            resize_plan: Optional[Dict[int, int]] = None,
            max_steps: int = 100_000) -> ServeStats:
        """Run a trace to completion. ``resize_plan`` maps step index →
        replica count (deterministic mid-trace resizes for tests/bench);
        ``autoscaler`` polls discovery + queue depth instead."""
        import time as _time

        clock = clock or WallClock()
        for req in (requests or ()):
            self.submit(req)
        t0 = clock()
        for i in range(max_steps):
            if not self.has_work:
                break
            now = clock()
            if resize_plan and i in resize_plan:
                self.resize(resize_plan[i], now)
            if autoscaler is not None:
                autoscaler.poll(now)
            if self.step_all(now) == 0 and not isinstance(
                    clock, VirtualClock):
                _time.sleep(1e-3)
            if isinstance(clock, VirtualClock):
                clock.tick()
        else:
            raise RuntimeError(f"trace did not drain in {max_steps} steps")
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
        self.stats.wall_time = clock() - t0
        return self.stats


class ReplicaAutoscaler:
    """Discovery- and load-driven replica count.

    ``discovery`` reports available "hosts" (device groups) exactly as the
    elastic driver's discover loop consumes it — a shrinking report forces
    a drain+scale-down (the serving analogue of a blacklisted host), a
    recovered report allows scale-up again; within the available ceiling,
    queue pressure picks the target: above ``scale_up_depth`` queued
    requests per replica grow, below ``scale_down_depth`` shrink. Replica
    counts are restricted to even partitions of the device count.
    """

    def __init__(self, replica_set: ReplicaSet,
                 discovery: Optional[HostDiscovery] = None, *,
                 min_replicas: int = 1, max_replicas: Optional[int] = None,
                 scale_up_depth: int = 8, scale_down_depth: int = 1,
                 cooldown_steps: int = 0) -> None:
        self.rs = replica_set
        self.host_manager = (HostManager(discovery)
                             if discovery is not None else None)
        self.min_replicas = min_replicas
        n_dev = len(replica_set.devices)
        self.max_replicas = max_replicas or n_dev
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.cooldown_steps = cooldown_steps
        self._cooldown = 0
        self.decisions: List[Dict] = []

    def _valid(self, n: int) -> int:
        """Clamp to [min, max] and round DOWN to an even partition."""
        n_dev = len(self.rs.devices)
        n = max(self.min_replicas, min(self.max_replicas, n, n_dev))
        while n > 1 and n_dev % n:
            n -= 1
        return max(1, n)

    def target(self) -> int:
        ceiling = self.max_replicas
        if self.host_manager is not None:
            self.host_manager.update_available_hosts()
            hosts = self.host_manager.current_hosts
            ceiling = min(ceiling, max(self.min_replicas,
                                       sum(hosts.values())))
        per_replica = self.rs.queue_depth() / max(1, self.rs.n_replicas)
        want = self.rs.n_replicas
        if per_replica > self.scale_up_depth:
            want = self.rs.n_replicas * 2
        elif per_replica < self.scale_down_depth and not self.rs.in_flight():
            want = max(1, self.rs.n_replicas // 2)
        return self._valid(min(want, ceiling))

    def poll(self, now: float) -> Optional[int]:
        """One autoscale decision; returns the new count on a resize."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        tgt = self.target()
        if tgt == self.rs.n_replicas:
            return None
        self.rs.resize(tgt, now)
        self._cooldown = self.cooldown_steps
        self.decisions.append({"time": now, "to": tgt})
        return tgt
