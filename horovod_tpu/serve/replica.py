"""Elastic replica groups: N independent engines over device partitions.

A replica group is a contiguous slice of the job's devices running one
:class:`~horovod_tpu.serve.engine.GenerationEngine` (attention heads
tensor-parallel inside the group). :class:`ReplicaSet` owns a global
request queue, dispatches to the least-loaded replica, and — the elastic
part — **resizes** the partition mid-trace: every engine drains (in-flight
requests fold their progress into the prompt and return to the global
queue; nothing is dropped), the engines are rebuilt over the new
partition, and the trace continues. This is the serving analogue of the
elastic driver's commit/restore cycle: drain = commit, re-admission =
restore into the new world.

:class:`ReplicaAutoscaler` drives resizes through the **existing
elastic discovery layer** (elastic/discovery.py): a
:class:`~horovod_tpu.elastic.discovery.HostManager` polls a
``HostDiscovery`` exactly as ``ElasticDriver._discover_loop`` does
(driver.py:365-391), and the replica target is
``min(available groups, queue-pressure target)`` — discovery shrinking
the fleet forces a scale-down, discovery re-adding capacity (plus queue
depth beyond ``scale_up_depth``) grows it back.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax

from ..common import basics
from ..elastic.discovery import HostDiscovery, HostManager
from .engine import GenerationEngine, ServeStats, VirtualClock, WallClock
from .kv_cache import PageConfig
from .scheduler import Request


class ReplicaSet:
    """Partition ``devices`` into ``n_replicas`` engine groups sharing one
    queue. Group count must divide the device count, and the model's head
    count must divide by the per-group tp degree."""

    def __init__(self, cfg, params, page_config: PageConfig, *,
                 devices: Optional[Sequence] = None, n_replicas: int = 1,
                 eos_id: int = 1, temperature: float = 0.0,
                 seed: int = 0, moe_experts: int = 0,
                 expert_router=None, hot_expert_factor: float = 2.0,
                 rebalance_every: int = 8) -> None:
        import numpy as np

        self.cfg = cfg
        self.params = params
        self.page_config = page_config
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.queue: List[Request] = []
        self.stats = ServeStats()
        self.resize_events: List[Dict] = []
        self.engines: List[GenerationEngine] = []
        # Hot-expert replication (docs/moe.md): with ``moe_experts`` > 0
        # each request is affinity-routed to its primary expert's home
        # engine(s). An expert whose cumulative token share exceeds
        # ``hot_expert_factor`` x the fair share (1/E) is HOT: its
        # engine set grows by one replica per ``rebalance_experts``
        # pass, spreading a skewed expert's traffic over more engines —
        # the serving answer to "MoE routing under load"
        # (docs/serving.md).
        self.moe_experts = max(0, int(moe_experts))
        self._expert_router = expert_router or (
            (lambda tok: int(tok) % self.moe_experts)
            if self.moe_experts else None)
        self.hot_expert_factor = float(hot_expert_factor)
        self.rebalance_every = max(1, int(rebalance_every))
        self.expert_replicas = (np.ones((self.moe_experts,), np.int64)
                                if self.moe_experts else None)
        self.hot_expert_events: List[Dict] = []
        self._drained_expert_tokens = (
            np.zeros((self.moe_experts,), np.int64)
            if self.moe_experts else None)
        self._build(n_replicas)

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    def _build(self, n_replicas: int) -> None:
        n_dev = len(self.devices)
        if n_replicas < 1 or n_dev % n_replicas:
            raise ValueError(
                f"{n_replicas} replicas do not evenly partition "
                f"{n_dev} devices")
        per = n_dev // n_replicas
        self.engines = [
            GenerationEngine(
                self.cfg, self.params, self.page_config,
                devices=self.devices[i * per:(i + 1) * per],
                eos_id=self.eos_id, temperature=self.temperature,
                seed=self.seed + i, name=f"replica{i}",
                moe_experts=self.moe_experts,
                expert_router=self._expert_router)
            for i in range(n_replicas)]
        if self.expert_replicas is not None:
            # New partition: replication counts re-clamp to what it can
            # hold (an expert cannot span more engines than exist).
            import numpy as np

            self.expert_replicas = np.minimum(
                self.expert_replicas, len(self.engines))

    # -- dispatch ---------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.queue.append(req)

    def queue_depth(self) -> int:
        return len(self.queue) + sum(e.queue_depth() for e in self.engines)

    def in_flight(self) -> int:
        return sum(e.in_flight() for e in self.engines)

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(e.has_work for e in self.engines)

    def _engine_set(self, expert: int) -> List[int]:
        """The engine indices serving ``expert``: the home engine
        (``expert % n_replicas``) plus one neighbor per replication
        increment the rebalancer granted."""
        n = self.n_replicas
        reps = int(self.expert_replicas[expert])
        return [(expert + i) % n for i in range(min(reps, n))]

    def _dispatch(self, now: float) -> None:
        """Feed due arrivals to the least-loaded replica (queue depth +
        in-flight); FIFO within the global queue. With MoE on, a request
        is affinity-routed to its primary expert's engine set (grown by
        hot-expert replication) — least-loaded WITHIN the set."""
        while self.queue and self.queue[0].arrival_time <= now:
            req = self.queue.pop(0)
            if self.moe_experts and req.prompt:
                expert = self._expert_router(int(req.prompt[0]))
                idxs = self._engine_set(expert)
                eng = min((self.engines[i] for i in idxs),
                          key=lambda e: e.queue_depth() + e.in_flight())
            else:
                eng = min(self.engines,
                          key=lambda e: e.queue_depth() + e.in_flight())
            eng.submit(req)

    # -- hot-expert replication -------------------------------------------

    def expert_load(self):
        """Cumulative per-expert token counts across the fleet
        (resize-survivor: drained engines fold their counts in)."""
        if not self.moe_experts:
            return None
        load = self._drained_expert_tokens.copy()
        for eng in self.engines:
            if eng.expert_tokens is not None:
                load += eng.expert_tokens
        return load

    def rebalance_experts(self, now: float = 0.0) -> List[int]:
        """One replication pass: every expert whose cumulative token
        share exceeds ``hot_expert_factor / moe_experts`` and is not yet
        fleet-wide gains one engine replica. Returns the experts grown
        this pass (docs/moe.md)."""
        if not self.moe_experts or self.n_replicas < 2:
            return []
        load = self.expert_load()
        total = float(load.sum())
        if total <= 0:
            return []
        from ..monitor import registry as _metrics

        tl = basics._state.timeline if basics.is_initialized() else None
        gate = self.hot_expert_factor / self.moe_experts
        grown: List[int] = []
        for e in range(self.moe_experts):
            share = float(load[e]) / total
            _metrics.gauge("serve.expert_share", expert=str(e)).set(share)
            if share > gate and \
                    int(self.expert_replicas[e]) < self.n_replicas:
                self.expert_replicas[e] += 1
                grown.append(e)
                _metrics.counter("serve.hot_expert_replications",
                                 expert=str(e)).inc()
                self.hot_expert_events.append(
                    {"time": now, "expert": e, "share": round(share, 4),
                     "replicas": int(self.expert_replicas[e])})
                if tl is not None:
                    tl.instant(
                        f"SERVE:EXPERT_REPLICATE expert{e} "
                        f"share{share:.2f} "
                        f"x{int(self.expert_replicas[e])}", tid="serve")
        for e in range(self.moe_experts):
            _metrics.gauge("serve.expert_replicas", expert=str(e)).set(
                float(self.expert_replicas[e]))
        return grown

    def step_all(self, now: float) -> int:
        self._dispatch(now)
        return sum(e.step(now) for e in self.engines)

    # -- elastic resize ---------------------------------------------------

    def resize(self, n_replicas: int, now: float = 0.0) -> int:
        """Drain every engine and rebuild over ``n_replicas`` groups.

        In-flight requests fold generated progress into their prompts and
        re-enter the global queue ahead of untouched arrivals — the
        resize migrates work, it never drops it. Returns how many
        requests were migrated."""
        if n_replicas == self.n_replicas:
            return 0
        tl = basics._state.timeline if basics.is_initialized() else None
        migrated: List[Request] = []
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
            if self.moe_experts and eng.expert_tokens is not None:
                self._drained_expert_tokens += eng.expert_tokens
            migrated.extend(eng.drain())
        in_flight = sum(1 for r in migrated if r.resizes)
        self.queue[:0] = migrated
        old = self.n_replicas
        self._build(n_replicas)
        self.resize_events.append({
            "time": now, "from": old, "to": n_replicas,
            "migrated": len(migrated), "in_flight": in_flight})
        from ..monitor import registry as _metrics

        _metrics.counter("serve.resizes").inc()
        _metrics.counter("serve.migrated_requests").inc(len(migrated))
        _metrics.gauge("serve.replicas").set(n_replicas)
        if tl is not None:
            tl.instant(f"SERVE:RESIZE {old}->{n_replicas} "
                       f"migrated{len(migrated)}", tid="serve")
        return len(migrated)

    # -- trace loop -------------------------------------------------------

    def run(self, requests: Optional[Sequence[Request]] = None, *,
            clock=None, autoscaler: "ReplicaAutoscaler" = None,
            resize_plan: Optional[Dict[int, int]] = None,
            max_steps: int = 100_000) -> ServeStats:
        """Run a trace to completion. ``resize_plan`` maps step index →
        replica count (deterministic mid-trace resizes for tests/bench);
        ``autoscaler`` polls discovery + queue depth instead."""
        import time as _time

        clock = clock or WallClock()
        for req in (requests or ()):
            self.submit(req)
        t0 = clock()
        for i in range(max_steps):
            if not self.has_work:
                break
            now = clock()
            if resize_plan and i in resize_plan:
                self.resize(resize_plan[i], now)
            if autoscaler is not None:
                autoscaler.poll(now)
            if self.moe_experts and i and i % self.rebalance_every == 0:
                self.rebalance_experts(now)
            if self.step_all(now) == 0 and not isinstance(
                    clock, VirtualClock):
                _time.sleep(1e-3)
            if isinstance(clock, VirtualClock):
                clock.tick()
        else:
            raise RuntimeError(f"trace did not drain in {max_steps} steps")
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
        self.stats.wall_time = clock() - t0
        return self.stats


class ReplicaAutoscaler:
    """Discovery- and load-driven replica count.

    ``discovery`` reports available "hosts" (device groups) exactly as the
    elastic driver's discover loop consumes it — a shrinking report forces
    a drain+scale-down (the serving analogue of a blacklisted host), a
    recovered report allows scale-up again; within the available ceiling,
    queue pressure picks the target: above ``scale_up_depth`` queued
    requests per replica grow, below ``scale_down_depth`` shrink. Replica
    counts are restricted to even partitions of the device count.
    """

    def __init__(self, replica_set: ReplicaSet,
                 discovery: Optional[HostDiscovery] = None, *,
                 min_replicas: int = 1, max_replicas: Optional[int] = None,
                 scale_up_depth: int = 8, scale_down_depth: int = 1,
                 cooldown_steps: int = 0) -> None:
        self.rs = replica_set
        self.host_manager = (HostManager(discovery)
                             if discovery is not None else None)
        self.min_replicas = min_replicas
        n_dev = len(replica_set.devices)
        self.max_replicas = max_replicas or n_dev
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.cooldown_steps = cooldown_steps
        self._cooldown = 0
        self.decisions: List[Dict] = []

    def _valid(self, n: int) -> int:
        """Clamp to [min, max] and round DOWN to an even partition."""
        n_dev = len(self.rs.devices)
        n = max(self.min_replicas, min(self.max_replicas, n, n_dev))
        while n > 1 and n_dev % n:
            n -= 1
        return max(1, n)

    def target(self) -> int:
        ceiling = self.max_replicas
        if self.host_manager is not None:
            self.host_manager.update_available_hosts()
            hosts = self.host_manager.current_hosts
            ceiling = min(ceiling, max(self.min_replicas,
                                       sum(hosts.values())))
        per_replica = self.rs.queue_depth() / max(1, self.rs.n_replicas)
        want = self.rs.n_replicas
        if per_replica > self.scale_up_depth:
            want = self.rs.n_replicas * 2
        elif per_replica < self.scale_down_depth and not self.rs.in_flight():
            want = max(1, self.rs.n_replicas // 2)
        return self._valid(min(want, ceiling))

    def poll(self, now: float) -> Optional[int]:
        """One autoscale decision; returns the new count on a resize."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        tgt = self.target()
        if tgt == self.rs.n_replicas:
            return None
        self.rs.resize(tgt, now)
        self._cooldown = self.cooldown_steps
        self.decisions.append({"time": now, "to": tgt})
        return tgt
