"""Elastic replica groups: N independent engines over device partitions.

A replica group is a contiguous slice of the job's devices running one
:class:`~horovod_tpu.serve.engine.GenerationEngine` (attention heads
tensor-parallel inside the group). :class:`ReplicaSet` owns a global
request queue, dispatches to the least-loaded replica, and — the elastic
part — **resizes** the partition mid-trace: every engine drains (in-flight
requests fold their progress into the prompt and return to the global
queue; nothing is dropped), the engines are rebuilt over the new
partition, and the trace continues. This is the serving analogue of the
elastic driver's commit/restore cycle: drain = commit, re-admission =
restore into the new world.

:class:`ReplicaAutoscaler` drives resizes through the **existing
elastic discovery layer** (elastic/discovery.py): a
:class:`~horovod_tpu.elastic.discovery.HostManager` polls a
``HostDiscovery`` exactly as ``ElasticDriver._discover_loop`` does
(driver.py:365-391), and the replica target is
``min(available groups, queue-pressure target)`` — discovery shrinking
the fleet forces a scale-down, discovery re-adding capacity (plus queue
depth beyond ``scale_up_depth``) grows it back.

**Disaggregated serving** (``disagg=(P, D)``, docs/serving.md): the
first ``P`` replicas run prefill-only (prefix cache attached — that is
where a shared-prompt hit skips work), the remaining ``D`` decode-only
(speculative window attached — that is where per-step latency
dominates). A finished prefill's KV pages ride the ``kv_migrate`` wire
plan to the least-loaded decode replica — layer chunks pumped BETWEEN
decode steps (``migrate_layers_per_step``) so the destination batch
keeps stepping while the handoff is on the wire; a decode step that
finds no work while a migration is pending counts into
``serve.kv.stall_steps``, the disagg leg's stall budget. The
autoscaler re-splits ``P:D`` by measured prefill:decode token demand.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..common import basics
from ..elastic.discovery import HostDiscovery, HostManager
from ..monitor import registry as _metrics
from ..monitor import straggler as _straggler
from ..plan import compiler as _wire
from ..plan import ir as _ir
from ..plan.accounting import kv_span
from ..plan.cost import predict_hop_ms, price_kv_migrate
from ..plan.planner import derive_kv_migrate, predict_kv_migrate_bytes
from .engine import (GenerationEngine, ServeStats, VirtualClock,
                     WallClock, warm_step_executables)
from .kv_cache import PageConfig
from .scheduler import Request

logger = logging.getLogger("horovod_tpu.serve")


class ReplicaSet:
    """Partition ``devices`` into ``n_replicas`` engine groups sharing one
    queue. Group count must divide the device count, and the model's head
    count must divide by the per-group tp degree."""

    def __init__(self, cfg, params, page_config: PageConfig, *,
                 devices: Optional[Sequence] = None, n_replicas: int = 1,
                 eos_id: int = 1, temperature: float = 0.0,
                 seed: int = 0, moe_experts: int = 0,
                 expert_router=None, hot_expert_factor: float = 2.0,
                 rebalance_every: int = 8,
                 disagg: Optional[Tuple[int, int]] = None,
                 prefix_cache: bool = False, spec_k: int = 0,
                 kv_migrate_quantized: bool = False,
                 kv_migrate_block: Optional[int] = None,
                 kv_mesh_shape: Optional[Tuple[int, ...]] = None,
                 migrate_layers_per_step: int = 2) -> None:
        self.cfg = cfg
        self.params = params
        self.page_config = page_config
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.queue: List[Request] = []
        self.stats = ServeStats()
        self.resize_events: List[Dict] = []
        self.engines: List[GenerationEngine] = []
        # Hot-expert replication (docs/moe.md): with ``moe_experts`` > 0
        # each request is affinity-routed to its primary expert's home
        # engine(s). An expert whose cumulative token share exceeds
        # ``hot_expert_factor`` x the fair share (1/E) is HOT: its
        # engine set grows by one replica per ``rebalance_experts``
        # pass, spreading a skewed expert's traffic over more engines —
        # the serving answer to "MoE routing under load"
        # (docs/serving.md).
        self.moe_experts = max(0, int(moe_experts))
        self._expert_router = expert_router or (
            (lambda tok: int(tok) % self.moe_experts)
            if self.moe_experts else None)
        self.hot_expert_factor = float(hot_expert_factor)
        self.rebalance_every = max(1, int(rebalance_every))
        self.expert_replicas = (np.ones((self.moe_experts,), np.int64)
                                if self.moe_experts else None)
        self.hot_expert_events: List[Dict] = []
        self._drained_expert_tokens = (
            np.zeros((self.moe_experts,), np.int64)
            if self.moe_experts else None)
        # Disaggregation state (module docstring). The migration wire
        # plan is derived once for the fleet's replica-to-replica hop
        # (``kv_mesh_shape`` names the geometry — the default single
        # host is an ICI hop, where int8 is forced off by the planner's
        # placement rule).
        self._disagg = (int(disagg[0]), int(disagg[1])) if disagg \
            else None
        self.prefix_cache_enabled = bool(prefix_cache)
        self.spec_k = max(0, int(spec_k))
        self.kv_mesh_shape = (tuple(kv_mesh_shape) if kv_mesh_shape
                              else (len(self.devices), 1))
        self.kv_plan = derive_kv_migrate(
            mesh_shape=self.kv_mesh_shape,
            quantized=kv_migrate_quantized, block=kv_migrate_block)
        self.migrate_layers_per_step = max(1, int(migrate_layers_per_step))
        self._migrations: List[Dict] = []     # in flight, FIFO
        self.migration_events: List[Dict] = []
        self.kv_migrations = 0
        self.kv_migration_bytes = 0.0
        self.kv_migration_fp_bytes = 0.0
        self.kv_stall_steps = 0
        # Background-precompiled resize state (docs/compile.md): a
        # pending request_resize() and the post-resize first-token clock.
        self._pending_resize: Optional[Dict] = None
        self._post_resize_t0: Optional[float] = None
        self._reused_engines = 0
        self._build(n_replicas)

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    def _build(self, n_replicas: int) -> None:
        n_dev = len(self.devices)
        if n_replicas < 1 or n_dev % n_replicas:
            raise ValueError(
                f"{n_replicas} replicas do not evenly partition "
                f"{n_dev} devices")
        if self._disagg is not None:
            p, d = self._disagg
            if p < 1 or d < 1 or p + d != n_replicas:
                raise ValueError(
                    f"disagg split {self._disagg} must be two positive "
                    f"counts summing to n_replicas={n_replicas}")
        per = n_dev // n_replicas
        # Identical-geometry reuse (the PR-20 resize fix): a live,
        # drained engine whose device slice AND role configuration match
        # a slot in the new partition is kept as-is — its compiled step,
        # KV pools, and prefix cache all transfer; only the name
        # changes. Rebuilding it from scratch re-paid param split +
        # pool allocation (and, pre-executable-cache, the XLA compile)
        # for a byte-identical engine.
        reusable: Dict[tuple, GenerationEngine] = {}
        for eng in self.engines:
            key = (tuple(getattr(d, "id", None)
                         for d in eng.mesh.devices.ravel()),
                   eng.prefill_only, eng.prefix_cache is not None,
                   eng.spec_k)
            reusable.setdefault(key, eng)
        self._reused_engines = 0
        self.engines = []
        for i in range(n_replicas):
            is_prefill = self._disagg is not None and i < self._disagg[0]
            is_decode = self._disagg is not None and not is_prefill
            name = (f"prefill{i}" if is_prefill else
                    f"decode{i - self._disagg[0]}" if is_decode else
                    f"replica{i}")
            group = self.devices[i * per:(i + 1) * per]
            want_prefix = self.prefix_cache_enabled and not is_decode
            key = (tuple(getattr(d, "id", None) for d in group),
                   is_prefill, want_prefix, self.spec_k)
            eng = reusable.pop(key, None)
            if eng is not None:
                eng.name = name
                self.engines.append(eng)
                self._reused_engines += 1
                continue
            self.engines.append(GenerationEngine(
                self.cfg, self.params, self.page_config,
                devices=group,
                eos_id=self.eos_id, temperature=self.temperature,
                seed=self.seed + i, name=name,
                moe_experts=self.moe_experts,
                expert_router=self._expert_router,
                prefill_only=is_prefill,
                # The cache pays on the prefill side (aliased pages skip
                # prefill); the window pays on BOTH sides — decode slots
                # verify spec_k drafts per step, prefill slots chunk
                # spec_k+1 prompt tokens per step (chunked prefill: the
                # same compiled window program, fed prompt instead of
                # drafts, so a P-replica drains prompts W× faster).
                prefix_cache=want_prefix,
                spec_k=self.spec_k))
        if self.expert_replicas is not None:
            # New partition: replication counts re-clamp to what it can
            # hold (an expert cannot span more engines than exist).
            import numpy as np

            self.expert_replicas = np.minimum(
                self.expert_replicas, len(self.engines))

    # -- dispatch ---------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.queue.append(req)

    def queue_depth(self) -> int:
        return len(self.queue) + sum(e.queue_depth() for e in self.engines)

    def in_flight(self) -> int:
        return sum(e.in_flight() for e in self.engines)

    @property
    def has_work(self) -> bool:
        return (bool(self.queue) or bool(self._migrations)
                or any(e.has_work for e in self.engines)
                or any(e.prefill_done for e in self.engines))

    @property
    def prefill_engines(self) -> List[GenerationEngine]:
        """The replicas taking fresh arrivals (all of them when not
        disaggregated)."""
        if self._disagg is None:
            return self.engines
        return self.engines[:self._disagg[0]]

    @property
    def decode_engines(self) -> List[GenerationEngine]:
        if self._disagg is None:
            return self.engines
        return self.engines[self._disagg[0]:]

    def _engine_set(self, expert: int) -> List[int]:
        """The engine indices serving ``expert``: the home engine
        (``expert % n_replicas``) plus one neighbor per replication
        increment the rebalancer granted."""
        n = self.n_replicas
        reps = int(self.expert_replicas[expert])
        return [(expert + i) % n for i in range(min(reps, n))]

    def _dispatch(self, now: float) -> None:
        """Feed due arrivals to the least-loaded replica (queue depth +
        in-flight); FIFO within the global queue. With MoE on, a request
        is affinity-routed to its primary expert's engine set (grown by
        hot-expert replication) — least-loaded WITHIN the set. With the
        prefix cache on, a request is affinity-routed by its FIRST
        PROMPT PAGE — tenant-mates sharing a prefix land on the same
        prefill engine, whose cache is the only one that can alias
        their pages."""
        while self.queue and self.queue[0].arrival_time <= now:
            req = self.queue.pop(0)
            if self.moe_experts and req.prompt and self._disagg is None:
                expert = self._expert_router(int(req.prompt[0]))
                idxs = self._engine_set(expert)
                eng = min((self.engines[i] for i in idxs),
                          key=lambda e: e.queue_depth() + e.in_flight())
            else:
                # Disaggregated: arrivals only ever enter the prefill
                # side (expert affinity is a decode-locality concern and
                # the decode destination is picked at migration time).
                pool = self.prefill_engines
                ps = self.page_config.page_size
                if (self.prefix_cache_enabled and len(pool) > 1
                        and len(req.prompt) > ps):
                    eng = pool[hash(tuple(req.prompt[:ps])) % len(pool)]
                else:
                    eng = min(pool, key=lambda e: e.queue_depth()
                              + e.in_flight())
            eng.submit(req)

    # -- hot-expert replication -------------------------------------------

    def expert_load(self):
        """Cumulative per-expert token counts across the fleet
        (resize-survivor: drained engines fold their counts in)."""
        if not self.moe_experts:
            return None
        load = self._drained_expert_tokens.copy()
        for eng in self.engines:
            if eng.expert_tokens is not None:
                load += eng.expert_tokens
        return load

    def rebalance_experts(self, now: float = 0.0) -> List[int]:
        """One replication pass: every expert whose cumulative token
        share exceeds ``hot_expert_factor / moe_experts`` and is not yet
        fleet-wide gains one engine replica. Returns the experts grown
        this pass (docs/moe.md)."""
        if not self.moe_experts or self.n_replicas < 2:
            return []
        load = self.expert_load()
        total = float(load.sum())
        if total <= 0:
            return []
        from ..monitor import registry as _metrics

        tl = basics._state.timeline if basics.is_initialized() else None
        gate = self.hot_expert_factor / self.moe_experts
        grown: List[int] = []
        for e in range(self.moe_experts):
            share = float(load[e]) / total
            _metrics.gauge("serve.expert_share", expert=str(e)).set(share)
            if share > gate and \
                    int(self.expert_replicas[e]) < self.n_replicas:
                self.expert_replicas[e] += 1
                grown.append(e)
                _metrics.counter("serve.hot_expert_replications",
                                 expert=str(e)).inc()
                self.hot_expert_events.append(
                    {"time": now, "expert": e, "share": round(share, 4),
                     "replicas": int(self.expert_replicas[e])})
                if tl is not None:
                    tl.instant(
                        f"SERVE:EXPERT_REPLICATE expert{e} "
                        f"share{share:.2f} "
                        f"x{int(self.expert_replicas[e])}", tid="serve")
        for e in range(self.moe_experts):
            _metrics.gauge("serve.expert_replicas", expert=str(e)).set(
                float(self.expert_replicas[e]))
        return grown

    def step_all(self, now: float) -> int:
        self.maybe_finish_resize(now)
        self._dispatch(now)
        if self._disagg is None:
            tok = sum(e.step(now) for e in self.engines)
            return self._after_step(tok)
        # Disaggregated order: prefill steps produce handoffs, the wire
        # pumps a bounded chunk of the head migration, decode steps keep
        # their in-flight batches moving while the rest of the payload
        # is still on the wire (overlap — the batch never waits for a
        # whole slot's KV).
        tok = sum(e.step(now) for e in self.prefill_engines)
        self._collect_handoffs(now)
        self._pump_migrations(now)
        for eng in self.decode_engines:
            t = eng.step(now)
            if t == 0 and self._migrations:
                # Idle decode replica while KV is stuck on the wire:
                # the migration IS the bottleneck this step.
                self.kv_stall_steps += 1
                _metrics.counter("serve.kv.stall_steps").inc()
                _metrics.counter("serve.kv.stall_steps_by",
                                 replica=eng.name).inc()
            tok += t
        return self._after_step(tok)

    def _after_step(self, tok: int) -> int:
        """Post-step accounting: the first productive step after a
        resize closes the rebuilt partition's time-to-first-token."""
        if tok > 0 and self._post_resize_t0 is not None \
                and self.resize_events:
            ttft_ms = (time.perf_counter() - self._post_resize_t0) * 1e3
            self._post_resize_t0 = None
            self.resize_events[-1]["post_resize_ttft_ms"] = round(
                ttft_ms, 3)
            _metrics.gauge("serve.post_resize_ttft_ms").set(ttft_ms)
        return tok

    # -- KV migration (disaggregation) ------------------------------------

    def _decode_load(self, j: int) -> float:
        eng = self.decode_engines[j]
        return (eng.queue_depth() + eng.in_flight()
                + sum(1 for m in self._migrations if m["dst"] == j))

    def _collect_handoffs(self, now: float) -> None:
        """Turn finished prefills into in-flight migrations, destined
        for the least-loaded decode replica (in-flight migrations count
        toward its load — a burst spreads)."""
        tl = basics._state.timeline if basics.is_initialized() else None
        for eng in self.prefill_engines:
            while eng.prefill_done:
                req, kv, n_tok = eng.prefill_done.pop(0)
                dst = min(range(len(self.decode_engines)),
                          key=self._decode_load)
                self._migrations.append(
                    {"req": req, "kv": kv, "n_tok": n_tok, "dst": dst,
                     "layer": 0, "k_out": [], "v_out": [],
                     "bytes": 0.0, "src": eng.name, "t0": now})
                if tl is not None:
                    tl.instant(
                        f"SERVE:KV_MIGRATE_START req{req.req_id} "
                        f"{eng.name}->{self.decode_engines[dst].name} "
                        f"{n_tok}tok", tid="serve")

    def _pump_migrations(self, now: float) -> None:
        """Advance EVERY pending migration by up to
        ``migrate_layers_per_step`` layer chunks through the
        ``kv_migrate`` wire plan; deliver to the destination engine when
        a migration's last layer lands. Chunking (not whole-payload
        sends) is what overlaps the transfers with decode steps;
        pumping all pending migrations per step (not just the head)
        keeps the aggregate migration rate off the completion critical
        path when a burst of prefills hands off together. Each chunk
        charges ``comm.kv.bytes{hop}`` (plan/accounting), records into
        the straggler's ``wire.kv`` phase, and scores the hop's link
        health at the cost model's modeled duration."""
        if not self._migrations:
            return
        (leg,) = self.kv_plan.legs
        hop = _ir.LEVEL_HOP[leg.level]
        chunk_bytes = 0.0
        t0 = time.perf_counter()
        with kv_span("MIGRATE", tid="serve"):
            for m in self._migrations:
                k, v = m["kv"]
                L = int(k.shape[0])
                for _ in range(self.migrate_layers_per_step):
                    if m["layer"] >= L:
                        break
                    lay = m["layer"]
                    chunk = np.stack([k[lay], v[lay]])
                    recv, wire = _wire.lower_kv_migrate(
                        self.kv_plan, chunk,
                        transfers=1 if lay == L - 1 else 0)
                    m["k_out"].append(recv[0])
                    m["v_out"].append(recv[1])
                    m["bytes"] += wire
                    chunk_bytes += wire
                    m["layer"] += 1
        _straggler.record_phase(
            "wire.kv", (time.perf_counter() - t0) * 1e3)
        if chunk_bytes > 0:
            # Score link health at the modeled duration (host-simulated
            # wire — a real deployment feeds the measured transfer time).
            _straggler.observe_wire(
                hop, chunk_bytes, predict_hop_ms(hop, chunk_bytes))
        while self._migrations and \
                self._migrations[0]["layer"] >= int(
                    self._migrations[0]["kv"][0].shape[0]):
            self._finish_migration(self._migrations.pop(0), now)

    def _finish_migration(self, m: Dict, now: float) -> None:
        tl = basics._state.timeline if basics.is_initialized() else None
        k, v = m["kv"]
        dst = self.decode_engines[m["dst"]]
        dst.submit_migrated(
            m["req"], (np.stack(m["k_out"]), np.stack(m["v_out"])),
            m["n_tok"])
        n_elems = int(k.size) + int(v.size)
        isz = float(np.dtype(k.dtype).itemsize)
        # Predict at the pump's actual granularity — one [2, n, H, D]
        # chunk per layer — so blockwise padding and scale overhead
        # match what lower_kv_migrate charged (predicted == accounted).
        L = int(k.shape[0])
        chunk_elems = int(k[0].size) + int(v[0].size)
        (row,) = predict_kv_migrate_bytes(self.kv_plan, chunk_elems, isz)
        pr = price_kv_migrate(self.kv_plan, chunk_elems * isz,
                              transfers=L, itemsize=isz,
                              mesh_shape=self.kv_mesh_shape)
        self.kv_migrations += 1
        self.kv_migration_bytes += m["bytes"]
        self.kv_migration_fp_bytes += n_elems * isz
        self.migration_events.append({
            "req_id": m["req"].req_id, "src": m["src"], "dst": dst.name,
            "n_tokens": m["n_tok"], "hop": row["hop"],
            "wire_bytes": m["bytes"], "fp_bytes": n_elems * isz,
            "predicted_bytes": row["bytes"] * L,
            "predicted_ms": pr["predicted_ms"],
            "modeled_ms": pr["modeled_ms"],
            "start": m["t0"], "finish": now})
        _metrics.counter("serve.kv.migrations").inc()
        if tl is not None:
            tl.instant(
                f"SERVE:KV_MIGRATE req{m['req'].req_id} "
                f"{m['src']}->{dst.name} {int(m['bytes'])}B", tid="serve")

    def token_demand(self) -> Tuple[int, int]:
        """Cumulative fleet (prefill_tokens, decode_tokens) — the
        measured demand ratio the autoscaler splits capacity by."""
        pf = self.stats.prefill_tokens + sum(
            e.stats.prefill_tokens for e in self.engines)
        dc = self.stats.decode_tokens + sum(
            e.stats.decode_tokens for e in self.engines)
        return pf, dc

    # -- elastic resize ---------------------------------------------------

    def _warm_targets(self, n_replicas: int) -> None:
        """AOT-compile the TARGET partition's step executables through
        the executable cache, one per distinct device slice — without
        touching the live engines. After this, ``_build``'s engine
        constructors hit the registry in memory and pay zero compile."""
        n_dev = len(self.devices)
        if n_replicas < 1 or n_dev % n_replicas:
            return  # resize() raises the real error
        per = n_dev // n_replicas
        seen = set()
        for i in range(n_replicas):
            group = self.devices[i * per:(i + 1) * per]
            key = tuple(getattr(d, "id", None) for d in group)
            if key in seen:
                continue
            seen.add(key)
            warm_step_executables(self.cfg, self.params,
                                  self.page_config, group,
                                  spec_k=self.spec_k)

    @property
    def resize_pending(self) -> bool:
        """A :meth:`request_resize` whose background precompile has not
        yet completed into a drain."""
        return self._pending_resize is not None

    def request_resize(self, n_replicas: int, *,
                       split: Optional[Tuple[int, int]] = None) -> bool:
        """Begin a background-precompiled resize: a host thread warms
        the TARGET geometry's executables while serving continues; the
        drain runs in a later ``step_all`` tick, only once the warm
        executables are ready (``maybe_finish_resize`` — the
        docs/compile.md ordering contract). Returns False when a resize
        is already pending."""
        if self._pending_resize is not None:
            return False
        ready = threading.Event()
        t0 = time.perf_counter()

        def _warm() -> None:
            try:
                self._warm_targets(n_replicas)
            except Exception as e:  # warm pool is an optimization only
                logger.warning("background resize precompile failed "
                               "(%s: %s) — the drain will compile cold",
                               type(e).__name__, str(e)[:200])
            finally:
                ready.set()

        thread = threading.Thread(target=_warm, daemon=True,
                                  name="serve-resize-precompile")
        self._pending_resize = {"n": int(n_replicas), "split": split,
                                "ready": ready, "t0": t0}
        thread.start()
        return True

    def maybe_finish_resize(self, now: float = 0.0) -> Optional[int]:
        """Complete a pending :meth:`request_resize` once its background
        precompile finished; None while it is still compiling (serving
        keeps stepping) or when nothing is pending."""
        p = self._pending_resize
        if p is None or not p["ready"].is_set():
            return None
        self._pending_resize = None
        bg_ms = (time.perf_counter() - p["t0"]) * 1e3
        return self.resize(p["n"], now, split=p["split"], warm=False,
                           _bg_precompile_ms=bg_ms)

    def resize(self, n_replicas: int, now: float = 0.0, *,
               split: Optional[Tuple[int, int]] = None,
               warm: bool = True,
               _bg_precompile_ms: Optional[float] = None) -> int:
        """Drain every engine and rebuild over ``n_replicas`` groups.

        In-flight requests fold generated progress into their prompts and
        re-enter the global queue ahead of untouched arrivals — the
        resize migrates work, it never drops it. On a disaggregated set,
        ``split`` rebalances the prefill:decode partition (a resize
        proceeds when EITHER the count or the split changes); in-flight
        KV migrations and undelivered handoffs requeue their requests
        (the payload is dropped — the new partition replays those
        prefills). Returns how many requests were migrated.

        ``warm=True`` (default) precompiles the target geometry's step
        executables BEFORE the drain starts, so the measured stall
        (``serve.resize_stall_ms``: drain start → new engines ready)
        contains no XLA compile; ``warm=False`` is the cold-rebuild
        baseline (or the :meth:`request_resize` completion path, which
        already warmed in the background)."""
        if split is not None:
            split = (int(split[0]), int(split[1]))
            if self._disagg is None:
                raise ValueError("split= requires a disaggregated set")
        elif self._disagg is not None and n_replicas != self.n_replicas:
            # Count change with no explicit split: keep the ratio.
            p, d = self._disagg
            p_new = max(1, min(n_replicas - 1,
                               round(n_replicas * p / (p + d))))
            split = (p_new, n_replicas - p_new)
        if n_replicas == self.n_replicas and \
                (split is None or split == self._disagg):
            return 0
        precompile_ms = _bg_precompile_ms or 0.0
        if warm and _bg_precompile_ms is None:
            # Warm BEFORE the drain: nothing has stopped serving yet
            # while the target executables compile (or load from the
            # persistent cache).
            t_warm = time.perf_counter()
            try:
                self._warm_targets(n_replicas)
            except Exception as e:  # warm pool is an optimization only
                logger.warning("resize precompile failed (%s: %s) — "
                               "rebuilding cold", type(e).__name__,
                               str(e)[:200])
            precompile_ms = (time.perf_counter() - t_warm) * 1e3
        t_stall = time.perf_counter()
        tl = basics._state.timeline if basics.is_initialized() else None
        migrated: List[Request] = []
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
            if self.moe_experts and eng.expert_tokens is not None:
                self._drained_expert_tokens += eng.expert_tokens
            for req, _kv, _n in eng.prefill_done:
                migrated.append(req)
            eng.prefill_done.clear()
            migrated.extend(eng.drain())
        for m in self._migrations:
            migrated.append(m["req"])
        self._migrations.clear()
        in_flight = sum(1 for r in migrated if r.resizes)
        self.queue[:0] = migrated
        old = self.n_replicas
        old_split = self._disagg
        if split is not None:
            self._disagg = split
        self._reused_engines = 0
        self._build(n_replicas)
        stall_ms = (time.perf_counter() - t_stall) * 1e3
        self._post_resize_t0 = time.perf_counter()
        self.resize_events.append({
            "time": now, "from": old, "to": n_replicas,
            "from_split": old_split, "to_split": self._disagg,
            "migrated": len(migrated), "in_flight": in_flight,
            "resize_stall_ms": round(stall_ms, 3),
            "precompile_ms": round(precompile_ms, 3),
            "warm": bool(warm), "background": _bg_precompile_ms is not None,
            "reused_engines": self._reused_engines})
        _metrics.gauge("serve.resize_stall_ms").set(stall_ms)
        _metrics.counter("serve.resizes").inc()
        _metrics.counter("serve.migrated_requests").inc(len(migrated))
        _metrics.gauge("serve.replicas").set(n_replicas)
        if self._disagg is not None:
            _metrics.gauge("serve.prefill_replicas").set(self._disagg[0])
            _metrics.gauge("serve.decode_replicas").set(self._disagg[1])
        if tl is not None:
            suffix = (f" split{old_split}->{self._disagg}"
                      if self._disagg is not None else "")
            tl.instant(f"SERVE:RESIZE {old}->{n_replicas} "
                       f"migrated{len(migrated)}{suffix}", tid="serve")
        return len(migrated)

    # -- trace loop -------------------------------------------------------

    def run(self, requests: Optional[Sequence[Request]] = None, *,
            clock=None, autoscaler: "ReplicaAutoscaler" = None,
            resize_plan: Optional[Dict[int, int]] = None,
            max_steps: int = 100_000) -> ServeStats:
        """Run a trace to completion. ``resize_plan`` maps step index →
        replica count (deterministic mid-trace resizes for tests/bench);
        ``autoscaler`` polls discovery + queue depth instead."""
        import time as _time

        clock = clock or WallClock()
        for req in (requests or ()):
            self.submit(req)
        t0 = clock()
        for i in range(max_steps):
            if not self.has_work:
                break
            now = clock()
            if resize_plan and i in resize_plan:
                self.resize(resize_plan[i], now)
            if autoscaler is not None:
                autoscaler.poll(now)
            if self.moe_experts and i and i % self.rebalance_every == 0:
                self.rebalance_experts(now)
            if self.step_all(now) == 0 and not isinstance(
                    clock, VirtualClock):
                _time.sleep(1e-3)
            if isinstance(clock, VirtualClock):
                clock.tick()
        else:
            raise RuntimeError(f"trace did not drain in {max_steps} steps")
        for eng in self.engines:
            self.stats.merge(eng.stats)
            eng.stats = ServeStats()
        self.stats.wall_time = clock() - t0
        return self.stats


class ReplicaAutoscaler:
    """Discovery- and load-driven replica count.

    ``discovery`` reports available "hosts" (device groups) exactly as the
    elastic driver's discover loop consumes it — a shrinking report forces
    a drain+scale-down (the serving analogue of a blacklisted host), a
    recovered report allows scale-up again; within the available ceiling,
    queue pressure picks the target: above ``scale_up_depth`` queued
    requests per replica grow, below ``scale_down_depth`` shrink. Replica
    counts are restricted to even partitions of the device count.

    On a disaggregated set the autoscaler also owns the **prefill:decode
    split**: once ``split_min_tokens`` of fleet traffic have been
    measured, the target split is ``P = round(n * prefill_tokens /
    (prefill_tokens + decode_tokens))`` clamped to ``[1, n-1]`` — a
    prompt-heavy trace shifts capacity toward prefill replicas, a
    generation-heavy one toward decode, and a split change alone is
    enough to trigger a resize.
    """

    def __init__(self, replica_set: ReplicaSet,
                 discovery: Optional[HostDiscovery] = None, *,
                 min_replicas: int = 1, max_replicas: Optional[int] = None,
                 scale_up_depth: int = 8, scale_down_depth: int = 1,
                 cooldown_steps: int = 0,
                 split_min_tokens: int = 256) -> None:
        self.rs = replica_set
        self.host_manager = (HostManager(discovery)
                             if discovery is not None else None)
        self.min_replicas = min_replicas
        n_dev = len(replica_set.devices)
        self.max_replicas = max_replicas or n_dev
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.cooldown_steps = cooldown_steps
        self.split_min_tokens = int(split_min_tokens)
        self._cooldown = 0
        self.decisions: List[Dict] = []

    def _valid(self, n: int) -> int:
        """Clamp to [min, max] and round DOWN to an even partition."""
        n_dev = len(self.rs.devices)
        n = max(self.min_replicas, min(self.max_replicas, n, n_dev))
        while n > 1 and n_dev % n:
            n -= 1
        return max(1, n)

    def target(self) -> int:
        ceiling = self.max_replicas
        if self.host_manager is not None:
            self.host_manager.update_available_hosts()
            hosts = self.host_manager.current_hosts
            ceiling = min(ceiling, max(self.min_replicas,
                                       sum(hosts.values())))
        per_replica = self.rs.queue_depth() / max(1, self.rs.n_replicas)
        want = self.rs.n_replicas
        if per_replica > self.scale_up_depth:
            want = self.rs.n_replicas * 2
        elif per_replica < self.scale_down_depth and not self.rs.in_flight():
            want = max(1, self.rs.n_replicas // 2)
        return self._valid(min(want, ceiling))

    def split_target(self, n: int) -> Optional[Tuple[int, int]]:
        """Demand-proportional prefill:decode split of ``n`` replicas,
        or None before ``split_min_tokens`` of traffic (or when the set
        is not disaggregated / too small to split)."""
        if self.rs._disagg is None or n < 2:
            return None
        pf, dc = self.rs.token_demand()
        if pf + dc < self.split_min_tokens:
            return None
        p = max(1, min(n - 1, round(n * pf / (pf + dc))))
        return (p, n - p)

    def poll(self, now: float) -> Optional[int]:
        """One autoscale decision; returns the new count on a resize
        (a split-only rebalance returns the unchanged count)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        tgt = self.target()
        if self.rs._disagg is not None:
            tgt = self._valid(max(2, tgt))
            if tgt < 2:
                return None  # device count cannot host a split
        split = self.split_target(tgt)
        if tgt == self.rs.n_replicas and \
                (split is None or split == self.rs._disagg):
            return None
        self.rs.resize(tgt, now, split=split)
        self._cooldown = self.cooldown_steps
        self.decisions.append(
            {"time": now, "to": tgt, "split": split})
        return tgt
