"""Continuous-batching generation engine over the Horovod mesh.

The inference scenario family of the north star ("heavy traffic from
millions of users"), built on the training stack's primitives:

* :mod:`.kv_cache` — paged, TP-head-sharded (and optionally ring/
  sequence-striped) KV cache with a host-side page allocator;
* :mod:`.scheduler` — request queue, Poisson arrival traces, and the
  page-availability-driven admission/preemption policy;
* :mod:`.engine` — the continuous-batching step loop: mixed prefill/
  decode in ONE compiled step, eviction + admission every iteration,
  with opt-in shared-prefix copy-on-write caching, speculative
  windowed decoding, and prefill-only/migrated-KV disaggregation
  hooks;
* :mod:`.spec` — drafters for the speculative window (the model-free
  n-gram prompt-lookup drafter by default);
* :mod:`.replica` — elastic replica groups over device partitions,
  drained (never dropped) across resizes, scaled through the elastic
  discovery layer; ``disagg=(P, D)`` splits the fleet into prefill and
  decode halves joined by the ``kv_migrate`` wire plan.

See docs/serving.md for the architecture and the page math.
"""

from .engine import GenerationEngine  # noqa: F401
from .kv_cache import (  # noqa: F401
    KVCache,
    PageAllocator,
    PageConfig,
    PrefixCache,
    init_cache,
    kv_cache_pspecs,
    paged_attention,
)
from .replica import ReplicaAutoscaler, ReplicaSet  # noqa: F401
from .scheduler import (  # noqa: F401
    PoissonTrace,
    Request,
    Scheduler,
)
from .spec import NGramDrafter  # noqa: F401
