"""Continuous-batching generation engine over the Horovod mesh.

The inference scenario family of the north star ("heavy traffic from
millions of users"), built on the training stack's primitives:

* :mod:`.kv_cache` — paged, TP-head-sharded (and optionally ring/
  sequence-striped) KV cache with a host-side page allocator;
* :mod:`.scheduler` — request queue, Poisson arrival traces, and the
  page-availability-driven admission/preemption policy;
* :mod:`.engine` — the continuous-batching step loop: mixed prefill/
  decode in ONE compiled step, eviction + admission every iteration;
* :mod:`.replica` — elastic replica groups over device partitions,
  drained (never dropped) across resizes, scaled through the elastic
  discovery layer.

See docs/serving.md for the architecture and the page math.
"""

from .engine import GenerationEngine  # noqa: F401
from .kv_cache import (  # noqa: F401
    KVCache,
    PageAllocator,
    PageConfig,
    init_cache,
    kv_cache_pspecs,
    paged_attention,
)
from .replica import ReplicaAutoscaler, ReplicaSet  # noqa: F401
from .scheduler import (  # noqa: F401
    PoissonTrace,
    Request,
    Scheduler,
)
