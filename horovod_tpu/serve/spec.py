"""Speculative-decoding drafters for the generation engine.

The engine's speculative mode (``spec_k > 0``, docs/serving.md) feeds a
window of ``k + 1`` tokens per decode slot per compiled step: the real
next token plus ``k`` *draft* proposals, verified against the model's
own greedy argmax in ONE batched engine step. The drafter only shapes
the proposals — acceptance is decided by the target model, so greedy
output is bit-identical to plain decode no matter how bad the drafts
are; a better drafter only raises the accepted-per-step rate
(``serve.spec.acceptance_rate``).

:class:`NGramDrafter` is the model-free prompt-lookup drafter (the
"prompt lookup decoding" trick): propose the tokens that followed the
longest recent match of the current context suffix earlier in the
context. Zero extra FLOPs, deterministic, and strong exactly where
speculative decoding pays best — prompts the output echoes (extraction,
code edits, shared-prefix chat with repetitive structure). A learned
drafter model drops in behind the same ``propose`` contract.
"""

from __future__ import annotations

from typing import List, Sequence


class NGramDrafter:
    """Prompt-lookup drafter: longest-suffix n-gram match over the
    sequence so far (prompt + generated), proposing its continuation.

    ``max_ngram`` bounds the suffix length tried (longest first —
    longer matches are more specific, so their continuations accept
    more often); a context with no match repeats the last token (a
    cheap bet that still wins on runs).
    """

    def __init__(self, max_ngram: int = 3) -> None:
        if max_ngram < 1:
            raise ValueError("max_ngram must be >= 1")
        self.max_ngram = int(max_ngram)

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        """``k`` draft tokens continuing ``context`` (never empty when
        ``k > 0`` — the engine pads windows with real proposals only)."""
        ctx = list(context)
        if k <= 0 or not ctx:
            return []
        for n in range(min(self.max_ngram, len(ctx) - 1), 0, -1):
            suffix = ctx[-n:]
            # Last earlier occurrence of the suffix (most recent wins:
            # local repetition dominates generation structure).
            for start in range(len(ctx) - n - 1, -1, -1):
                if ctx[start:start + n] == suffix:
                    cont = ctx[start + n:start + n + k]
                    if cont:
                        return (cont + [ctx[-1]] * (k - len(cont)))[:k]
        return [ctx[-1]] * k
