"""Paged, mesh-sharded KV cache for the generation engine.

vLLM-style paged attention re-thought for the Horovod mesh (PAPERS.md:
continuous batching / PagedAttention line of work; no reference analogue —
the reference is a training-only framework):

* **Pages.** K/V live in fixed-size pages ``[page_size, H, D]`` inside one
  flat pool per layer; a sequence owns a *page table* row of page ids, so
  cache memory fragments at page granularity instead of max-seq-len
  granularity. Page 0 is the reserved **null page**: the allocator never
  hands it out, page-table rows point at it when unused, and masked/idle
  batch slots direct their writes there — a scatter sink, never read.
* **TP sharding.** The head dim of the pools shards over the
  tensor-parallel mesh axis exactly like attention itself
  (``kv_cache_pspecs`` → ``P(..., tp_axis, ...)``, e.g. ``P(HVD_AXES)``
  heads over the full mesh); inside ``hvd.shard_map`` each rank allocates
  only its local heads, so cache bytes scale 1/tp like the qkv weights.
* **Ring (sequence) sharding.** For contexts longer than one host's pool,
  pages stripe **round-robin over a mesh axis** (global page ``g`` lives
  on rank ``g % n`` as local page ``g // n``). Decode then reuses the
  ring-attention streaming-softmax algebra from
  :func:`horovod_tpu.parallel.sequence.ring_attention`: every rank
  computes a *partial* flash accumulator ``(o, m, l)`` over its local
  pages and :func:`merge_attention_partials` combines them across the
  axis with the identical rescale rule (``alpha = exp(m - m_new)``) —
  collapsed to one collective round because a decode query is a single
  token, so there is no per-step compute to pipeline the n-step ppermute
  ring against.

Everything device-side is a pure function of a :class:`KVCache` pytree —
usable under ``jit`` / ``hvd.shard_map`` with no mutable state; the host
side (:class:`PageAllocator`) owns which pages are live.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG_INF = float(jnp.finfo(jnp.float32).min)

# The reserved scatter-sink page (see module docstring).
NULL_PAGE = 0


@dataclass(frozen=True)
class PageConfig:
    """Static geometry of the paged cache.

    ``num_pages`` counts the pool size on THIS rank (ring mode stripes the
    global pool, so per-rank pools are ``global_pages / ring_size``);
    page 0 of every pool is the null page and is never allocatable.
    ``pages_per_slot`` bounds one sequence's table row — the longest
    context a slot can hold is ``pages_per_slot * page_size`` tokens.
    """

    num_pages: int
    page_size: int
    max_slots: int
    pages_per_slot: int
    num_layers: int
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved null page)")
        for f in ("page_size", "max_slots", "pages_per_slot",
                  "num_layers", "num_heads", "head_dim"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f} must be >= 1")

    @property
    def tokens_per_slot(self) -> int:
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens``."""
        return -(-max(n_tokens, 0) // self.page_size)


class KVCache(NamedTuple):
    """Device-side cache state (a pytree; thread through the decode step).

    k/v: ``[L, num_pages, page_size, H_local, D]`` page pools.
    page_table: ``[max_slots, pages_per_slot]`` int32 page ids (NULL_PAGE
    where unallocated; ring mode stores GLOBAL page ids).
    seq_lens: ``[max_slots]`` int32 tokens currently stored per slot — the
    write cursor: the next token of slot ``s`` lands at position
    ``seq_lens[s]``.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    page_table: jnp.ndarray
    seq_lens: jnp.ndarray


def init_cache(cfg: PageConfig, tp: int = 1) -> KVCache:
    """Zero-initialized cache; ``tp`` > 1 allocates only local heads
    (call inside ``shard_map``, or device_put with ``kv_cache_pspecs``)."""
    if cfg.num_heads % tp:
        raise ValueError(
            f"num_heads {cfg.num_heads} not divisible by tp={tp}")
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size,
             cfg.num_heads // tp, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        page_table=jnp.full((cfg.max_slots, cfg.pages_per_slot), NULL_PAGE,
                            jnp.int32),
        seq_lens=jnp.zeros((cfg.max_slots,), jnp.int32),
    )


def kv_cache_pspecs(tp_axis=None) -> KVCache:
    """PartitionSpecs for ``device_put``-ing a global cache onto the mesh:
    page pools shard their head dim over ``tp_axis`` (pass ``HVD_AXES``
    for heads over the whole mesh); table/lens replicate."""
    pool = P(None, None, None, tp_axis, None) if tp_axis else P()
    return KVCache(k=pool, v=pool, page_table=P(), seq_lens=P())


class StepMeta(NamedTuple):
    """Write coordinates for one engine step, computed ONCE from the
    pre-step ``seq_lens`` and shared by every layer (all layers must write
    the same position).

    write_page/write_off: ``[S]`` scatter target per slot (the null page
    for inactive slots). attend_len: ``[S]`` tokens visible to the step's
    query AFTER its own k/v lands (``seq_lens + 1``; min 1 on inactive
    slots so the masked softmax stays finite). active: ``[S]`` bool.
    """

    write_page: jnp.ndarray
    write_off: jnp.ndarray
    attend_len: jnp.ndarray
    active: jnp.ndarray


def step_meta(cache: KVCache, active, page_size: int,
              ring_axis=None) -> StepMeta:
    """Build the step's write coordinates. In ring mode (``ring_axis``)
    the owner of the write page is ``global_page % n``; non-owners (and
    inactive slots) write to their null page."""
    pos = cache.seq_lens
    active = jnp.asarray(active, bool)
    slot = jnp.arange(cache.page_table.shape[0])
    gpage = cache.page_table[slot, pos // page_size]
    off = pos % page_size
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        me = lax.axis_index(ring_axis) if n > 1 else 0
        owner, local = ring_owner_local(gpage, n)
        mine = owner == me
        page = jnp.where(active & mine, local, NULL_PAGE)
        off = jnp.where(active & mine, off, 0)
    else:
        page = jnp.where(active, gpage, NULL_PAGE)
        off = jnp.where(active, off, 0)
    return StepMeta(
        write_page=page.astype(jnp.int32),
        write_off=off.astype(jnp.int32),
        attend_len=jnp.where(active, pos + 1, 1).astype(jnp.int32),
        active=active,
    )


def _ring_size(axis) -> int:
    from ..parallel.sequence import _axis_size

    return _axis_size(axis)


def ring_owner_local(gpage, n: int):
    """Map GLOBAL page ids to ``(owner_rank, local_page)`` under the ring
    stripe. Allocatable ids (``g >= 1``) stripe round-robin starting at
    rank 0; the null page maps to every rank's local null page with owner
    ``-1`` (matches no rank, so null entries are never 'mine' — each
    rank's local page 0 stays a pure scatter sink and a global pool of
    ``n * (local_pages - 1) + 1`` ids covers ``n`` local pools exactly)."""
    owner = jnp.where(gpage == NULL_PAGE, -1, (gpage - 1) % n)
    local = jnp.where(gpage == NULL_PAGE, NULL_PAGE, 1 + (gpage - 1) // n)
    return owner, local


def ring_pool_ids(total_pages: int, n: int) -> int:
    """Global allocatable-id count for ``n`` ranks of ``total_pages``-page
    local pools (PageAllocator(total_pages=...) argument)."""
    return n * (total_pages - 1) + 1


def append_layer_kv(cache: KVCache, layer: int, k_new, v_new,
                    meta: StepMeta) -> KVCache:
    """Scatter one step's k/v (``[S, H, D]``) into layer ``layer`` at the
    step's write coordinates. Inactive (and, in ring mode, non-owner)
    slots land on the null page — duplicate indices there are harmless
    because the null page is never read."""
    k = cache.k.at[layer, meta.write_page, meta.write_off].set(
        k_new.astype(cache.k.dtype))
    v = cache.v.at[layer, meta.write_page, meta.write_off].set(
        v_new.astype(cache.v.dtype))
    return cache._replace(k=k, v=v)


def advance(cache: KVCache, meta: StepMeta) -> KVCache:
    """Commit the step: bump write cursors of active slots (call once per
    step, after every layer appended)."""
    return cache._replace(
        seq_lens=cache.seq_lens + meta.active.astype(jnp.int32))


def _gather_pages(pool, page_table):
    """``[P, ps, H, D]`` pool + ``[S, Pps]`` table → ``[S, Pps*ps, H, D]``
    contiguous per-slot K or V (positions ``j*ps + off``)."""
    S, Pps = page_table.shape
    ps = pool.shape[1]
    g = pool[page_table]                       # [S, Pps, ps, H, D]
    return g.reshape(S, Pps * ps, *pool.shape[2:])


def _attend(q, keys, vals, mask, scale):
    """Masked single-query attention partials.

    q ``[S, 1, H, D]``, keys/vals ``[S, T, H, D]``, mask ``[S, T]`` →
    flash accumulator ``(o [S,1,H,D] fp32 unnormalized, m [S,1,H],
    l [S,1,H])`` so callers can either normalize locally or merge partials
    across a mesh axis (ring mode)."""
    s = jnp.einsum("sqhd,skhd->sqhk", q.astype(jnp.float32),
                   keys.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                 # [S,1,H]
    # Guard fully-masked rows: exp(-inf - -inf) would be NaN.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask[:, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)                                 # [S,1,H]
    o = jnp.einsum("sqhk,skhd->sqhd", p, vals.astype(jnp.float32))
    return o, m_safe, l


def paged_attention_partial(q, k_pool, v_pool, page_table, attend_len,
                            scale: Optional[float] = None,
                            page_mask=None, page_positions=None):
    """Flash-softmax partials of a single decode query over this rank's
    pages. ``page_mask`` ``[S, Pps]`` (default: all table entries count)
    masks entries another rank owns; ``page_positions`` ``[S, Pps]``
    (default ``j``) gives each entry's GLOBAL page index within the
    sequence so position masking survives ring striping."""
    S, Pps = page_table.shape
    ps = k_pool.shape[1]
    D = q.shape[-1]
    scale = D ** -0.5 if scale is None else scale
    keys = _gather_pages(k_pool, page_table)
    vals = _gather_pages(v_pool, page_table)
    if page_positions is None:
        page_positions = jnp.broadcast_to(jnp.arange(Pps)[None], (S, Pps))
    # Position of table entry j, offset t: page_positions[s,j]*ps + t.
    pos = (page_positions[:, :, None] * ps
           + jnp.arange(ps)[None, None, :]).reshape(S, Pps * ps)
    mask = pos < attend_len[:, None]
    if page_mask is not None:
        mask = mask & jnp.repeat(page_mask, ps, axis=1)
    return _attend(q, keys, vals, mask, scale)


def finalize_attention(o, m, l):
    """Normalize a flash accumulator; fully-masked rows → 0."""
    safe = jnp.where(l > 0, l, 1.0)
    return jnp.where((l > 0)[..., None], o / safe[..., None], 0.0)


def merge_attention_partials(o, m, l, axis):
    """Combine per-rank flash partials across ``axis`` — the
    ring-attention streaming-softmax combine
    (:func:`horovod_tpu.parallel.sequence.ring_attention`'s
    ``alpha = exp(m - m_new)`` rescale) in one collective round: a decode
    query is a single token, so unlike training there is no per-step
    einsum for an n-step ppermute ring to hide behind."""
    m_g = lax.pmax(m, axis)
    alpha = jnp.exp(m - m_g)
    l_g = lax.psum(l * alpha, axis)
    o_g = lax.psum(o * alpha[..., None], axis)
    return o_g, m_g, l_g


def paged_attention(q, k_pool, v_pool, page_table, attend_len,
                    scale: Optional[float] = None, ring_axis=None):
    """Single-token paged attention: ``q [S, 1, H, D]`` against the slot's
    cached pages, masked to ``attend_len`` tokens. With ``ring_axis`` the
    table holds GLOBAL page ids striped ``g % n`` across the axis: each
    rank attends its local stripe and the partials merge ring-style."""
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        if n > 1:
            me = lax.axis_index(ring_axis)
            owner, local = ring_owner_local(page_table, n)
            mine = owner == me
            local = jnp.where(mine, local, NULL_PAGE)
            Pps = page_table.shape[1]
            positions = jnp.broadcast_to(
                jnp.arange(Pps)[None], page_table.shape)
            o, m, l = paged_attention_partial(
                q, k_pool, v_pool, local, attend_len, scale,
                page_mask=mine, page_positions=positions)
            o, m, l = merge_attention_partials(o, m, l, ring_axis)
            return finalize_attention(o, m, l).astype(q.dtype)
    o, m, l = paged_attention_partial(q, k_pool, v_pool, page_table,
                                      attend_len, scale)
    return finalize_attention(o, m, l).astype(q.dtype)


def gather_slot_kv(cache: KVCache, layer: int, slot: int,
                   n_tokens: int, ring_axis=None):
    """Debug/test readback: layer ``layer``'s contiguous ``[n, H, D]``
    K/V of slot ``slot`` (eager or in-trace; ring mode all-gathers the
    stripes via max-merge over the axis — exact because non-owned
    positions read the zero null page... use outside hot paths only)."""
    table = cache.page_table[slot]
    ps = cache.k.shape[2]
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        if n > 1:
            me = lax.axis_index(ring_axis)
            owner, local = ring_owner_local(table, n)
            mine = owner == me
            local = jnp.where(mine, local, NULL_PAGE)
            k = cache.k[layer, local] * mine[:, None, None, None]
            v = cache.v[layer, local] * mine[:, None, None, None]
            k = lax.psum(k, ring_axis)
            v = lax.psum(v, ring_axis)
            return (k.reshape(-1, *k.shape[2:])[:n_tokens],
                    v.reshape(-1, *v.shape[2:])[:n_tokens])
    k = cache.k[layer, table].reshape(-1, *cache.k.shape[3:])
    v = cache.v[layer, table].reshape(-1, *cache.v.shape[3:])
    return k[:n_tokens], v[:n_tokens]


class PageAllocator:
    """Host-side free-list over the page pool (ring mode: over GLOBAL page
    ids ``1..total_pages-1``; page 0 is the null page).

    All-or-nothing grants: ``alloc``/``extend`` either return the pages or
    ``None`` with no state change — the scheduler's admission invariant
    ("admission never exceeds free pages") falls out of that atomicity.
    ``check_invariants`` is O(pages) and meant for tests/debug asserts.
    """

    def __init__(self, total_pages: int) -> None:
        if total_pages < 2:
            raise ValueError("total_pages must be >= 2 (null page + 1)")
        self.total_pages = total_pages
        # LIFO free list → recently-freed pages are reused first (the
        # aliasing test's worst case, on purpose).
        self._free: List[int] = list(range(total_pages - 1, 0, -1))
        self._owner: Dict[int, List[int]] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_of(self, seq_id) -> List[int]:
        return list(self._owner.get(seq_id, ()))

    def alloc(self, seq_id, n: int) -> Optional[List[int]]:
        """Grant ``n`` pages to a NEW sequence, or None if short."""
        if seq_id in self._owner:
            raise ValueError(f"sequence {seq_id!r} already live")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._owner[seq_id] = pages
        return pages

    def extend(self, seq_id, n: int = 1) -> Optional[List[int]]:
        """Grow a live sequence by ``n`` pages, or None if short."""
        if seq_id not in self._owner:
            raise ValueError(f"sequence {seq_id!r} not live")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._owner[seq_id].extend(pages)
        return pages

    def free(self, seq_id) -> List[int]:
        """Release exactly the sequence's pages back to the pool."""
        pages = self._owner.pop(seq_id)
        self._free.extend(pages)
        return pages

    def live_sequences(self) -> List:
        return list(self._owner)

    def check_invariants(self) -> None:
        """No page double-owned, none both free and owned, null page never
        granted, and the pool accounts for every page."""
        owned = [p for pages in self._owner.values() for p in pages]
        assert len(owned) == len(set(owned)), "page owned twice"
        assert NULL_PAGE not in owned, "null page allocated"
        assert NULL_PAGE not in self._free, "null page in free list"
        assert not (set(owned) & set(self._free)), "page both free and owned"
        assert len(owned) + len(self._free) == self.total_pages - 1, \
            "pages leaked"
