"""Paged, mesh-sharded KV cache for the generation engine.

vLLM-style paged attention re-thought for the Horovod mesh (PAPERS.md:
continuous batching / PagedAttention line of work; no reference analogue —
the reference is a training-only framework):

* **Pages.** K/V live in fixed-size pages ``[page_size, H, D]`` inside one
  flat pool per layer; a sequence owns a *page table* row of page ids, so
  cache memory fragments at page granularity instead of max-seq-len
  granularity. Page 0 is the reserved **null page**: the allocator never
  hands it out, page-table rows point at it when unused, and masked/idle
  batch slots direct their writes there — a scatter sink, never read.
* **TP sharding.** The head dim of the pools shards over the
  tensor-parallel mesh axis exactly like attention itself
  (``kv_cache_pspecs`` → ``P(..., tp_axis, ...)``, e.g. ``P(HVD_AXES)``
  heads over the full mesh); inside ``hvd.shard_map`` each rank allocates
  only its local heads, so cache bytes scale 1/tp like the qkv weights.
* **Ring (sequence) sharding.** For contexts longer than one host's pool,
  pages stripe **round-robin over a mesh axis** (global page ``g`` lives
  on rank ``g % n`` as local page ``g // n``). Decode then reuses the
  ring-attention streaming-softmax algebra from
  :func:`horovod_tpu.parallel.sequence.ring_attention`: every rank
  computes a *partial* flash accumulator ``(o, m, l)`` over its local
  pages and :func:`merge_attention_partials` combines them across the
  axis with the identical rescale rule (``alpha = exp(m - m_new)``) —
  collapsed to one collective round because a decode query is a single
  token, so there is no per-step compute to pipeline the n-step ppermute
  ring against.

Everything device-side is a pure function of a :class:`KVCache` pytree —
usable under ``jit`` / ``hvd.shard_map`` with no mutable state; the host
side (:class:`PageAllocator`) owns which pages are live.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG_INF = float(jnp.finfo(jnp.float32).min)

# The reserved scatter-sink page (see module docstring).
NULL_PAGE = 0


@dataclass(frozen=True)
class PageConfig:
    """Static geometry of the paged cache.

    ``num_pages`` counts the pool size on THIS rank (ring mode stripes the
    global pool, so per-rank pools are ``global_pages / ring_size``);
    page 0 of every pool is the null page and is never allocatable.
    ``pages_per_slot`` bounds one sequence's table row — the longest
    context a slot can hold is ``pages_per_slot * page_size`` tokens.
    """

    num_pages: int
    page_size: int
    max_slots: int
    pages_per_slot: int
    num_layers: int
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved null page)")
        for f in ("page_size", "max_slots", "pages_per_slot",
                  "num_layers", "num_heads", "head_dim"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f} must be >= 1")

    @property
    def tokens_per_slot(self) -> int:
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens``."""
        return -(-max(n_tokens, 0) // self.page_size)


class KVCache(NamedTuple):
    """Device-side cache state (a pytree; thread through the decode step).

    k/v: ``[L, num_pages, page_size, H_local, D]`` page pools.
    page_table: ``[max_slots, pages_per_slot]`` int32 page ids (NULL_PAGE
    where unallocated; ring mode stores GLOBAL page ids).
    seq_lens: ``[max_slots]`` int32 tokens currently stored per slot — the
    write cursor: the next token of slot ``s`` lands at position
    ``seq_lens[s]``.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    page_table: jnp.ndarray
    seq_lens: jnp.ndarray


def init_cache(cfg: PageConfig, tp: int = 1) -> KVCache:
    """Zero-initialized cache; ``tp`` > 1 allocates only local heads
    (call inside ``shard_map``, or device_put with ``kv_cache_pspecs``)."""
    if cfg.num_heads % tp:
        raise ValueError(
            f"num_heads {cfg.num_heads} not divisible by tp={tp}")
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size,
             cfg.num_heads // tp, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        page_table=jnp.full((cfg.max_slots, cfg.pages_per_slot), NULL_PAGE,
                            jnp.int32),
        seq_lens=jnp.zeros((cfg.max_slots,), jnp.int32),
    )


def kv_cache_pspecs(tp_axis=None) -> KVCache:
    """PartitionSpecs for ``device_put``-ing a global cache onto the mesh:
    page pools shard their head dim over ``tp_axis`` (pass ``HVD_AXES``
    for heads over the whole mesh); table/lens replicate."""
    pool = P(None, None, None, tp_axis, None) if tp_axis else P()
    return KVCache(k=pool, v=pool, page_table=P(), seq_lens=P())


class StepMeta(NamedTuple):
    """Write coordinates for one engine step, computed ONCE from the
    pre-step ``seq_lens`` and shared by every layer (all layers must write
    the same position).

    write_page/write_off: ``[S]`` scatter target per slot (the null page
    for inactive slots). attend_len: ``[S]`` tokens visible to the step's
    query AFTER its own k/v lands (``seq_lens + 1``; min 1 on inactive
    slots so the masked softmax stays finite). active: ``[S]`` bool.

    **Windowed steps** (speculative verify / chunked prefill): when
    ``active`` is ``[S, W]`` every field is ``[S, W]`` — window position
    ``w`` of slot ``s`` writes at sequence position ``seq_lens[s] + w``
    and attends ``seq_lens[s] + w + 1`` tokens, so one batched apply
    reproduces ``W`` sequential single-token steps bit-exactly (each
    query's mask admits exactly the positions the chain had written).
    """

    write_page: jnp.ndarray
    write_off: jnp.ndarray
    attend_len: jnp.ndarray
    active: jnp.ndarray


def step_meta(cache: KVCache, active, page_size: int,
              ring_axis=None) -> StepMeta:
    """Build the step's write coordinates. In ring mode (``ring_axis``)
    the owner of the write page is ``global_page % n``; non-owners (and
    inactive slots) write to their null page. ``active [S, W]`` builds
    windowed coordinates (see :class:`StepMeta`); window validity must be
    a contiguous prefix per slot."""
    active = jnp.asarray(active, bool)
    slot = jnp.arange(cache.page_table.shape[0])
    if active.ndim == 2:
        W = active.shape[1]
        pos = cache.seq_lens[:, None] + jnp.arange(W)[None, :]
        gpage = cache.page_table[slot[:, None], pos // page_size]
    else:
        pos = cache.seq_lens
        gpage = cache.page_table[slot, pos // page_size]
    off = pos % page_size
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        me = lax.axis_index(ring_axis) if n > 1 else 0
        owner, local = ring_owner_local(gpage, n)
        mine = owner == me
        page = jnp.where(active & mine, local, NULL_PAGE)
        off = jnp.where(active & mine, off, 0)
    else:
        page = jnp.where(active, gpage, NULL_PAGE)
        off = jnp.where(active, off, 0)
    return StepMeta(
        write_page=page.astype(jnp.int32),
        write_off=off.astype(jnp.int32),
        attend_len=jnp.where(active, pos + 1, 1).astype(jnp.int32),
        active=active,
    )


def _ring_size(axis) -> int:
    from ..parallel.sequence import _axis_size

    return _axis_size(axis)


def ring_owner_local(gpage, n: int):
    """Map GLOBAL page ids to ``(owner_rank, local_page)`` under the ring
    stripe. Allocatable ids (``g >= 1``) stripe round-robin starting at
    rank 0; the null page maps to every rank's local null page with owner
    ``-1`` (matches no rank, so null entries are never 'mine' — each
    rank's local page 0 stays a pure scatter sink and a global pool of
    ``n * (local_pages - 1) + 1`` ids covers ``n`` local pools exactly)."""
    owner = jnp.where(gpage == NULL_PAGE, -1, (gpage - 1) % n)
    local = jnp.where(gpage == NULL_PAGE, NULL_PAGE, 1 + (gpage - 1) // n)
    return owner, local


def ring_pool_ids(total_pages: int, n: int) -> int:
    """Global allocatable-id count for ``n`` ranks of ``total_pages``-page
    local pools (PageAllocator(total_pages=...) argument)."""
    return n * (total_pages - 1) + 1


def append_layer_kv(cache: KVCache, layer: int, k_new, v_new,
                    meta: StepMeta) -> KVCache:
    """Scatter one step's k/v (``[S, H, D]``, or ``[S, W, H, D]`` with
    windowed meta) into layer ``layer`` at the step's write coordinates.
    Inactive (and, in ring mode, non-owner) slots land on the null page —
    duplicate indices there are harmless because the null page is never
    read."""
    k = cache.k.at[layer, meta.write_page, meta.write_off].set(
        k_new.astype(cache.k.dtype))
    v = cache.v.at[layer, meta.write_page, meta.write_off].set(
        v_new.astype(cache.v.dtype))
    return cache._replace(k=k, v=v)


def advance(cache: KVCache, meta: StepMeta) -> KVCache:
    """Commit the step: bump write cursors of active slots (call once per
    step, after every layer appended). Windowed meta advances each slot
    by its count of valid window positions."""
    inc = meta.active.astype(jnp.int32)
    if inc.ndim == 2:
        inc = inc.sum(axis=-1)
    return cache._replace(seq_lens=cache.seq_lens + inc)


def _gather_pages(pool, page_table):
    """``[P, ps, H, D]`` pool + ``[S, Pps]`` table → ``[S, Pps*ps, H, D]``
    contiguous per-slot K or V (positions ``j*ps + off``)."""
    S, Pps = page_table.shape
    ps = pool.shape[1]
    g = pool[page_table]                       # [S, Pps, ps, H, D]
    return g.reshape(S, Pps * ps, *pool.shape[2:])


def _attend(q, keys, vals, mask, scale):
    """Masked few-query attention partials.

    q ``[S, Q, H, D]``, keys/vals ``[S, T, H, D]``, mask ``[S, T]``
    (shared by every query) or ``[S, Q, T]`` (per-query, windowed steps)
    → flash accumulator ``(o [S,Q,H,D] fp32 unnormalized, m [S,Q,H],
    l [S,Q,H])`` so callers can either normalize locally or merge partials
    across a mesh axis (ring mode)."""
    mb = mask[:, None, None, :] if mask.ndim == 2 else mask[:, :, None, :]
    s = jnp.einsum("sqhd,skhd->sqhk", q.astype(jnp.float32),
                   keys.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mb, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                 # [S,Q,H]
    # Guard fully-masked rows: exp(-inf - -inf) would be NaN.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mb, p, 0.0)
    l = jnp.sum(p, axis=-1)                                 # [S,Q,H]
    o = jnp.einsum("sqhk,skhd->sqhd", p, vals.astype(jnp.float32))
    return o, m_safe, l


def paged_attention_partial(q, k_pool, v_pool, page_table, attend_len,
                            scale: Optional[float] = None,
                            page_mask=None, page_positions=None):
    """Flash-softmax partials of a decode query over this rank's pages.
    ``attend_len`` is ``[S]`` (one query per slot) or ``[S, W]``
    (windowed verify: per-query visible lengths). ``page_mask``
    ``[S, Pps]`` (default: all table entries count) masks entries another
    rank owns; ``page_positions`` ``[S, Pps]`` (default ``j``) gives each
    entry's GLOBAL page index within the sequence so position masking
    survives ring striping."""
    S, Pps = page_table.shape
    ps = k_pool.shape[1]
    D = q.shape[-1]
    scale = D ** -0.5 if scale is None else scale
    keys = _gather_pages(k_pool, page_table)
    vals = _gather_pages(v_pool, page_table)
    if page_positions is None:
        page_positions = jnp.broadcast_to(jnp.arange(Pps)[None], (S, Pps))
    # Position of table entry j, offset t: page_positions[s,j]*ps + t.
    pos = (page_positions[:, :, None] * ps
           + jnp.arange(ps)[None, None, :]).reshape(S, Pps * ps)
    if jnp.ndim(attend_len) == 2:
        mask = pos[:, None, :] < attend_len[:, :, None]     # [S, W, T]
    else:
        mask = pos < attend_len[:, None]                    # [S, T]
    if page_mask is not None:
        pm = jnp.repeat(page_mask, ps, axis=1)
        mask = mask & (pm[:, None, :] if mask.ndim == 3 else pm)
    return _attend(q, keys, vals, mask, scale)


def finalize_attention(o, m, l):
    """Normalize a flash accumulator; fully-masked rows → 0."""
    safe = jnp.where(l > 0, l, 1.0)
    return jnp.where((l > 0)[..., None], o / safe[..., None], 0.0)


def merge_attention_partials(o, m, l, axis):
    """Combine per-rank flash partials across ``axis`` — the
    ring-attention streaming-softmax combine
    (:func:`horovod_tpu.parallel.sequence.ring_attention`'s
    ``alpha = exp(m - m_new)`` rescale) in one collective round: a decode
    query is a single token, so unlike training there is no per-step
    einsum for an n-step ppermute ring to hide behind."""
    m_g = lax.pmax(m, axis)
    alpha = jnp.exp(m - m_g)
    l_g = lax.psum(l * alpha, axis)
    o_g = lax.psum(o * alpha[..., None], axis)
    return o_g, m_g, l_g


def paged_attention(q, k_pool, v_pool, page_table, attend_len,
                    scale: Optional[float] = None, ring_axis=None):
    """Paged attention: ``q [S, 1, H, D]`` (or ``[S, W, H, D]`` with
    ``attend_len [S, W]`` — the batched speculative-verify window)
    against the slot's cached pages, masked per query to ``attend_len``
    tokens. With ``ring_axis`` the
    table holds GLOBAL page ids striped ``g % n`` across the axis: each
    rank attends its local stripe and the partials merge ring-style."""
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        if n > 1:
            me = lax.axis_index(ring_axis)
            owner, local = ring_owner_local(page_table, n)
            mine = owner == me
            local = jnp.where(mine, local, NULL_PAGE)
            Pps = page_table.shape[1]
            positions = jnp.broadcast_to(
                jnp.arange(Pps)[None], page_table.shape)
            o, m, l = paged_attention_partial(
                q, k_pool, v_pool, local, attend_len, scale,
                page_mask=mine, page_positions=positions)
            o, m, l = merge_attention_partials(o, m, l, ring_axis)
            return finalize_attention(o, m, l).astype(q.dtype)
    o, m, l = paged_attention_partial(q, k_pool, v_pool, page_table,
                                      attend_len, scale)
    return finalize_attention(o, m, l).astype(q.dtype)


def gather_slot_kv(cache: KVCache, layer: int, slot: int,
                   n_tokens: int, ring_axis=None):
    """Debug/test readback: layer ``layer``'s contiguous ``[n, H, D]``
    K/V of slot ``slot`` (eager or in-trace; ring mode all-gathers the
    stripes via max-merge over the axis — exact because non-owned
    positions read the zero null page... use outside hot paths only)."""
    table = cache.page_table[slot]
    ps = cache.k.shape[2]
    if ring_axis is not None:
        n = _ring_size(ring_axis)
        if n > 1:
            me = lax.axis_index(ring_axis)
            owner, local = ring_owner_local(table, n)
            mine = owner == me
            local = jnp.where(mine, local, NULL_PAGE)
            k = cache.k[layer, local] * mine[:, None, None, None]
            v = cache.v[layer, local] * mine[:, None, None, None]
            k = lax.psum(k, ring_axis)
            v = lax.psum(v, ring_axis)
            return (k.reshape(-1, *k.shape[2:])[:n_tokens],
                    v.reshape(-1, *v.shape[2:])[:n_tokens])
    k = cache.k[layer, table].reshape(-1, *cache.k.shape[3:])
    v = cache.v[layer, table].reshape(-1, *cache.v.shape[3:])
    return k[:n_tokens], v[:n_tokens]


class PageAllocator:
    """Host-side refcounted free-list over the page pool (ring mode: over
    GLOBAL page ids ``1..total_pages-1``; page 0 is the null page).

    All-or-nothing grants: ``alloc``/``extend`` either return the pages or
    ``None`` with no state change — the scheduler's admission invariant
    ("admission never exceeds free pages") falls out of that atomicity.

    **Copy-on-write aliasing** (docs/serving.md): a page may have several
    readers — the sequences whose page-table rows list it, plus the
    prefix cache's own hold (``retain``/``release``). ``_refs[p]`` counts
    them all; a page returns to the free list exactly when the LAST
    reader lets go, so an aliased shared-prefix page can never be
    recycled under a live reader. Writes stay exclusive by construction:
    the scheduler only hands out FULL (immutable) prefix pages, and a
    tenant's write cursor starts past them — ``check_invariants``
    cross-checks the refcount bookkeeping, O(pages), for tests/debug.
    """

    def __init__(self, total_pages: int) -> None:
        if total_pages < 2:
            raise ValueError("total_pages must be >= 2 (null page + 1)")
        self.total_pages = total_pages
        # LIFO free list → recently-freed pages are reused first (the
        # aliasing test's worst case, on purpose).
        self._free: List[int] = list(range(total_pages - 1, 0, -1))
        self._owner: Dict[int, List[int]] = {}
        # Total readers per granted page (owner-list memberships plus
        # external retain() holds); absent == page is free.
        self._refs: Dict[int, int] = {}
        # The externally-held component of _refs (the prefix cache's
        # holds) — tracked separately so check_invariants can verify
        # refs == owner-list count + external holds exactly.
        self._held: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_of(self, seq_id) -> List[int]:
        return list(self._owner.get(seq_id, ()))

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def alloc(self, seq_id, n: int,
              shared: Optional[List[int]] = None) -> Optional[List[int]]:
        """Grant ``n`` FRESH pages to a NEW sequence, or None if short.
        ``shared`` prepends already-granted (copy-on-write) pages to the
        owner list — each gains this sequence as a reader (+1 ref) —
        so a prefix-hit admission is ``alloc(seq, n_private,
        shared=prefix_pages)``. Atomic: a short pool leaves the shared
        pages' refcounts untouched."""
        if seq_id in self._owner:
            raise ValueError(f"sequence {seq_id!r} already live")
        if n > len(self._free):
            return None
        shared = list(shared or ())
        for p in shared:
            if p not in self._refs:
                raise ValueError(f"shared page {p} is not granted")
        pages = [self._free.pop() for _ in range(n)]
        for p in shared:
            self._refs[p] += 1
        for p in pages:
            self._refs[p] = 1
        self._owner[seq_id] = shared + pages
        return self._owner[seq_id]

    def extend(self, seq_id, n: int = 1) -> Optional[List[int]]:
        """Grow a live sequence by ``n`` fresh pages, or None if short."""
        if seq_id not in self._owner:
            raise ValueError(f"sequence {seq_id!r} not live")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self._owner[seq_id].extend(pages)
        return pages

    def retain(self, pages: List[int]) -> None:
        """Add an external reader hold on already-granted pages (the
        prefix cache pinning the pages it indexes)."""
        for p in pages:
            if p not in self._refs:
                raise ValueError(f"cannot retain free page {p}")
        for p in pages:
            self._refs[p] += 1
            self._held[p] = self._held.get(p, 0) + 1

    def release(self, pages: List[int]) -> List[int]:
        """Drop an external reader hold; returns the pages whose LAST
        reader this was (now back on the free list)."""
        freed: List[int] = []
        for p in pages:
            held = self._held.get(p, 0)
            if held <= 0:
                raise ValueError(f"page {p} has no external hold")
            self._held[p] = held - 1
            if self._held[p] == 0:
                del self._held[p]
            freed.extend(self._unref(p))
        return freed

    def _unref(self, p: int) -> List[int]:
        self._refs[p] -= 1
        if self._refs[p] == 0:
            del self._refs[p]
            self._free.append(p)
            return [p]
        return []

    def free(self, seq_id) -> List[int]:
        """Remove the sequence as a reader of its pages; pages whose last
        reader it was return to the pool (and are the return value —
        aliased prefix pages with other live readers stay granted)."""
        pages = self._owner.pop(seq_id)
        freed: List[int] = []
        for p in pages:
            freed.extend(self._unref(p))
        return freed

    def live_sequences(self) -> List:
        return list(self._owner)

    def check_invariants(self) -> None:
        """Refcount bookkeeping is exact: every granted page's refcount
        equals its owner-list memberships plus its external holds (a COW
        page is freed exactly when the last reader releases), a page is
        listed at most once per owner (cross-tenant aliasing never turns
        into intra-tenant duplication), the null page is never granted,
        free and granted sets are disjoint, and the pool accounts for
        every page."""
        owner_count: Dict[int, int] = {}
        for seq_id, pages in self._owner.items():
            assert len(pages) == len(set(pages)), \
                f"sequence {seq_id!r} lists a page twice"
            for p in pages:
                owner_count[p] = owner_count.get(p, 0) + 1
        granted = set(self._refs)
        assert set(owner_count) <= granted, "owned page with no refcount"
        assert set(self._held) <= granted, "held page with no refcount"
        for p in granted:
            expect = owner_count.get(p, 0) + self._held.get(p, 0)
            assert self._refs[p] == expect, (
                f"page {p}: refcount {self._refs[p]} != "
                f"{owner_count.get(p, 0)} owners + "
                f"{self._held.get(p, 0)} holds")
            assert self._refs[p] >= 1, f"granted page {p} with zero refs"
        assert NULL_PAGE not in granted, "null page allocated"
        assert NULL_PAGE not in self._free, "null page in free list"
        assert not (granted & set(self._free)), "page both free and granted"
        assert len(granted) + len(self._free) == self.total_pages - 1, \
            "pages leaked"


class PrefixCache:
    """Copy-on-write shared-prefix page cache (docs/serving.md).

    A trie over FULL pages of prompt tokens: each node is keyed by
    ``(parent_node, page_tokens)`` and pins one physical page whose KV
    holds exactly those tokens at those positions (prefix KV depends
    only on the token ids and absolute positions, so it is identical
    across tenants). The cache holds one allocator reference per cached
    page (``retain``), so a cached page can never be recycled while the
    cache — or any tenant reading through it — is alive; eviction
    (``evict_unreferenced``) releases only pages no tenant currently
    reads (refcount == the cache's own hold), leaf-first so chains stay
    walkable.

    Sharing is capped at ``len(prompt) - 1`` tokens: a tenant must
    consume at least one prompt token itself to produce its first
    logits, and the cap keeps every shared page FULL — tenants write
    from their first private page, never into an aliased one.
    """

    def __init__(self, allocator: PageAllocator, page_size: int) -> None:
        self.allocator = allocator
        self.page_size = int(page_size)
        # (parent_node_id, page token tuple) -> node record.
        self._nodes: Dict[tuple, dict] = {}
        self._children: Dict[int, int] = {}   # node id -> cached children
        self._next_id = 1
        self._clock = 0
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.insertions = 0
        self.evictions = 0

    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    @property
    def hit_rate(self) -> float:
        """Fraction of lookups that matched at least one full page."""
        return self.hits / self.lookups if self.lookups else 0.0

    def _shareable_pages(self, prompt) -> int:
        return max(0, (len(prompt) - 1) // self.page_size)

    def _walk(self, prompt, limit: int):
        """Yield ``(key, node_or_None)`` down the trie for each full
        page of ``prompt`` up to ``limit`` pages."""
        ps = self.page_size
        parent = 0
        for i in range(limit):
            key = (parent, tuple(prompt[i * ps:(i + 1) * ps]))
            node = self._nodes.get(key)
            yield key, node
            if node is None:
                return
            parent = node["id"]

    def lookup(self, prompt, *, count: bool = True):
        """Longest cached prefix of ``prompt``: ``(pages, n_tokens)``
        where ``pages`` are the aliased physical pages (NOT yet
        retained — the caller's ``alloc(..., shared=pages)`` adds the
        tenant's reader refs atomically with its private grant).
        ``count=False`` re-walks without touching the hit/lookup stats
        (the post-eviction retry path)."""
        self._clock += 1
        if count:
            self.lookups += 1
        pages: List[int] = []
        for _key, node in self._walk(prompt, self._shareable_pages(prompt)):
            if node is None:
                break
            node["last_use"] = self._clock
            pages.append(node["page"])
        if pages and count:
            self.hits += 1
            self.hit_tokens += len(pages) * self.page_size
        return pages, len(pages) * self.page_size

    def insert(self, prompt, pages: List[int]) -> int:
        """Register a prefilled sequence's full prompt pages (``pages``
        is its page-table row, shared prefix first — the walk order):
        new trie nodes retain their page; existing nodes are left as-is
        (first writer wins). Returns the number of NEW pages cached."""
        added = 0
        ps = self.page_size
        limit = min(self._shareable_pages(prompt), len(pages))
        parent = 0
        for i in range(limit):
            key = (parent, tuple(prompt[i * ps:(i + 1) * ps]))
            node = self._nodes.get(key)
            if node is None:
                self.allocator.retain([pages[i]])
                node = {"id": self._next_id, "page": pages[i],
                        "last_use": self._clock}
                self._next_id += 1
                self._nodes[key] = node
                self._children[parent] = self._children.get(parent, 0) + 1
                added += 1
            parent = node["id"]
        self.insertions += added
        return added

    def evict_unreferenced(self, need: Optional[int] = None) -> int:
        """Release cached pages no tenant currently reads (allocator
        refcount == 1, the cache's own hold), LRU-first and leaf-only
        (a node with cached children stays — chains must remain
        walkable). Stops after freeing ``need`` pages when given.
        Never touches a page with live readers."""
        freed = 0
        while need is None or freed < need:
            victims = sorted(
                (node["last_use"], key)
                for key, node in self._nodes.items()
                if not self._children.get(node["id"], 0)
                and self.allocator.refcount(node["page"]) == 1)
            if not victims:
                break
            for _, key in victims:
                node = self._nodes.pop(key)
                self._children[key[0]] = self._children.get(key[0], 1) - 1
                freed += len(self.allocator.release([node["page"]]))
                self.evictions += 1
                if need is not None and freed >= need:
                    return freed
        return freed
