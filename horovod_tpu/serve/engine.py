"""Continuous-batching generation engine (one replica).

Iteration-level (Orca-style) batching: every engine step runs ONE
compiled program over all ``max_slots`` batch slots, each slot consuming
exactly one token — a *prompt* token for sequences still in prefill
(teacher-forced, logits discarded until the boundary) or the previously
*sampled* token for sequences in decode. Prefill and decode therefore mix
freely in the same compiled step; there is no static-batch barrier:
finished sequences are evicted and queued requests admitted between any
two steps, and the compiled shape never changes (dead slots ride along
masked, their writes landing on the cache's null page).

The model runs under ``hvd.shard_map`` over the replica's mesh with
attention heads tensor-parallel (``tp_axis``) and the KV page pools
sharded the same way — the serving analogue of the training TP path, on
the identical collective stack. Timeline spans: ``SERVE:PREFILL`` /
``SERVE:DECODE`` bracket the compiled call (whichever phases the step
contains), ``SERVE:ADMIT`` / ``SERVE:EVICT`` / ``SERVE:PREEMPT`` are
instants with the slot/request in the name.

Three opt-in extensions (docs/serving.md) compose with the base loop:

* ``prefix_cache=True`` — shared-prefix copy-on-write paging: admitted
  prompts alias the cached full pages of any previously-prefilled
  prompt prefix and skip their prefill (the consume cursor starts past
  the hit); finished prefills register their pages back into the trie.
* ``spec_k > 0`` — speculative decoding: every decode slot feeds a
  window of ``1 + k`` tokens (real next token + ``k`` drafter
  proposals) through ONE compiled windowed step; the model's own argmax
  verifies the chain, so greedy output is bit-identical to plain
  decode while accepted drafts advance multiple tokens per step.
  Prefill slots use the same window to chunk ``W`` prompt tokens/step.
* ``prefill_only=True`` — the prefill half of a disaggregated pair
  (replica.py): slots leave at the prefill boundary as
  ``(request, KV payload, n_tokens)`` handoffs on ``prefill_done``,
  wire-migrated to a decode replica that resumes them via
  ``submit_migrated`` with zero prefill replay.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..common import basics
from ..models.gpt import GPT, GPTConfig
from ..monitor import flight as _flight
from ..monitor import registry as _metrics
from ..monitor import stall as _stall
from ..parallel.tensor import tp_merge_params, tp_split_params
from . import kv_cache as kvlib
from .kv_cache import KVCache, PageConfig
from .scheduler import Request, Scheduler
from .spec import NGramDrafter

SERVE_TP_AXIS = "serve_tp"

logger = logging.getLogger("horovod_tpu.serve")


def _tp_degree(mesh: Mesh, tp_axis) -> int:
    return int(np.prod([mesh.shape[a] for a in (
        (tp_axis,) if isinstance(tp_axis, str) else tp_axis)]))


def _step_specs(tp: int, tp_axis):
    """(stacked-params spec, KV cache spec tree) for a tp-degree replica.

    tp=1: fully replicated specs (a head-sharded in_spec on a size-1
    axis would mark every downstream value varying and fail the
    out_specs replication check even though no collective differs)."""
    stk_spec = P(tp_axis) if tp > 1 else P()
    pool_spec = (P(None, None, None, tp_axis, None) if tp > 1 else P())
    cache_specs = KVCache(k=pool_spec, v=pool_spec,
                          page_table=P(), seq_lens=P())
    return stk_spec, cache_specs


def _make_step_fn(model_cfg: GPTConfig, mesh: Mesh, stk_spec,
                  cache_specs):
    """The jitted mixed prefill/decode step program. One function serves
    both admission shape buckets — the W=1 step (tokens ``[S]``) and the
    speculative window (``[S, W]``); each shape is its own executable."""

    def spmd(stk, rp, cache, tokens, active):
        local = tp_merge_params(
            jax.tree.map(lambda a: a[0], stk), rp)
        return GPT(model_cfg).apply({"params": local}, tokens,
                                    cache=cache, active=active)

    return jax.jit(basics.shard_map(
        spmd, mesh=mesh,
        in_specs=(stk_spec, P(), cache_specs, P(), P()),
        out_specs=(P(), cache_specs)))


def step_abstract_args(params, page_config: PageConfig, mesh: Mesh,
                       tp_axis, *, window: int = 0):
    """The engine step's abstract ``(stacked, repl, cache, tokens,
    active)`` argument tuple: sharding-carrying ``ShapeDtypeStruct``
    trees, no device allocation. ``params`` is the dense param tree (or
    its ``jax.eval_shape`` counterpart); ``window`` > 1 produces the
    speculative ``[S, W]`` token/valid bucket. Both the engine's own
    startup warm and :func:`warm_step_executables` build their cache
    keys from this ONE function, so a background precompile and the
    engine that follows it always agree."""
    tp = _tp_degree(mesh, tp_axis)
    stk_spec, cache_specs = _step_specs(tp, tp_axis)
    tp_sh = jax.sharding.NamedSharding(mesh, stk_spec)
    rep_sh = jax.sharding.NamedSharding(mesh, P())

    def _sds(tree, sh):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            tree)

    stacked_s, repl_s = jax.eval_shape(
        lambda p: tp_split_params(p, tp), params)
    stacked_s, repl_s = _sds(stacked_s, tp_sh), _sds(repl_s, rep_sh)
    cache_s = jax.tree.map(
        lambda s, spec: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=jax.sharding.NamedSharding(mesh, spec)),
        jax.eval_shape(lambda: kvlib.init_cache(page_config, tp=1)),
        cache_specs)
    S = page_config.max_slots
    tok_shape = (S, window) if window > 1 else (S,)
    tokens_s = jax.ShapeDtypeStruct(tok_shape, jnp.int32, sharding=rep_sh)
    active_s = jax.ShapeDtypeStruct(tok_shape, jnp.bool_, sharding=rep_sh)
    return (stacked_s, repl_s, cache_s, tokens_s, active_s)


def _device_ids_token(mesh: Mesh) -> str:
    import hashlib

    ids = ",".join(str(getattr(d, "id", "?"))
                   for d in mesh.devices.ravel())
    if len(ids) > 48:
        ids = hashlib.sha1(ids.encode()).hexdigest()[:12]
    return f"dev{ids}"


def warm_step_executables(cfg: GPTConfig, params,
                          page_config: PageConfig,
                          devices: Optional[Sequence] = None, *,
                          mesh: Optional[Mesh] = None, tp_axis=None,
                          spec_k: int = 0) -> dict:
    """AOT-compile (or cache-load) the step executables an engine over
    ``devices`` will need — WITHOUT building the engine: no param split,
    no KV pool allocation, nothing placed on the target devices until
    the executables are warm. ``ReplicaSet`` runs this in the background
    for the TARGET geometry before a resize drains anything
    (docs/compile.md ordering contract); the engine built afterwards
    hits the registry in memory and pays zero compile. Returns
    ``{"step": CompileResult[, "window": CompileResult]}``."""
    import dataclasses as _dc

    from .. import compile as _xc

    if mesh is None:
        if devices is None:
            devices = [jax.devices()[0]]
        mesh = Mesh(np.array(list(devices)), (SERVE_TP_AXIS,))
        tp_axis = SERVE_TP_AXIS
    if tp_axis is None:
        raise ValueError("pass tp_axis along with mesh")
    tp = _tp_degree(mesh, tp_axis)
    model_cfg = _dc.replace(cfg, tp_axis=(tp_axis if tp > 1 else None))
    stk_spec, cache_specs = _step_specs(tp, tp_axis)
    fn = _make_step_fn(model_cfg, mesh, stk_spec, cache_specs)
    dev_tok = _device_ids_token(mesh)
    out = {}
    args = step_abstract_args(params, page_config, mesh, tp_axis)
    out["step"] = _xc.get_or_compile(
        "serve.step", lambda: fn.lower(*args),
        mesh=mesh, shapes=args, extra=dev_tok)
    if spec_k:
        wargs = step_abstract_args(params, page_config, mesh, tp_axis,
                                   window=spec_k + 1)
        out["window"] = _xc.get_or_compile(
            "serve.step", lambda: fn.lower(*wargs),
            mesh=mesh, shapes=wargs, extra=dev_tok)
    return out


class WallClock:
    def __call__(self) -> float:
        return time.monotonic() - self._t0

    def __init__(self) -> None:
        self._t0 = time.monotonic()


class VirtualClock:
    """Deterministic clock: advances ``dt`` per engine step (tests; wall
    time would make admission order timing-dependent)."""

    def __init__(self, dt: float = 1.0) -> None:
        self.dt = dt
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def tick(self) -> None:
        self.now += self.dt


@dataclass
class ServeStats:
    """One trace's outcome (see docs/serving.md for the metric defs)."""

    completed: List[Request] = field(default_factory=list)
    steps: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0
    wall_time: float = 0.0
    preemptions: int = 0
    resizes: int = 0

    @property
    def throughput_tokens(self) -> int:
        """Every token the engine processed (prefill + decode, including
        replayed work after preemption/resize)."""
        return self.prefill_tokens + self.decode_tokens

    @property
    def goodput_tokens(self) -> int:
        """Tokens that reached a user: generated tokens of COMPLETED
        requests only — replayed prefill and abandoned partials don't
        count."""
        return sum(len(r.generated) for r in self.completed)

    def tokens_per_sec(self) -> float:
        return self.throughput_tokens / max(self.wall_time, 1e-9)

    def goodput_tokens_per_sec(self) -> float:
        return self.goodput_tokens / max(self.wall_time, 1e-9)

    def latency_percentiles(self) -> Dict[str, float]:
        lats = sorted(r.latency for r in self.completed
                      if r.latency is not None)
        if not lats:
            return {"p50": float("nan"), "p99": float("nan")}
        def pct(p):
            return lats[min(len(lats) - 1, int(p * (len(lats) - 1) + 0.5))]
        return {"p50": pct(0.50), "p99": pct(0.99)}

    def merge(self, other: "ServeStats") -> None:
        self.completed.extend(other.completed)
        self.steps += other.steps
        self.prefill_tokens += other.prefill_tokens
        self.decode_tokens += other.decode_tokens
        self.preemptions += other.preemptions
        self.resizes += other.resizes


@dataclass
class _SlotState:
    req: Request
    consumed: int = 0   # tokens fed = this slot's device write cursor
    prefix_registered: bool = False  # prompt pages offered to the cache

    @property
    def n_prompt(self) -> int:
        return len(self.req.prompt)

    def next_token(self) -> int:
        if self.consumed < self.n_prompt:
            return self.req.prompt[self.consumed]
        return self.req.generated[-1]

    @property
    def in_prefill(self) -> bool:
        # The step consuming the LAST prompt token already produces the
        # first sampled logits — count it as decode for TTFT purposes.
        return self.consumed < self.n_prompt - 1


class GenerationEngine:
    """One replica: a compiled mixed prefill/decode step over a device
    group, plus the host-side continuous-batching loop.

    ``devices``: the replica's device subset — becomes a 1-D
    ``(serve_tp,)`` mesh with attention heads (and KV pools) sharded
    ``len(devices)``-way. Alternatively pass an existing ``mesh`` +
    ``tp_axis`` (e.g. the Horovod mesh with ``tp_axis=hvd.HVD_AXES``).
    ``params`` are the DENSE model params; the engine splits them.
    """

    def __init__(self, cfg: GPTConfig, params, page_config: PageConfig,
                 *, devices: Optional[Sequence] = None,
                 mesh: Optional[Mesh] = None, tp_axis=None,
                 eos_id: int = 1, temperature: float = 0.0,
                 seed: int = 0, name: str = "replica0",
                 moe_experts: int = 0, expert_router=None,
                 prefix_cache: bool = False, spec_k: int = 0,
                 drafter=None, prefill_only: bool = False) -> None:
        import dataclasses

        if mesh is None:
            if devices is None:
                devices = [jax.devices()[0]]
            mesh = Mesh(np.array(list(devices)), (SERVE_TP_AXIS,))
            tp_axis = SERVE_TP_AXIS
        if tp_axis is None:
            raise ValueError("pass tp_axis along with mesh")
        tp = int(np.prod([mesh.shape[a] for a in (
            (tp_axis,) if isinstance(tp_axis, str) else tp_axis)]))
        if cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by the replica's "
                f"tp degree {tp} ({len(mesh.devices.ravel())} devices)")
        if page_config.num_heads != cfg.num_heads or \
                page_config.num_layers != cfg.num_layers or \
                page_config.head_dim != cfg.d_model // cfg.num_heads:
            raise ValueError("page_config geometry does not match the "
                             "model config")
        self.cfg = dataclasses.replace(
            cfg, tp_axis=(tp_axis if tp > 1 else None))
        self.page_config = page_config
        self.mesh = mesh
        self.tp = tp
        self.eos_id = eos_id
        self.temperature = temperature
        self.name = name
        self._rng = np.random.RandomState(seed)
        allocator = kvlib.PageAllocator(page_config.num_pages)
        self.prefix_cache = (
            kvlib.PrefixCache(allocator, page_config.page_size)
            if prefix_cache else None)
        self.sched = Scheduler(page_config, allocator,
                               prefix_cache=self.prefix_cache)
        self.slots: Dict[int, _SlotState] = {}
        self.stats = ServeStats()
        # Disaggregation + speculation state (module docstring).
        self.prefill_only = bool(prefill_only)
        self.spec_k = max(0, int(spec_k))
        self.drafter = drafter if drafter is not None else (
            NGramDrafter() if self.spec_k else None)
        # (request, (k, v) [L, n, H, D], n_tokens) tuples awaiting
        # migration — replica.py drains this after every prefill step.
        self.prefill_done: List[tuple] = []
        self._migrated: Dict[object, tuple] = {}  # req_id -> (kv, n_tok)
        self._spec_proposed = 0
        self._spec_accepted = 0
        # Expert-parallel decode accounting (docs/moe.md): with
        # ``moe_experts`` > 0 every consumed token is attributed to its
        # routed expert — ``expert_router(token_id) -> expert`` (default:
        # the deterministic ``token % E`` proxy, replaced by the model's
        # real router when the served model is MoE) — feeding the
        # per-expert ``serve.expert_tokens{expert}`` load histograms the
        # hot-expert replication layer (replica.py) reads.
        self.moe_experts = max(0, int(moe_experts))
        self._expert_router = expert_router or (
            (lambda tok: int(tok) % self.moe_experts)
            if self.moe_experts else None)
        self.expert_tokens = (np.zeros((self.moe_experts,), np.int64)
                              if self.moe_experts else None)

        stacked, repl = tp_split_params(params, tp)
        stk_spec, cache_specs = _step_specs(tp, tp_axis)
        rep_sh = jax.sharding.NamedSharding(mesh, P())
        tp_sh = jax.sharding.NamedSharding(mesh, stk_spec)
        self._stacked = jax.device_put(stacked, tp_sh)
        self._repl = jax.device_put(repl, rep_sh)

        model_cfg = self.cfg
        self._step_fn = _make_step_fn(model_cfg, mesh, stk_spec,
                                      cache_specs)

        # Speculative window: ONE compiled program feeding W = spec_k+1
        # tokens per slot — a single batched apply returning logits
        # [S, W, V]. The window's k/v land in the cache pages first and
        # per-query attend lengths (seq_lens + w + 1) keep position w
        # blind to positions > w, so greedy verification is bit-identical
        # to W chained single-token steps at ~1/W the dispatch cost (the
        # whole point of verifying the draft in one batched step).
        self._window_fn = (_make_step_fn(model_cfg, mesh, stk_spec,
                                         cache_specs)
                           if self.spec_k else None)

        cache = kvlib.init_cache(page_config, tp=1)  # global-shaped pools
        cache_sh = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), cache_specs)
        self.cache = jax.device_put(cache, cache_sh)

        # AOT warm pool (docs/compile.md): every admission shape bucket
        # — the W=1 step and (with speculation on) the W=spec_k+1
        # window — is compiled ahead of the first request, through the
        # executable cache. A background resize precompile
        # (warm_step_executables) or a previous process already paid
        # this compile; then these are registry hits and the engine
        # starts warm. Cache trouble falls back to the jit path.
        self._step_exec = None
        self._window_exec = None
        try:
            from .. import compile as _xc

            dev_tok = _device_ids_token(mesh)
            args = step_abstract_args(params, page_config, mesh, tp_axis)
            self._step_exec = _xc.get_or_compile(
                "serve.step", lambda: self._step_fn.lower(*args),
                mesh=mesh, shapes=args, extra=dev_tok).compiled
            if self.spec_k:
                wargs = step_abstract_args(params, page_config, mesh,
                                           tp_axis,
                                           window=self.spec_k + 1)
                self._window_exec = _xc.get_or_compile(
                    "serve.step", lambda: self._window_fn.lower(*wargs),
                    mesh=mesh, shapes=wargs, extra=dev_tok).compiled
        except Exception as e:  # warm pool is an optimization only
            logger.warning("serve step AOT precompile failed (%s: %s) — "
                           "running on the jit path",
                           type(e).__name__, str(e)[:200])

    # -- queue ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.sched.submit(req)

    def submit_migrated(self, req: Request, kv, n_tokens: int) -> None:
        """Admit a request whose prefill ran elsewhere: ``kv`` is the
        migrated ``(k, v)`` payload ([L, n_tokens, H, D] each, host
        arrays — what ``prefill_done`` hands off, post-wire). It is
        scattered into this replica's pages at admission and decode
        resumes at the migrated cursor — no prefill replay. Queued at
        the front: the payload is already paid for."""
        self._migrated[req.req_id] = (kv, int(n_tokens))
        self.sched.submit(req, front=True)

    @property
    def has_work(self) -> bool:
        return bool(self.sched.queue or self.slots)

    def queue_depth(self) -> int:
        return self.sched.queue_depth()

    def in_flight(self) -> int:
        return len(self.slots)

    # -- the continuous-batching step -------------------------------------

    def _run_step(self, exec_attr: str, jit_fn, cache, tokens, active):
        """Run one compiled step: the AOT warm-pool executable when one
        loaded, dropping permanently to the jit path the first time it
        rejects its inputs (shape drift means the warm key no longer
        describes this engine — an optimization lost, never an error)."""
        exec_ = getattr(self, exec_attr)
        if exec_ is not None:
            try:
                return exec_(self._stacked, self._repl, cache,
                             tokens, active)
            except Exception as e:
                setattr(self, exec_attr, None)
                logger.warning(
                    "AOT step executable rejected its inputs "
                    "(%s: %s) — engine %s continues on the jit path",
                    type(e).__name__, str(e)[:200], self.name)
        return jit_fn(self._stacked, self._repl, cache, tokens, active)

    def step(self, now: float) -> int:
        """Admit, run ONE compiled mixed prefill/decode step, sample,
        evict. Returns the number of tokens processed (0 = idle)."""
        tl = basics._state.timeline if basics.is_initialized() else None
        self._admit(now, tl)
        _metrics.gauge("serve.queue_depth").set(self.sched.queue_depth())
        _metrics.gauge("serve.in_flight").set(len(self.slots))
        if self.prefill_only:
            # Slots already past the boundary (a fully-cached prompt
            # admitted with its whole prefill aliased) leave before the
            # step — a prefill replica never decodes.
            for slot in list(self.slots):
                if self.slots[slot].consumed >= \
                        self.slots[slot].n_prompt - 1:
                    self._handoff(slot, now, tl)
        if not self.slots:
            return 0
        if self.spec_k:
            return self._spec_step(now, tl)

        # Page growth for this step's write position; preempt youngest on
        # an empty pool (the preempted slot leaves the batch mid-flight).
        for slot in sorted(self.slots):
            if slot not in self.slots:   # evicted by a preemption below
                continue
            st = self.slots[slot]
            while not self.sched.ensure_page(slot, st.consumed):
                victim = self.sched.preempt_for_page(slot)
                if victim is None:
                    raise RuntimeError(
                        f"page pool exhausted by a single sequence "
                        f"(slot {slot}, pos {st.consumed}): size the pool "
                        f"to at least pages_for(prompt+max_new_tokens)")
                self.stats.preemptions += 1
                _metrics.counter("serve.preemptions").inc()
                _stall.record_done(
                    f"serve.req{self.slots[victim].req.req_id}")
                if tl is not None:
                    tl.instant(
                        f"SERVE:PREEMPT slot{victim} "
                        f"req{self.slots[victim].req.req_id}",
                        tid=self.name)
                del self.slots[victim]

        S = self.page_config.max_slots
        tokens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        lens = np.zeros((S,), np.int32)
        n_prefill = n_decode = 0
        for slot, st in self.slots.items():
            tokens[slot] = st.next_token()
            active[slot] = True
            lens[slot] = st.consumed
            if st.in_prefill:
                n_prefill += 1
            else:
                n_decode += 1

        # Host mirrors are authoritative: admission/eviction/preemption
        # edit the table and reset cursors, so push both every step.
        cache = self.cache._replace(
            page_table=jnp.asarray(self.sched.page_table),
            seq_lens=jnp.asarray(lens))
        phases = ([("PREFILL", n_prefill)] if n_prefill else []) + \
                 ([("DECODE", n_decode)] if n_decode else [])
        if tl is not None:
            for ph, _ in phases:
                tl.begin(self.name, f"SERVE:{ph}")
        # StepTraceAnnotation: the device-trace step marker, so a
        # jax.profiler capture of a serving run shows one annotated step
        # per engine iteration (the same marker hvd.profile_window and
        # DistributedOptimizer use — host/device trace correlation).
        with jax.profiler.StepTraceAnnotation("serve_step",
                                              step_num=self.stats.steps):
            logits, self.cache = self._run_step(
                "_step_exec", self._step_fn, cache,
                jnp.asarray(tokens), jnp.asarray(active))
        if tl is not None:
            for ph, _ in reversed(phases):
                tl.end(self.name, f"SERVE:{ph}")
        logits = np.asarray(logits)

        self.stats.prefill_tokens += n_prefill
        self.stats.decode_tokens += n_decode
        self.stats.steps += 1
        if self.moe_experts:
            # Per-expert load this step: one histogram observation per
            # expert that saw traffic (the registry's log2 buckets give
            # the load distribution; the count is the step total).
            step_load = np.zeros((self.moe_experts,), np.int64)
            for slot in self.slots:
                step_load[self._expert_router(int(tokens[slot]))] += 1
            self.expert_tokens += step_load
            for e in np.nonzero(step_load)[0]:
                _metrics.histogram("serve.expert_tokens",
                                   expert=str(int(e))).observe(
                    float(step_load[e]))
        _metrics.counter("serve.steps").inc()
        _metrics.counter("serve.prefill_tokens").inc(n_prefill)
        _metrics.counter("serve.decode_tokens").inc(n_decode)
        # Flight ring (monitor/flight.py): one instant per engine step —
        # the serving analogue of FLIGHT:STEP, so a crashed replica's
        # dump shows what the batch looked like when it died.
        _flight.instant("FLIGHT:SERVE_STEP", tid="flight",
                        args={"engine": self.name,
                              "step": self.stats.steps,
                              "prefill": n_prefill, "decode": n_decode,
                              "slots": len(self.slots)})

        for slot in list(self.slots):
            st = self.slots[slot]
            st.consumed += 1
            if self.prefill_only and st.consumed >= st.n_prompt - 1:
                self._handoff(slot, now, tl)
                continue
            self._register_prefix(slot, st)
            if st.consumed < st.n_prompt:
                continue  # still prefilling: logits discarded
            self._emit(slot, st, [self._sample(logits[slot])], now, tl)
        return n_prefill + n_decode

    # -- admission / eviction / handoff helpers ---------------------------

    def _admit(self, now: float, tl) -> None:
        for slot in self.sched.admit(now):
            req = self.sched.running[slot]
            st = _SlotState(req, consumed=self.sched.take_prefix_len(slot))
            self.slots[slot] = st
            payload = self._migrated.pop(req.req_id, None)
            if payload is not None:
                kv, n_tok = payload
                # Shared prefix pages (if any) already hold EXACT KV —
                # scatter only past them so a quantized payload never
                # perturbs pages other tenants read.
                self._scatter_migrated(slot, kv, n_tok, skip=st.consumed)
                st.consumed = max(st.consumed, n_tok)
                _metrics.counter("serve.kv.migrations_in").inc()
            _metrics.counter("serve.admissions").inc()
            # The StallInspector watches every admitted request: one that
            # sits in a slot past stall_check_time (a wedged compiled
            # step, a starved replica) surfaces as a STALL:serve.req*
            # warning (docs/observability.md).
            _stall.record_start(f"serve.req{req.req_id}", kind="serve")
            if tl is not None:
                tl.instant(f"SERVE:ADMIT slot{slot} "
                           f"req{req.req_id}", tid=self.name)
        if self.prefix_cache is not None:
            pc = self.prefix_cache
            _metrics.gauge("serve.prefix_lookups").set(pc.lookups)
            _metrics.gauge("serve.prefix_hits").set(pc.hits)
            _metrics.gauge("serve.prefix_hit_tokens").set(pc.hit_tokens)
            _metrics.gauge("serve.prefix_hit_rate").set(pc.hit_rate)
            _metrics.gauge("serve.prefix_cached_pages").set(
                pc.cached_pages)

    def _register_prefix(self, slot: int, st: _SlotState) -> None:
        # Offer the prompt's full pages to the trie once its KV is
        # complete (consumed >= n_prompt-1 covers every insertable page:
        # insert caps at (n_prompt-1)//page_size full pages).
        if (st.prefix_registered or self.prefix_cache is None
                or st.consumed < st.n_prompt - 1):
            return
        st.prefix_registered = True
        self.sched.register_prefix(slot)

    def _emit(self, slot: int, st: _SlotState, toks: Sequence[int],
              now: float, tl) -> None:
        """Append sampled tokens in order, finishing (and truncating the
        remainder) at EOS or the new-token budget."""
        for tok in toks:
            st.req.generated.append(tok)
            if st.req.first_token_time is None:
                st.req.first_token_time = now
            if tok == self.eos_id or st.req.remaining_new_tokens <= 0:
                reason = "eos" if tok == self.eos_id else "length"
                req = self.sched.evict(slot, now, reason)
                del self.slots[slot]
                self.stats.completed.append(req)
                _metrics.counter("serve.completions", reason=reason).inc()
                _stall.record_done(f"serve.req{req.req_id}")
                if tl is not None:
                    tl.instant(f"SERVE:EVICT slot{slot} req{req.req_id} "
                               f"{reason}", tid=self.name)
                return

    def _handoff(self, slot: int, now: float, tl) -> None:
        """Prefill boundary reached on a prefill-only replica: register
        the prompt with the prefix cache, pull the slot's KV off-device,
        release the pages, and queue the handoff for migration."""
        st = self.slots.pop(slot)
        self._register_prefix(slot, st)
        n_tok = st.n_prompt - 1
        kv = self._gather_slot_kv(slot, n_tok)
        req = self.sched.release(slot)
        self.prefill_done.append((req, kv, n_tok))
        _metrics.counter("serve.prefill_handoffs").inc()
        _stall.record_done(f"serve.req{req.req_id}")
        if tl is not None:
            tl.instant(f"SERVE:PREFILL_DONE slot{slot} req{req.req_id}",
                       tid=self.name)

    def _gather_slot_kv(self, slot: int, n_tok: int):
        """Contiguous KV for one slot, all layers: ``(k, v)`` host
        arrays [L, n_tok, H, D]. Indexes with the HOST page table (the
        device copy can be one admission stale). Always gathers the FULL
        slot row (ungranted entries hit the zero null page) so the
        compiled gather has ONE shape per engine — per-request lengths
        would otherwise recompile it mid-trace."""
        ps = self.page_config.page_size
        n_pages = self.page_config.pages_for(n_tok)
        table = jnp.asarray(self.sched.page_table[slot])
        k = np.asarray(self.cache.k[:, table])   # [L, Pps, ps, H, D]
        v = np.asarray(self.cache.v[:, table])
        L = k.shape[0]
        k = k.reshape(L, table.shape[0] * ps, *k.shape[3:])[:, :n_tok]
        v = v.reshape(L, table.shape[0] * ps, *v.shape[3:])[:, :n_tok]
        return k, v

    def _scatter_migrated(self, slot: int, kv, n_tok: int,
                          skip: int = 0) -> None:
        """Write a migrated KV payload into the slot's granted pages,
        skipping the first ``skip`` tokens (full shared-prefix pages —
        ``skip`` is always a page multiple)."""
        k, v = kv
        ps = self.page_config.page_size
        Pps = self.page_config.pages_per_slot
        n_pages = self.page_config.pages_for(n_tok)
        start = skip // ps
        if start >= n_pages:
            return
        L = k.shape[0]
        # Fixed-shape scatter: always write the full [Pps] slot row so
        # the compiled scatter has ONE shape per engine (per-request
        # lengths would recompile it mid-trace). Entries outside
        # [start, n_pages) redirect to the null page with zero payload —
        # the null page stays zero and real pages outside the span are
        # untouched.
        pad = Pps * ps - n_tok
        if pad:
            zk = np.zeros((L, pad) + k.shape[2:], k.dtype)
            k = np.concatenate([k, zk], axis=1)
            v = np.concatenate([v, np.zeros_like(zk)], axis=1)
        kp = k.reshape(L, Pps, ps, *k.shape[2:])
        vp = v.reshape(L, Pps, ps, *v.shape[2:])
        live = np.zeros((Pps,), bool)
        live[start:n_pages] = True
        kp = np.where(live[None, :, None, None, None], kp, 0)
        vp = np.where(live[None, :, None, None, None], vp, 0)
        pages = jnp.asarray(np.where(live, self.sched.page_table[slot],
                                     kvlib.NULL_PAGE))
        dt = self.cache.k.dtype
        self.cache = self.cache._replace(
            k=self.cache.k.at[:, pages].set(jnp.asarray(kp, dt)),
            v=self.cache.v.at[:, pages].set(jnp.asarray(vp, dt)))

    # -- the speculative windowed step ------------------------------------

    def _spec_step(self, now: float, tl) -> int:
        """One compiled W = spec_k+1 token window per slot: prefill
        slots chunk W prompt tokens; decode slots feed the real next
        token plus spec_k drafts and keep the longest argmax-verified
        chain (module docstring — greedy output is bit-identical to the
        W=1 path because each window position's logits condition on
        exactly the verified prefix)."""
        W = self.spec_k + 1
        S = self.page_config.max_slots

        # Per-slot window plan (before page growth: preemption below
        # drops victims from the plan).
        plans: Dict[int, tuple] = {}
        for slot, st in self.slots.items():
            if st.consumed < st.n_prompt:
                cap = st.n_prompt - st.consumed
                if self.prefill_only:
                    cap = max(1, st.n_prompt - 1 - st.consumed)
                w_valid = min(W, cap)
                toks = list(st.req.prompt[
                    st.consumed:st.consumed + w_valid])
            else:
                w_valid = max(1, min(
                    W, st.req.remaining_new_tokens,
                    self.page_config.tokens_per_slot - st.consumed))
                drafts = self.drafter.propose(
                    st.req.prompt + st.req.generated, w_valid - 1)
                toks = [st.next_token()] + list(drafts)
            plans[slot] = (w_valid, [int(t) for t in toks])

        # Page growth over the whole window; preempt youngest on an
        # empty pool, exactly as the W=1 path.
        for slot in sorted(plans):
            if slot not in self.slots:
                continue
            st = self.slots[slot]
            w_valid, _ = plans[slot]
            for off in range(w_valid):
                while not self.sched.ensure_page(slot, st.consumed + off):
                    victim = self.sched.preempt_for_page(slot)
                    if victim is None:
                        raise RuntimeError(
                            f"page pool exhausted by a single sequence "
                            f"(slot {slot}, pos {st.consumed + off}): "
                            f"size the pool to at least "
                            f"pages_for(prompt+max_new_tokens)")
                    self.stats.preemptions += 1
                    _metrics.counter("serve.preemptions").inc()
                    _stall.record_done(
                        f"serve.req{self.slots[victim].req.req_id}")
                    if tl is not None:
                        tl.instant(
                            f"SERVE:PREEMPT slot{victim} "
                            f"req{self.slots[victim].req.req_id}",
                            tid=self.name)
                    del self.slots[victim]
                    plans.pop(victim, None)

        tokens = np.zeros((S, W), np.int32)
        valid = np.zeros((S, W), bool)
        lens = np.zeros((S,), np.int32)
        n_prefill = n_decode = 0
        for slot, st in self.slots.items():
            w_valid, toks = plans[slot]
            tokens[slot, :w_valid] = toks
            valid[slot, :w_valid] = True
            lens[slot] = st.consumed
            if st.in_prefill:
                n_prefill += 1
            else:
                n_decode += 1

        cache = self.cache._replace(
            page_table=jnp.asarray(self.sched.page_table),
            seq_lens=jnp.asarray(lens))
        phases = ([("PREFILL", n_prefill)] if n_prefill else []) + \
                 ([("DECODE", n_decode)] if n_decode else [])
        if tl is not None:
            for ph, _ in phases:
                tl.begin(self.name, f"SERVE:{ph}")
        with jax.profiler.StepTraceAnnotation("serve_step",
                                              step_num=self.stats.steps):
            logits, self.cache = self._run_step(
                "_window_exec", self._window_fn, cache,
                jnp.asarray(tokens), jnp.asarray(valid))
        if tl is not None:
            for ph, _ in reversed(phases):
                tl.end(self.name, f"SERVE:{ph}")
        logits = np.asarray(logits)   # [S, W, V]

        step_prefill = step_decode = 0
        proposed = accepted = 0
        for slot in list(self.slots):
            st = self.slots[slot]
            w_valid, toks = plans[slot]
            old = st.consumed
            if old < st.n_prompt:
                # Chunked prefill: positions feeding prompt indices
                # below n_prompt-1 count as prefill, the boundary
                # position (whose logits sample the first token) as
                # decode — same accounting as W=1 steps.
                st.consumed = old + w_valid
                step_prefill += min(w_valid, st.n_prompt - 1 - old)
                if self.prefill_only and \
                        st.consumed >= st.n_prompt - 1:
                    self._handoff(slot, now, tl)
                    continue
                self._register_prefix(slot, st)
                if st.consumed >= st.n_prompt:
                    step_decode += 1
                    self._emit(slot, st,
                               [self._sample(logits[slot, w_valid - 1])],
                               now, tl)
                continue
            # Decode: verify the draft chain against this window's own
            # argmax. Window position w's logits condition on tokens
            # through position w; draft w (fed at position w+1) is
            # accepted iff it equals that argmax — then position w+1's
            # logits are the true next conditional and the chain
            # continues. The first mismatch's argmax is the correction
            # token (always emitted), exactly what plain decode would
            # have produced.
            emitted: List[int] = []
            acc = 0
            for w in range(w_valid):
                tok = self._sample(logits[slot, w])
                emitted.append(tok)
                if w + 1 < w_valid and tok == toks[w + 1]:
                    acc += 1
                else:
                    break
            proposed += w_valid - 1
            accepted += acc
            # KV through old+acc is verified; the last emitted token
            # (the correction) has not been fed yet.
            st.consumed = old + 1 + acc
            step_decode += len(emitted)
            self._emit(slot, st, emitted, now, tl)

        self.stats.prefill_tokens += step_prefill
        self.stats.decode_tokens += step_decode
        self.stats.steps += 1
        _metrics.counter("serve.steps").inc()
        _metrics.counter("serve.prefill_tokens").inc(step_prefill)
        _metrics.counter("serve.decode_tokens").inc(step_decode)
        if proposed:
            self._spec_proposed += proposed
            self._spec_accepted += accepted
            _metrics.counter("serve.spec.proposed").inc(proposed)
            _metrics.counter("serve.spec.accepted").inc(accepted)
            _metrics.gauge("serve.spec.acceptance_rate").set(
                self._spec_accepted / max(1, self._spec_proposed))
        _flight.instant("FLIGHT:SERVE_STEP", tid="flight",
                        args={"engine": self.name,
                              "step": self.stats.steps,
                              "prefill": n_prefill, "decode": n_decode,
                              "slots": len(self.slots), "window": W})
        return step_prefill + step_decode

    def _sample(self, row: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(row))
        z = row.astype(np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    # -- trace loop -------------------------------------------------------

    def run(self, requests: Optional[Sequence[Request]] = None, *,
            clock=None, max_steps: int = 100_000) -> ServeStats:
        """Submit ``requests`` and step until queue and slots are empty.
        ``clock`` defaults to a fresh :class:`WallClock`; pass a
        :class:`VirtualClock` for deterministic tests."""
        clock = clock or WallClock()
        for req in (requests or ()):
            self.submit(req)
        t0 = clock()
        for _ in range(max_steps):
            if not self.has_work:
                break
            now = clock()
            if self.step(now) == 0 and not isinstance(clock, VirtualClock):
                time.sleep(1e-3)  # open-loop trace: next arrival is ahead
            if isinstance(clock, VirtualClock):
                clock.tick()
        else:
            raise RuntimeError(f"engine did not drain in {max_steps} steps")
        self.stats.wall_time = clock() - t0
        return self.stats

    # -- drain (replica resize) -------------------------------------------

    def drain(self) -> List[Request]:
        """Stop this replica: every in-flight request leaves with its
        progress folded into the prompt, ready to re-queue elsewhere.
        The engine is empty (but reusable) afterwards."""
        tl = basics._state.timeline if basics.is_initialized() else None
        if tl is not None and self.slots:
            tl.instant(f"SERVE:DRAIN {self.name} "
                       f"{len(self.slots)} in-flight", tid=self.name)
        for st in self.slots.values():
            _stall.record_done(f"serve.req{st.req.req_id}")
        self.slots.clear()
        # Pending migrated payloads are dropped with the drain — their
        # requests are still queued and simply replay prefill wherever
        # they land next.
        self._migrated.clear()
        drained = self.sched.drain()
        self.stats.resizes += len(drained)
        queued, self.sched.queue = self.sched.queue, []
        return queued
