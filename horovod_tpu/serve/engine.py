"""Continuous-batching generation engine (one replica).

Iteration-level (Orca-style) batching: every engine step runs ONE
compiled program over all ``max_slots`` batch slots, each slot consuming
exactly one token — a *prompt* token for sequences still in prefill
(teacher-forced, logits discarded until the boundary) or the previously
*sampled* token for sequences in decode. Prefill and decode therefore mix
freely in the same compiled step; there is no static-batch barrier:
finished sequences are evicted and queued requests admitted between any
two steps, and the compiled shape never changes (dead slots ride along
masked, their writes landing on the cache's null page).

The model runs under ``hvd.shard_map`` over the replica's mesh with
attention heads tensor-parallel (``tp_axis``) and the KV page pools
sharded the same way — the serving analogue of the training TP path, on
the identical collective stack. Timeline spans: ``SERVE:PREFILL`` /
``SERVE:DECODE`` bracket the compiled call (whichever phases the step
contains), ``SERVE:ADMIT`` / ``SERVE:EVICT`` / ``SERVE:PREEMPT`` are
instants with the slot/request in the name.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..common import basics
from ..models.gpt import GPT, GPTConfig
from ..monitor import flight as _flight
from ..monitor import registry as _metrics
from ..monitor import stall as _stall
from ..parallel.tensor import tp_merge_params, tp_split_params
from . import kv_cache as kvlib
from .kv_cache import KVCache, PageConfig
from .scheduler import Request, Scheduler

SERVE_TP_AXIS = "serve_tp"


class WallClock:
    def __call__(self) -> float:
        return time.monotonic() - self._t0

    def __init__(self) -> None:
        self._t0 = time.monotonic()


class VirtualClock:
    """Deterministic clock: advances ``dt`` per engine step (tests; wall
    time would make admission order timing-dependent)."""

    def __init__(self, dt: float = 1.0) -> None:
        self.dt = dt
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def tick(self) -> None:
        self.now += self.dt


@dataclass
class ServeStats:
    """One trace's outcome (see docs/serving.md for the metric defs)."""

    completed: List[Request] = field(default_factory=list)
    steps: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0
    wall_time: float = 0.0
    preemptions: int = 0
    resizes: int = 0

    @property
    def throughput_tokens(self) -> int:
        """Every token the engine processed (prefill + decode, including
        replayed work after preemption/resize)."""
        return self.prefill_tokens + self.decode_tokens

    @property
    def goodput_tokens(self) -> int:
        """Tokens that reached a user: generated tokens of COMPLETED
        requests only — replayed prefill and abandoned partials don't
        count."""
        return sum(len(r.generated) for r in self.completed)

    def tokens_per_sec(self) -> float:
        return self.throughput_tokens / max(self.wall_time, 1e-9)

    def goodput_tokens_per_sec(self) -> float:
        return self.goodput_tokens / max(self.wall_time, 1e-9)

    def latency_percentiles(self) -> Dict[str, float]:
        lats = sorted(r.latency for r in self.completed
                      if r.latency is not None)
        if not lats:
            return {"p50": float("nan"), "p99": float("nan")}
        def pct(p):
            return lats[min(len(lats) - 1, int(p * (len(lats) - 1) + 0.5))]
        return {"p50": pct(0.50), "p99": pct(0.99)}

    def merge(self, other: "ServeStats") -> None:
        self.completed.extend(other.completed)
        self.steps += other.steps
        self.prefill_tokens += other.prefill_tokens
        self.decode_tokens += other.decode_tokens
        self.preemptions += other.preemptions
        self.resizes += other.resizes


@dataclass
class _SlotState:
    req: Request
    consumed: int = 0   # tokens fed = this slot's device write cursor

    @property
    def n_prompt(self) -> int:
        return len(self.req.prompt)

    def next_token(self) -> int:
        if self.consumed < self.n_prompt:
            return self.req.prompt[self.consumed]
        return self.req.generated[-1]

    @property
    def in_prefill(self) -> bool:
        # The step consuming the LAST prompt token already produces the
        # first sampled logits — count it as decode for TTFT purposes.
        return self.consumed < self.n_prompt - 1


class GenerationEngine:
    """One replica: a compiled mixed prefill/decode step over a device
    group, plus the host-side continuous-batching loop.

    ``devices``: the replica's device subset — becomes a 1-D
    ``(serve_tp,)`` mesh with attention heads (and KV pools) sharded
    ``len(devices)``-way. Alternatively pass an existing ``mesh`` +
    ``tp_axis`` (e.g. the Horovod mesh with ``tp_axis=hvd.HVD_AXES``).
    ``params`` are the DENSE model params; the engine splits them.
    """

    def __init__(self, cfg: GPTConfig, params, page_config: PageConfig,
                 *, devices: Optional[Sequence] = None,
                 mesh: Optional[Mesh] = None, tp_axis=None,
                 eos_id: int = 1, temperature: float = 0.0,
                 seed: int = 0, name: str = "replica0",
                 moe_experts: int = 0, expert_router=None) -> None:
        import dataclasses

        if mesh is None:
            if devices is None:
                devices = [jax.devices()[0]]
            mesh = Mesh(np.array(list(devices)), (SERVE_TP_AXIS,))
            tp_axis = SERVE_TP_AXIS
        if tp_axis is None:
            raise ValueError("pass tp_axis along with mesh")
        tp = int(np.prod([mesh.shape[a] for a in (
            (tp_axis,) if isinstance(tp_axis, str) else tp_axis)]))
        if cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by the replica's "
                f"tp degree {tp} ({len(mesh.devices.ravel())} devices)")
        if page_config.num_heads != cfg.num_heads or \
                page_config.num_layers != cfg.num_layers or \
                page_config.head_dim != cfg.d_model // cfg.num_heads:
            raise ValueError("page_config geometry does not match the "
                             "model config")
        self.cfg = dataclasses.replace(
            cfg, tp_axis=(tp_axis if tp > 1 else None))
        self.page_config = page_config
        self.mesh = mesh
        self.tp = tp
        self.eos_id = eos_id
        self.temperature = temperature
        self.name = name
        self._rng = np.random.RandomState(seed)
        self.sched = Scheduler(page_config)
        self.slots: Dict[int, _SlotState] = {}
        self.stats = ServeStats()
        # Expert-parallel decode accounting (docs/moe.md): with
        # ``moe_experts`` > 0 every consumed token is attributed to its
        # routed expert — ``expert_router(token_id) -> expert`` (default:
        # the deterministic ``token % E`` proxy, replaced by the model's
        # real router when the served model is MoE) — feeding the
        # per-expert ``serve.expert_tokens{expert}`` load histograms the
        # hot-expert replication layer (replica.py) reads.
        self.moe_experts = max(0, int(moe_experts))
        self._expert_router = expert_router or (
            (lambda tok: int(tok) % self.moe_experts)
            if self.moe_experts else None)
        self.expert_tokens = (np.zeros((self.moe_experts,), np.int64)
                              if self.moe_experts else None)

        stacked, repl = tp_split_params(params, tp)
        stk_spec = P(tp_axis) if tp > 1 else P()
        rep_sh = jax.sharding.NamedSharding(mesh, P())
        tp_sh = jax.sharding.NamedSharding(mesh, stk_spec)
        self._stacked = jax.device_put(stacked, tp_sh)
        self._repl = jax.device_put(repl, rep_sh)

        # tp=1: fully replicated specs (a head-sharded in_spec on a size-1
        # axis would mark every downstream value varying and fail the
        # out_specs replication check even though no collective differs).
        pool_spec = (P(None, None, None, tp_axis, None) if tp > 1
                     else P())
        cache_specs = KVCache(k=pool_spec, v=pool_spec,
                              page_table=P(), seq_lens=P())
        model_cfg = self.cfg

        def spmd(stk, rp, cache, tokens, active):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(model_cfg).apply({"params": local}, tokens,
                                        cache=cache, active=active)

        self._step_fn = jax.jit(basics.shard_map(
            spmd, mesh=mesh,
            in_specs=(stk_spec, P(), cache_specs, P(), P()),
            out_specs=(P(), cache_specs)))

        cache = kvlib.init_cache(page_config, tp=1)  # global-shaped pools
        cache_sh = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), cache_specs)
        self.cache = jax.device_put(cache, cache_sh)

    # -- queue ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.sched.submit(req)

    @property
    def has_work(self) -> bool:
        return bool(self.sched.queue or self.slots)

    def queue_depth(self) -> int:
        return self.sched.queue_depth()

    def in_flight(self) -> int:
        return len(self.slots)

    # -- the continuous-batching step -------------------------------------

    def step(self, now: float) -> int:
        """Admit, run ONE compiled mixed prefill/decode step, sample,
        evict. Returns the number of tokens processed (0 = idle)."""
        tl = basics._state.timeline if basics.is_initialized() else None
        for slot in self.sched.admit(now):
            self.slots[slot] = _SlotState(self.sched.running[slot])
            req_id = self.slots[slot].req.req_id
            _metrics.counter("serve.admissions").inc()
            # The StallInspector watches every admitted request: one that
            # sits in a slot past stall_check_time (a wedged compiled
            # step, a starved replica) surfaces as a STALL:serve.req*
            # warning (docs/observability.md).
            _stall.record_start(f"serve.req{req_id}", kind="serve")
            if tl is not None:
                tl.instant(f"SERVE:ADMIT slot{slot} "
                           f"req{req_id}", tid=self.name)
        _metrics.gauge("serve.queue_depth").set(self.sched.queue_depth())
        _metrics.gauge("serve.in_flight").set(len(self.slots))
        if not self.slots:
            return 0

        # Page growth for this step's write position; preempt youngest on
        # an empty pool (the preempted slot leaves the batch mid-flight).
        for slot in sorted(self.slots):
            if slot not in self.slots:   # evicted by a preemption below
                continue
            st = self.slots[slot]
            while not self.sched.ensure_page(slot, st.consumed):
                victim = self.sched.preempt_for_page(slot)
                if victim is None:
                    raise RuntimeError(
                        f"page pool exhausted by a single sequence "
                        f"(slot {slot}, pos {st.consumed}): size the pool "
                        f"to at least pages_for(prompt+max_new_tokens)")
                self.stats.preemptions += 1
                _metrics.counter("serve.preemptions").inc()
                _stall.record_done(
                    f"serve.req{self.slots[victim].req.req_id}")
                if tl is not None:
                    tl.instant(
                        f"SERVE:PREEMPT slot{victim} "
                        f"req{self.slots[victim].req.req_id}",
                        tid=self.name)
                del self.slots[victim]

        S = self.page_config.max_slots
        tokens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        lens = np.zeros((S,), np.int32)
        n_prefill = n_decode = 0
        for slot, st in self.slots.items():
            tokens[slot] = st.next_token()
            active[slot] = True
            lens[slot] = st.consumed
            if st.in_prefill:
                n_prefill += 1
            else:
                n_decode += 1

        # Host mirrors are authoritative: admission/eviction/preemption
        # edit the table and reset cursors, so push both every step.
        cache = self.cache._replace(
            page_table=jnp.asarray(self.sched.page_table),
            seq_lens=jnp.asarray(lens))
        phases = ([("PREFILL", n_prefill)] if n_prefill else []) + \
                 ([("DECODE", n_decode)] if n_decode else [])
        if tl is not None:
            for ph, _ in phases:
                tl.begin(self.name, f"SERVE:{ph}")
        # StepTraceAnnotation: the device-trace step marker, so a
        # jax.profiler capture of a serving run shows one annotated step
        # per engine iteration (the same marker hvd.profile_window and
        # DistributedOptimizer use — host/device trace correlation).
        with jax.profiler.StepTraceAnnotation("serve_step",
                                              step_num=self.stats.steps):
            logits, self.cache = self._step_fn(
                self._stacked, self._repl, cache,
                jnp.asarray(tokens), jnp.asarray(active))
        if tl is not None:
            for ph, _ in reversed(phases):
                tl.end(self.name, f"SERVE:{ph}")
        logits = np.asarray(logits)

        self.stats.prefill_tokens += n_prefill
        self.stats.decode_tokens += n_decode
        self.stats.steps += 1
        if self.moe_experts:
            # Per-expert load this step: one histogram observation per
            # expert that saw traffic (the registry's log2 buckets give
            # the load distribution; the count is the step total).
            step_load = np.zeros((self.moe_experts,), np.int64)
            for slot in self.slots:
                step_load[self._expert_router(int(tokens[slot]))] += 1
            self.expert_tokens += step_load
            for e in np.nonzero(step_load)[0]:
                _metrics.histogram("serve.expert_tokens",
                                   expert=str(int(e))).observe(
                    float(step_load[e]))
        _metrics.counter("serve.steps").inc()
        _metrics.counter("serve.prefill_tokens").inc(n_prefill)
        _metrics.counter("serve.decode_tokens").inc(n_decode)
        # Flight ring (monitor/flight.py): one instant per engine step —
        # the serving analogue of FLIGHT:STEP, so a crashed replica's
        # dump shows what the batch looked like when it died.
        _flight.instant("FLIGHT:SERVE_STEP", tid="flight",
                        args={"engine": self.name,
                              "step": self.stats.steps,
                              "prefill": n_prefill, "decode": n_decode,
                              "slots": len(self.slots)})

        for slot in list(self.slots):
            st = self.slots[slot]
            st.consumed += 1
            if st.consumed < st.n_prompt:
                continue  # still prefilling: logits discarded
            tok = self._sample(logits[slot])
            st.req.generated.append(tok)
            if st.req.first_token_time is None:
                st.req.first_token_time = now
            if tok == self.eos_id or st.req.remaining_new_tokens <= 0:
                reason = "eos" if tok == self.eos_id else "length"
                req = self.sched.evict(slot, now, reason)
                del self.slots[slot]
                self.stats.completed.append(req)
                _metrics.counter("serve.completions", reason=reason).inc()
                _stall.record_done(f"serve.req{req.req_id}")
                if tl is not None:
                    tl.instant(f"SERVE:EVICT slot{slot} req{req.req_id} "
                               f"{reason}", tid=self.name)
        return n_prefill + n_decode

    def _sample(self, row: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(row))
        z = row.astype(np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    # -- trace loop -------------------------------------------------------

    def run(self, requests: Optional[Sequence[Request]] = None, *,
            clock=None, max_steps: int = 100_000) -> ServeStats:
        """Submit ``requests`` and step until queue and slots are empty.
        ``clock`` defaults to a fresh :class:`WallClock`; pass a
        :class:`VirtualClock` for deterministic tests."""
        clock = clock or WallClock()
        for req in (requests or ()):
            self.submit(req)
        t0 = clock()
        for _ in range(max_steps):
            if not self.has_work:
                break
            now = clock()
            if self.step(now) == 0 and not isinstance(clock, VirtualClock):
                time.sleep(1e-3)  # open-loop trace: next arrival is ahead
            if isinstance(clock, VirtualClock):
                clock.tick()
        else:
            raise RuntimeError(f"engine did not drain in {max_steps} steps")
        self.stats.wall_time = clock() - t0
        return self.stats

    # -- drain (replica resize) -------------------------------------------

    def drain(self) -> List[Request]:
        """Stop this replica: every in-flight request leaves with its
        progress folded into the prompt, ready to re-queue elsewhere.
        The engine is empty (but reusable) afterwards."""
        tl = basics._state.timeline if basics.is_initialized() else None
        if tl is not None and self.slots:
            tl.instant(f"SERVE:DRAIN {self.name} "
                       f"{len(self.slots)} in-flight", tid=self.name)
        for st in self.slots.values():
            _stall.record_done(f"serve.req{st.req.req_id}")
        self.slots.clear()
        drained = self.sched.drain()
        self.stats.resizes += len(drained)
        queued, self.sched.queue = self.sched.queue, []
        return queued
