"""Elastic driver: discovery loop, dynamic rank assignment, worker lifecycle.

Reference surface: ``horovod/runner/elastic/driver.py`` (309 LoC) —
``ElasticDriver`` runs a discovery thread (diff host set every
DISCOVER_HOSTS_FREQUENCY_SECS, notify workers on churn), computes host
assignments for each world incarnation, spawns one worker per slot, handles
worker exits (blacklist + resume), and serves rank/size to workers at
rendezvous (rendezvous.py:37-42 → driver.record_ready).

Redesign: the reference splits rendezvous (HTTP) from notification (RPC);
here both ride one HMAC-signed RPC service owned by the driver
(``ElasticDriverService``). Each world incarnation gets a ``world_id`` and a
fresh native-controller port, so a worker re-rendezvousing after a reset
can ask for "an assignment newer than the one I had" and stale coordinator
sockets can never cross-talk between incarnations.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..chaos import injector as chaos
from ..common import counters
from ..runner import network
from ..runner.hosts import HostInfo, SlotInfo, get_host_assignments
from . import constants
from .discovery import HostManager, HostUpdateResult
from .registration import FAILURE, SUCCESS, WorkerStateRegistry
from .worker import WorkerNotificationClient


class GetSlotRequest:
    def __init__(self, host: str, local_rank: int, min_world_id: int = 0,
                 ifaces=None):
        self.host = host
        self.local_rank = local_rank
        self.min_world_id = min_world_id
        # [(ifname, ipv4)] of the requesting host (NIC registration,
        # reference driver_service.py:260); optional for compatibility.
        self.ifaces = ifaces


class GetSlotResponse:
    # status ∈ {"ok", "waiting", "shutdown"}
    def __init__(self, status: str, slot: Optional[dict] = None,
                 world_id: int = -1, controller_addr: str = "",
                 controller_port: int = 0):
        self.status = status
        self.slot = slot
        self.world_id = world_id
        self.controller_addr = controller_addr
        self.controller_port = controller_port


class RegisterWorkerAddressRequest:
    def __init__(self, host: str, local_rank: int, addr: str, port: int):
        self.host = host
        self.local_rank = local_rank
        self.addr = addr
        self.port = port


class SetControllerPortRequest:
    """Rank-0 worker reporting the controller port it actually bound
    (OS-assigned on its own host) for world ``world_id``."""

    def __init__(self, world_id: int, port: int):
        self.world_id = world_id
        self.port = port


class ElasticDriverService(network.BasicService):
    def __init__(self, key: bytes, driver: "ElasticDriver"):
        super().__init__("elastic driver service", key)
        self._driver = driver

    def _handle(self, req, client_address):
        if isinstance(req, GetSlotRequest):
            return self._driver.get_slot_info(
                req.host, req.local_rank, req.min_world_id,
                ifaces=getattr(req, "ifaces", None))
        if isinstance(req, RegisterWorkerAddressRequest):
            self._driver.register_worker_address(
                req.host, req.local_rank, req.addr, req.port)
            return network.AckResponse()
        if isinstance(req, SetControllerPortRequest):
            self._driver.set_controller_port(req.world_id, req.port)
            return network.AckResponse()
        return super()._handle(req, client_address)


class ElasticDriver:
    """Reference driver.py:68-309, minus the HTTP rendezvous split."""

    def __init__(self, discovery, min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None, verbose: int = 0,
                 key: Optional[bytes] = None,
                 controller_addr_override: Optional[str] = None,
                 stall_check_disable: Optional[bool] = None,
                 stall_warn_secs: Optional[float] = None,
                 stall_shutdown_secs: Optional[float] = None,
                 blacklist_cooldown_secs: Optional[float] = None):
        # controller_addr_override: tests simulating multi-host churn with
        # fake hostnames on one machine point every worker at 127.0.0.1
        # (the reference mocks ssh the same way, SURVEY §4).
        from ..runner import secret

        self._controller_addr_override = controller_addr_override
        self._min_np = min_np
        self._max_np = max_np
        self._verbose = verbose
        self._host_manager = HostManager(
            discovery, cooldown_secs=blacklist_cooldown_secs)
        self._registry = WorkerStateRegistry(self, self._host_manager,
                                             reset_limit=reset_limit,
                                             verbose=verbose > 0)
        self.key = key or secret.make_secret_key()
        self._service = ElasticDriverService(self.key, self)

        # Stall watchdog config: the --stall-check-* CLI flags land in
        # these env vars (runner/config_parser.py) and the elastic
        # launcher also passes them explicitly. Semantics: a world
        # incarnation that stops making *formation progress* (no slot
        # reaching rendezvous, no port report, no worker exit) for longer
        # than the warning threshold is reported; past the shutdown
        # threshold (0 = never) the incarnation is abandoned — hosts of
        # the slots that never showed up are blacklisted and the driver
        # resumes into a new world without them.
        self._stall_check_disable = _env_bool(
            "HOROVOD_STALL_CHECK_DISABLE", False) \
            if stall_check_disable is None else stall_check_disable
        self._stall_warn_secs = _env_float(
            "HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0) \
            if stall_warn_secs is None else stall_warn_secs
        self._stall_shutdown_secs = _env_float(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0) \
            if stall_shutdown_secs is None else stall_shutdown_secs
        self._progress_ts = time.monotonic()
        self._stall_warned_world = -1
        self._stall_handled_world = -1
        self._stall_thread: Optional[threading.Thread] = None

        self._lock = threading.RLock()
        self._world_id = -1
        self._host_order: List[str] = []
        # host -> [(ifname, ipv4)] as registered at rendezvous (NIC
        # discovery, reference driver_service.py:260).
        self._host_ifaces: Dict[str, list] = {}
        self._assignments: Dict[Tuple[str, int], SlotInfo] = {}
        self._controller_port = 0
        self._create_worker_fn: Optional[Callable] = None
        self._live_workers: Dict[Tuple[str, int], threading.Thread] = {}
        self._released: set = set()  # slots told to exit by a world shrink
        self._worker_clients: Dict[Tuple[str, int],
                                   WorkerNotificationClient] = {}
        self._shutdown = threading.Event()
        self._finished = threading.Event()
        self._result_lock = threading.Lock()
        self._discovery_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ API

    @property
    def service_port(self) -> int:
        return self._service.port

    @property
    def registry(self) -> WorkerStateRegistry:
        return self._registry

    @property
    def host_manager(self) -> HostManager:
        return self._host_manager

    @property
    def world_id(self) -> int:
        with self._lock:
            return self._world_id

    def current_assignments(self) -> List[SlotInfo]:
        with self._lock:
            return sorted(self._assignments.values(), key=lambda s: s.rank)

    def start(self, create_worker_fn: Callable[[SlotInfo, int], int]) -> None:
        """Begin discovery + spawn the first world.

        ``create_worker_fn(slot, world_id)`` runs a worker process to
        completion and returns its exit code (the launcher passes an
        ssh/local exec closure; tests pass mocks, same as reference
        test_elastic_driver.py).
        """
        self._create_worker_fn = create_worker_fn
        self.wait_for_available_slots(self._min_np)
        self._resume(initial=True)
        self._discovery_thread = threading.Thread(target=self._discover_loop,
                                                  daemon=True)
        self._discovery_thread.start()
        if not self._stall_check_disable and self._stall_warn_secs > 0:
            self._stall_thread = threading.Thread(
                target=self._stall_watchdog, daemon=True)
            self._stall_thread.start()

    def wait_for_available_slots(self, min_np: int,
                                 timeout: Optional[float] = None):
        """Block until discovery yields >= min_np slots (reference
        driver.py:150-176)."""
        timeout = timeout if timeout is not None else \
            constants.START_TIMEOUT_SECS
        deadline = time.monotonic() + timeout
        while True:
            try:
                self._host_manager.update_available_hosts()
            except Exception as e:
                # Transient discovery-script failure (same tolerance as
                # _discover_loop): keep retrying until the deadline.
                logging.warning(f"host discovery failed during startup: {e}")
            hosts = self._host_manager.current_hosts
            if sum(hosts.values()) >= min_np:
                return hosts
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots; discovered "
                    f"{hosts}")
            time.sleep(constants.DISCOVER_HOSTS_FREQUENCY_SECS)

    def stop(self) -> None:
        self._shutdown.set()
        self._finished.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the job to finish; True if at least one worker
        succeeded and the job wound down."""
        self._finished.wait(timeout)
        return (self._registry.total_count(SUCCESS) > 0
                and self._registry.count(FAILURE) == 0) or \
            (self._registry.total_count(SUCCESS) > 0
             and self._shutdown.is_set())

    def shutdown_service(self) -> None:
        self._service.shutdown()

    # ------------------------------------------------- rendezvous (workers)

    def get_slot_info(self, host: str, local_rank: int,
                      min_world_id: int = 0,
                      ifaces=None) -> GetSlotResponse:
        # An injected 'drop' here surfaces to the worker as an unanswered
        # RPC (its client retries with backoff); 'delay'/'stall' model a
        # driver too busy to grant slots.
        chaos.inject("driver.slot_grant", host=host, local_rank=local_rank)
        with self._lock:
            if ifaces:
                self._host_ifaces[host] = [tuple(i) for i in ifaces]
            if self._shutdown.is_set():
                return GetSlotResponse("shutdown")
            if self._registry.total_count(SUCCESS) > 0:
                # Winding down: a worker already finished training. A
                # re-rendezvousing worker (interrupted survivor, flapped
                # host) must exit cleanly, not wait for a world that will
                # never form. Mark released so its exit is neither success
                # nor failure.
                self._released.add((host, local_rank))
                return GetSlotResponse("shutdown")
            if self._world_id < min_world_id:
                if (min_world_id == self._world_id + 1
                        and (host, local_rank) in self._assignments
                        and (host, local_rank) not in self._released):
                    # A current-world assignee demanding a NEWER world is
                    # reporting that formation of the current world failed
                    # under it (native init timeout / peer lost mid-setup).
                    # Without this the job deadlocks: the driver sees no
                    # exits, never resumes, and every worker waits out
                    # ELASTIC_TIMEOUT. Build the next incarnation now.
                    # Concurrent reports can't storm: the first bump
                    # satisfies everyone else's min_world_id.
                    logging.warning(
                        f"worker {host}:{local_rank} reports failed "
                        f"formation of world {self._world_id}; resuming")
                    self._resume()
                if self._world_id < min_world_id:
                    return GetSlotResponse("waiting")
            slot = self._assignments.get((host, local_rank))
            if slot is None:
                # Not in the new world (host shrunk/blacklisted): worker
                # should exit cleanly. Its clean exit must NOT count as a
                # training success (it never finished func).
                self._released.add((host, local_rank))
                return GetSlotResponse("shutdown")
            # Controller port protocol: rank 0 binds port 0 on ITS host and
            # reports it via SetControllerPortRequest; everyone else waits
            # here until that report lands. No driver-side free-port guess
            # can race with the rank-0 host's port space.
            if slot.rank != 0 and slot.size > 1 and \
                    self._controller_port == 0:
                return GetSlotResponse("waiting")
            self._registry.record_ready(host, local_rank)
            self._touch_progress()
            rank0_host = next(s.hostname for s in self._assignments.values()
                              if s.rank == 0)
            if self._controller_addr_override is not None:
                addr = self._controller_addr_override
            else:
                addr = self._nic_controller_addr(rank0_host, host) or (
                    "127.0.0.1" if _is_local(rank0_host) else rank0_host)
            return GetSlotResponse("ok", slot=slot.__dict__.copy(),
                                   world_id=self._world_id,
                                   controller_addr=addr,
                                   controller_port=self._controller_port)

    def _nic_controller_addr(self, rank0_host: str,
                             requester_host: str) -> Optional[str]:
        """Rank-0's address on an interface common to rank-0's host and
        the REQUESTER's host (reference driver_service.py interface
        intersection), or None when either side hasn't registered NICs or
        there is no usable intersection. Pairwise, not world-wide: the
        controller listens on INADDR_ANY, so each worker only needs an
        address it can route itself — and a world-wide gate would hand
        early requesters the hostname heuristic whenever a slow host had
        not yet registered (exactly the unresolvable-hostname case this
        feature fixes)."""
        from ..runner import nic

        rank0_ifaces = self._host_ifaces.get(rank0_host)
        req_ifaces = self._host_ifaces.get(requester_host)
        if not rank0_ifaces or not req_ifaces:
            return None
        per_host = {rank0_host: rank0_ifaces, requester_host: req_ifaces}
        return nic.select_controller_addr(
            rank0_ifaces, per_host, allow=nic.iface_filter_from_env(),
            allow_loopback=requester_host == rank0_host)

    def set_controller_port(self, world_id: int, port: int) -> None:
        """Record the controller port rank 0 bound for ``world_id``;
        ignored if the world has already moved on (a stale incarnation's
        report must not poison the current one)."""
        with self._lock:
            if world_id == self._world_id:
                self._controller_port = port
                self._touch_progress()

    def register_worker_address(self, host: str, local_rank: int,
                                addr: str, port: int) -> None:
        client = WorkerNotificationClient(
            "worker notification service", addr, port, self.key,
            attempts=1, timeout=5.0)
        with self._lock:
            self._worker_clients[(host, local_rank)] = client

    # --------------------------------------------------- lifecycle internals

    def on_worker_failure(self, host: str, local_rank: int) -> None:
        if self._shutdown.is_set() or self._finished.is_set():
            return
        if self._registry.reset_limit_reached():
            logging.error("elastic reset limit reached — shutting down")
            self.stop()
            return
        self._maybe_resume()

    def _discover_loop(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(constants.DISCOVER_HOSTS_FREQUENCY_SECS)
            try:
                res = self._host_manager.update_available_hosts()
            except Exception as e:  # discovery script hiccup: retry
                logging.warning(f"host discovery failed: {e}")
                continue
            if res == HostUpdateResult.no_update:
                continue
            if self._shutdown.is_set():
                return
            if self._registry.total_count(SUCCESS) > 0:
                # Winding down after a success: don't interrupt the
                # remaining workers — let them finish naturally.
                continue
            # Any churn (added capacity or a graceful shrink) needs a new
            # world: re-assign immediately so re-rendezvous finds it, and
            # notify workers so they interrupt at the next commit
            # (reference driver.py:177-226). Workers on *dead* hosts
            # additionally fail their collectives (HorovodInternalError →
            # restore + re-rendezvous) via on_worker_failure.
            self._maybe_resume()
            self._notify_workers(res)

    # ---------------------------------------------------- stall watchdog

    def _touch_progress(self) -> None:
        self._progress_ts = time.monotonic()

    def _missing_slots(self) -> List[Tuple[str, int]]:
        """Assigned slots that have not reached rendezvous (or exited)
        this incarnation."""
        recorded = self._registry.recorded_slots()
        with self._lock:
            return [k for k in self._assignments
                    if f"{k[0]}:{k[1]}" not in recorded]

    def _stall_watchdog(self) -> None:
        """Enforce the --stall-check-* contract on world formation: warn
        when an incarnation stops making progress for
        ``stall_warn_secs``; past ``stall_shutdown_secs`` (if > 0),
        abandon it — blacklist the hosts whose slots never showed up and
        resume without them. The native core's stall inspector covers
        collectives *inside* a formed world; this thread covers the
        formation path the core never sees (a worker hung before init)."""
        interval = max(0.05, min(1.0, self._stall_warn_secs / 4))
        while not self._shutdown.wait(interval):
            if self._finished.is_set():
                return
            missing = self._missing_slots()
            with self._lock:
                world_id = self._world_id
                stalled_for = time.monotonic() - self._progress_ts
            if not missing:
                continue  # world fully formed (or empty): core takes over
            if stalled_for > self._stall_warn_secs and \
                    self._stall_warned_world < world_id:
                self._stall_warned_world = world_id
                counters.increment("elastic.stall.warning",
                                   attrs={"world_id": world_id})
                logging.warning(
                    f"world {world_id} formation stalled for "
                    f"{stalled_for:.1f}s — waiting on slots "
                    f"{sorted(missing)} "
                    f"(--stall-check-warning-time-seconds="
                    f"{self._stall_warn_secs:g})")
            if self._stall_shutdown_secs > 0 and \
                    stalled_for > self._stall_shutdown_secs and \
                    self._stall_handled_world < world_id:
                self._stall_handled_world = world_id
                counters.increment("elastic.stall.shutdown",
                                   attrs={"world_id": world_id})
                logging.error(
                    f"world {world_id} formation stalled for "
                    f"{stalled_for:.1f}s — abandoning the incarnation; "
                    f"blacklisting {sorted({h for h, _ in missing})}")
                # Abandon-incarnation is a flight-dump trigger: the
                # driver's ring (fault counters, stall warnings) plus
                # the missing-slot list is the postmortem's record of
                # WHICH hosts never formed (docs/observability.md).
                from ..monitor import flight as _flight

                _flight.dump_flight_record(
                    reason="elastic.abandon",
                    extra={"world_id": world_id,
                           "stalled_secs": round(stalled_for, 3),
                           "missing_slots": sorted(
                               f"{h}:{s}" for h, s in missing)})
                for host in {h for h, _ in missing}:
                    self._host_manager.blacklist(host)
                if self._registry.reset_limit_reached():
                    logging.error(
                        "elastic reset limit reached — shutting down")
                    self.stop()
                    return
                self._maybe_resume()

    def _notify_workers(self, res: int) -> None:
        with self._lock:
            clients = dict(self._worker_clients)
        ts = int(time.time() * 1000)
        for key, client in clients.items():
            try:
                client.notify_hosts_updated(ts, res)
            except ConnectionError:
                pass  # worker mid-restart; it will re-rendezvous anyway

    def _maybe_resume(self) -> None:
        with self._lock:
            self._resume()

    def _resume(self, initial: bool = False) -> None:
        """Compute assignments for the next world incarnation and spawn
        workers for slots without a live process (reference
        driver.py:292-308 resume + _activate_workers)."""
        with self._lock:
            if self._registry.total_count(SUCCESS) > 0:
                # A worker already finished training successfully: the job
                # is winding down. Building a new world here would erase
                # the success record and respawn finished slots, re-running
                # training from scratch.
                logging.info("skipping resume: job already has a "
                             "successful worker; winding down")
                return
            hosts = self._host_manager.current_hosts
            total = sum(hosts.values())
            if total < self._min_np:
                if initial:
                    raise RuntimeError(
                        f"cannot start: {total} slots < min_np={self._min_np}")
                logging.warning(
                    f"only {total} slots available (< min_np="
                    f"{self._min_np}); waiting for discovery")
                return
            # Previously-assigned hosts keep their order so rank 0 stays on
            # a SURVIVING host — state.sync() broadcasts from rank 0, and a
            # brand-new host must never be the state source (reference:
            # driver.py host_assignment_order).
            order = [h for h in self._host_order if h in hosts]
            order += sorted(h for h in hosts if h not in order)
            self._host_order = order
            host_infos = [HostInfo(h, hosts[h]) for h in order]
            slots = get_host_assignments(host_infos, self._min_np,
                                         self._max_np or total)
            self._world_id += 1
            self._touch_progress()
            # Unified observability: world transitions are a first-class
            # metric (docs/observability.md), alongside the FAULT:*
            # counters this driver already mirrors onto the Timeline.
            from ..monitor import registry as _metrics

            _metrics.counter("elastic.world_transitions").inc()
            _metrics.gauge("elastic.world_id").set(self._world_id)
            _metrics.gauge("elastic.world_size").set(len(slots))
            if not initial:
                self._registry.increment_reset_count()
            self._registry.reset()
            self._assignments = {(s.hostname, s.local_rank): s
                                 for s in slots}
            # Port 0 = "not yet known": the rank-0 worker of this world
            # binds an OS-assigned port on ITS host and reports it back via
            # SetControllerPortRequest; peers wait in get_slot_info until
            # then. (Round-2 flaw: find_free_port() probed the DRIVER's
            # port space for a socket that binds on the rank-0 worker.)
            self._controller_port = 0
            if self._verbose:
                logging.info(
                    f"world {self._world_id}: "
                    f"{[(s.hostname, s.rank) for s in slots]}")
            for key, slot in self._assignments.items():
                if key not in self._live_workers or \
                        not self._live_workers[key].is_alive():
                    self._spawn_worker(slot)

    def _spawn_worker(self, slot: SlotInfo) -> None:
        world_id = self._world_id
        key = (slot.hostname, slot.local_rank)

        def _run():
            try:
                code = self._create_worker_fn(slot, world_id)
            except Exception:
                logging.exception(f"worker {key} raised in exec")
                code = 1
            self._handle_worker_exit(slot, code)

        t = threading.Thread(target=_run, daemon=True)
        self._live_workers[key] = t
        t.start()

    def _handle_worker_exit(self, slot: SlotInfo, code: int) -> None:
        # 'delay' here models a slow exit-status pipeline (ssh teardown);
        # the lifecycle decisions below must tolerate arriving late.
        chaos.inject("driver.worker_exit", host=slot.hostname,
                     local_rank=slot.local_rank, code=code)
        self._touch_progress()
        key = (slot.hostname, slot.local_rank)
        with self._lock:
            self._live_workers.pop(key, None)
            self._worker_clients.pop(key, None)
            released = key in self._released
            self._released.discard(key)
        if self._shutdown.is_set():
            return
        if released:
            # Shrink-released worker: neither success nor failure. If the
            # host flapped (removed then re-added) its slot may already be
            # assigned in a newer world that _resume skipped while this
            # process was still alive — spawn it now or the new world
            # never forms.
            with self._lock:
                slot_now = self._assignments.get(key)
                if slot_now is not None and key not in self._live_workers \
                        and not self._shutdown.is_set() \
                        and self._registry.total_count(SUCCESS) == 0:
                    self._spawn_worker(slot_now)
                    return
        elif code == 0:
            self._registry.record_success(slot.hostname, slot.local_rank)
        else:
            self._registry.record_failure(slot.hostname, slot.local_rank)
        with self._lock:
            live = sum(1 for t in self._live_workers.values() if t.is_alive())
        if live == 0:
            # total_count: a success must end the job even if a later
            # world-reset cleared the per-incarnation states.
            if self._registry.total_count(SUCCESS) > 0:
                self._finished.set()
                self._shutdown.set()
            elif self._registry.reset_limit_reached() or \
                    not self._has_any_hosts():
                self._finished.set()
                self._shutdown.set()
            # else: resume already triggered via record_failure

    def _has_any_hosts(self) -> bool:
        return sum(self._host_manager.current_hosts.values()) > 0


def _is_local(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname(),
                        socket.getfqdn())


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")
