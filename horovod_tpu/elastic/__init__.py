"""Elastic (fault-tolerant, dynamic world) training.

Reference surface: ``hvd.elastic`` — ``State``/``ObjectState``, the
``@hvd.elastic.run`` wrapper (common/elastic.py:147-168), ``ElasticSampler``
— plus the driver-side machinery in ``horovod/runner/elastic/`` (driver,
discovery, registration, rendezvous, worker notification).

Worker protocol (reference common/elastic.py + rendezvous.py):

1. the launcher spawns the worker with ``HOROVOD_HOSTNAME``,
   ``HOROVOD_LOCAL_RANK``, ``HOROVOD_ELASTIC=1`` and the elastic driver's
   RPC coordinates (``HOROVOD_ELASTIC_DRIVER_ADDR/PORT/KEY``);
2. ``run(func)(state)`` rendezvouses: asks the driver for a slot newer than
   the last world it saw, exports the ``HOROVOD_RANK/SIZE/...`` contract +
   native controller address, and calls ``hvd.init()`` — the worker script
   must NOT call ``hvd.init()`` itself in elastic mode;
3. ``state.sync()`` broadcasts committed state from the new rank 0;
4. on ``HorovodInternalError`` (peer died mid-collective): restore to the
   last commit, shutdown, re-rendezvous, retry;
   on ``HostsUpdatedInterrupt`` (raised by ``state.commit()`` after a
   driver notification): keep state, re-rendezvous into the new world.
"""

from __future__ import annotations

import functools
import logging
import os
import socket
import time

from ..common import basics
from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from . import constants
from .discovery import (  # noqa: F401
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
    HostUpdateResult,
)
from .driver import (  # noqa: F401
    ElasticDriver,
    GetSlotRequest,
    RegisterWorkerAddressRequest,
    SetControllerPortRequest,
)
from .registration import WorkerStateRegistry  # noqa: F401
from .sampler import ElasticSampler  # noqa: F401
from .state import JaxState, ObjectState, State  # noqa: F401
from .worker import notification_manager  # noqa: F401


def _driver_client():
    from ..runner import network

    needed = ("HOROVOD_ELASTIC_DRIVER_ADDR", "HOROVOD_ELASTIC_DRIVER_PORT",
              "HOROVOD_ELASTIC_DRIVER_KEY")
    missing = [k for k in needed if k not in os.environ]
    if missing:
        raise RuntimeError(
            f"not running under the elastic driver ({missing} unset): "
            "launch this script with `hvdrun -np N --min-np N "
            "[--max-np M] --host-discovery-script ... <cmd>` (the "
            "driver injects the HOROVOD_ELASTIC_DRIVER_* coordinates "
            "into workers)")
    addr = os.environ["HOROVOD_ELASTIC_DRIVER_ADDR"]
    port = int(os.environ["HOROVOD_ELASTIC_DRIVER_PORT"])
    key = bytes.fromhex(os.environ["HOROVOD_ELASTIC_DRIVER_KEY"])
    client = network.BasicClient("elastic driver service", addr, port, key,
                                 attempts=3, timeout=10.0)
    return client, key


_last_world_id = [-1]


def _rendezvous(client) -> None:
    """Ask the driver for the next world's slot; export the env contract;
    init (reference rendezvous.py:37-42 + gloo_run.py:65-76)."""
    from ..runner import nic

    host = os.environ["HOROVOD_HOSTNAME"]
    local_rank = int(os.environ["HOROVOD_LOCAL_RANK"])
    try:
        ifaces = nic.list_interfaces()
    except Exception:  # NIC introspection must never block rendezvous
        ifaces = None
    deadline = time.monotonic() + constants.ELASTIC_TIMEOUT_SECS
    while True:
        # Worker-side rendezvous hazard gate: 'crash' is a worker dying
        # between worlds (driver sees the exit and resumes without it);
        # 'stall' holds this slot back and trips the driver's formation
        # watchdog rather than any collective-level detector.
        from ..chaos import injector as _chaos

        _chaos.inject("bootstrap.rendezvous", phase="elastic",
                      world_id=_last_world_id[0] + 1)
        resp = client._send(GetSlotRequest(host, local_rank,
                                           _last_world_id[0] + 1,
                                           ifaces=ifaces))
        if resp.status == "ok":
            break
        if resp.status == "shutdown":
            logging.info("driver released this worker — exiting cleanly")
            raise SystemExit(0)
        if time.monotonic() > deadline:
            raise TimeoutError("elastic rendezvous timed out")
        time.sleep(constants.WORKER_RENDEZVOUS_RETRY_SECS)

    slot = resp.slot
    os.environ.update({
        "HOROVOD_RANK": str(slot["rank"]),
        "HOROVOD_SIZE": str(slot["size"]),
        "HOROVOD_LOCAL_RANK": str(slot["local_rank"]),
        "HOROVOD_LOCAL_SIZE": str(slot["local_size"]),
        "HOROVOD_CROSS_RANK": str(slot["cross_rank"]),
        "HOROVOD_CROSS_SIZE": str(slot["cross_size"]),
        "HOROVOD_CONTROLLER_ADDR": resp.controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(resp.controller_port),
    })
    _last_world_id[0] = resp.world_id
    if slot["rank"] == 0 and slot["size"] > 1 and resp.controller_port == 0:
        # This worker coordinates: bind an OS-assigned port on THIS host
        # (HOROVOD_CONTROLLER_PORT=0 → native Listen(0)) and report it to
        # the driver the moment the listener is up, so waiting peers can
        # rendezvous. Race-free by construction — the port is allocated by
        # the kernel of the host that uses it.
        world_id = resp.world_id
        basics.set_controller_port_callback(
            lambda port: client._send(SetControllerPortRequest(world_id,
                                                               port)))
    else:
        basics.set_controller_port_callback(None)
    try:
        basics.init()
    finally:
        basics.set_controller_port_callback(None)


def _register_notification_service(client, key: bytes) -> None:
    service = notification_manager.init(key)
    host = os.environ["HOROVOD_HOSTNAME"]
    local_rank = int(os.environ["HOROVOD_LOCAL_RANK"])
    addr = "127.0.0.1" if os.environ["HOROVOD_ELASTIC_DRIVER_ADDR"] in (
        "127.0.0.1", "localhost") else socket.getfqdn()
    client._send(RegisterWorkerAddressRequest(host, local_rank, addr,
                                              service.port))


def run(func):
    """Elastic training wrapper (reference common/elastic.py:147-168)::

        @hvd.elastic.run
        def train(state):
            for batch_idx in range(state.batch, num_batches):
                step(state, batches[batch_idx])
                state.batch = batch_idx
                state.commit()

        state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                     batch=0)
        train(state)
    """
    from ..cc import NativeError, NativeShutdownError

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        client, key = _driver_client()

        def _reset_world():
            """Tear down and join the next world incarnation. If a peer
            dies *during* world formation the native init fails — ask the
            driver for a yet-newer world and try again (the peer's exit
            will have triggered a resume)."""
            deadline = time.monotonic() + constants.ELASTIC_TIMEOUT_SECS
            while True:
                if basics.is_initialized():
                    basics.shutdown()
                try:
                    _rendezvous(client)
                    return
                except (NativeError, NativeShutdownError) as e:
                    if time.monotonic() > deadline:
                        raise
                    logging.warning(
                        f"world formation failed ({e}); re-rendezvousing")

        if not basics.is_initialized():
            _reset_world()
        # The State registered itself with the notification manager at
        # construction; the wrapper only has to start the service and hand
        # its address to the driver.
        _register_notification_service(client, key)
        skip_sync = False
        while True:
            try:
                if not skip_sync:
                    state.sync()
                return func(state, *args, **kwargs)
            except (HorovodInternalError, NativeShutdownError) as e:
                logging.warning(
                    f"step aborted ({e}); rolling back to last commit")
                # A peer died mid-collective: this survivor's ring holds
                # the last events before the abort — dump it before the
                # rollback erases the evidence (scripts/postmortem.py
                # joins these against the dead rank's chaos/crash dump).
                # No-op unless HOROVOD_FLIGHT_RECORDER_DIR is set.
                from ..monitor import flight as _flight

                _flight.dump_flight_record(
                    reason="elastic.reset", extra={"error": str(e)[:500]})
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                logging.info("host set changed — re-rendezvousing")
                skip_sync = e.skip_sync
            _reset_world()
            state.on_reset()
    return wrapper
