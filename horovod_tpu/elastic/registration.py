"""Worker state registry: driver-side record of worker outcomes.

Reference surface: ``horovod/runner/elastic/registration.py`` (173 LoC) —
``WorkerStateRegistry`` records each worker's READY/SUCCESS/FAILURE
transition, blacklists hosts on failure, and triggers ``driver.resume()``
when failures arrive, bounded by ``reset_limit``.

Redesign note: the reference synchronizes state transitions through a
breakable barrier sized to the world; here the driver owns worker lifetime
directly (per-slot exec threads), so the registry only needs atomic
bookkeeping + the blacklist/resume triggers — the "wait until the world
settles" logic lives in ``ElasticDriver._maybe_resume``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Set

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager,
                 reset_limit: Optional[int] = None, verbose: bool = False):
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._verbose = verbose
        self._lock = threading.Lock()
        self._states: Dict[str, str] = {}  # "host:local_rank" → state
        self._cumulative: Dict[str, int] = {READY: 0, SUCCESS: 0, FAILURE: 0}
        self._reset_count = 0

    @property
    def reset_count(self) -> int:
        with self._lock:
            return self._reset_count

    def increment_reset_count(self) -> None:
        with self._lock:
            self._reset_count += 1

    def reset_limit_reached(self) -> bool:
        with self._lock:
            return (self._reset_limit is not None
                    and self._reset_count >= self._reset_limit)

    def count(self, state: str) -> int:
        """Workers currently in ``state`` (this world incarnation)."""
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def total_count(self, state: str) -> int:
        """Cumulative transitions into ``state`` across all incarnations."""
        with self._lock:
            return self._cumulative[state]

    def get_recorded_slots(self, state: str) -> Set[str]:
        with self._lock:
            return {k for k, s in self._states.items() if s == state}

    def recorded_slots(self) -> Set[str]:
        """All ``host:local_rank`` keys that reached any state this
        incarnation (the stall watchdog's notion of 'showed up')."""
        with self._lock:
            return set(self._states)

    def reset(self) -> None:
        """Clear per-world state before a new assignment round
        (reference registration.py:63-72)."""
        with self._lock:
            self._states.clear()

    def record_ready(self, host: str, local_rank: int) -> None:
        self._record_state(host, local_rank, READY)

    def record_success(self, host: str, local_rank: int) -> None:
        self._record_state(host, local_rank, SUCCESS)

    def record_failure(self, host: str, local_rank: int) -> None:
        # Reference registration.py:105-112: a failure blacklists the host
        # so the next assignment excludes it.
        self._host_manager.blacklist(host)
        self._record_state(host, local_rank, FAILURE)
        self._driver.on_worker_failure(host, local_rank)

    def _record_state(self, host: str, local_rank: int, state: str) -> None:
        key = f"{host}:{local_rank}"
        with self._lock:
            prev = self._states.get(key)
            if prev == state:
                return
            self._states[key] = state
            self._cumulative[state] += 1
        if self._verbose:
            logging.info(f"worker {key} → {state}")
