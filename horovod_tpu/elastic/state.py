"""Elastic state: in-memory checkpoint with commit/restore/sync.

Reference surface: ``horovod/common/elastic.py:60-109`` (``State`` with
save/restore/sync/commit/check_host_updates + reset callbacks) and
``ObjectState`` (attr dict synced via ``broadcast_object``); the JAX-native
``JaxState`` plays the role of ``TorchState``/``TensorFlowState``
(torch/elastic/state.py:27, tensorflow/elastic.py): pytrees of arrays
broadcast from the new rank 0 after a reset.
"""

from __future__ import annotations

import copy
import queue
import threading
from typing import Any, Callable, Dict, List, Optional

from ..common.exceptions import HostsUpdatedInterrupt
from .discovery import HostUpdateResult
from .worker import notification_manager


class State:
    """Base elastic state (reference common/elastic.py:60-109)."""

    def __init__(self, **kwargs):
        self._host_messages: "queue.Queue" = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks: List[Callable[[], None]] = []
        notification_manager.register_listener(self)

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp: int, update_res: int) -> None:
        self._host_messages.put((timestamp, update_res))

    def commit(self) -> None:
        """Save + raise HostsUpdatedInterrupt if the world changed
        (reference common/elastic.py:84-93). Call at the point in the train
        loop where state is consistent."""
        self.save()
        # Commit points are the elastic loop's step boundaries: mark each
        # in the flight ring so a postmortem can place every rank's last
        # consistent state (monitor/flight.py; ``batch`` when the state
        # carries one — the convention of hvd.elastic examples/tests).
        from ..monitor import flight as _flight

        batch = getattr(self, "batch", None)
        _flight.instant(
            "FLIGHT:COMMIT", tid="flight",
            args=({"batch": int(batch)}
                  if isinstance(batch, int) else None))
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Drain pending host updates; raise to trigger a reset."""
        updated = False
        res = HostUpdateResult.no_update
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > self._last_updated_timestamp:
                self._last_updated_timestamp = timestamp
                updated = True
                res |= update_res
        if updated:
            raise HostsUpdatedInterrupt(res == HostUpdateResult.removed)

    # Overridables
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """Arbitrary picklable attrs, synced by broadcast from rank 0
    (reference common/elastic.py:112-146)."""

    def __init__(self, bcast_object: Optional[Callable] = None, **kwargs):
        if bcast_object is None:
            from ..parallel.functions import broadcast_object

            bcast_object = broadcast_object
        self._bcast_object = bcast_object
        self._saved_state: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        super().__init__()

    def save(self) -> None:
        new_state = {}
        for k in self._saved_state:
            new_state[k] = copy.deepcopy(getattr(self, k))
        self._saved_state = new_state

    def restore(self) -> None:
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        if self._saved_state:
            synced = self._bcast_object(self._saved_state, root_rank=0,
                                        name="elastic.object_state")
            self._saved_state = synced
            self.restore()


class JaxState(State):
    """Elastic state for JAX pytrees (params/opt_state/...) + plain attrs.

    Pytree leaves are broadcast tensor-by-tensor from rank 0 on sync()
    (the reference broadcasts parameters the same way,
    torch/elastic/state.py:27 + functions.py:30); scalars and other
    picklables ride one broadcast_object. JAX arrays are immutable, so
    save() just pins references — no copies.
    """

    def __init__(self, **kwargs):
        import jax

        self._tree_keys = [k for k, v in kwargs.items()
                           if _is_pytree_of_arrays(v)]
        self._obj_keys = [k for k in kwargs if k not in self._tree_keys]
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved: Dict[str, Any] = {}
        super().__init__()
        self.save()

    def save(self) -> None:
        self._saved = {k: getattr(self, k)
                       for k in (*self._tree_keys, *self._obj_keys)}
        # deep-copy the non-array attrs (mutable python state)
        for k in self._obj_keys:
            self._saved[k] = copy.deepcopy(self._saved[k])

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v) if k in self._obj_keys else v)

    def sync(self) -> None:
        from ..parallel.functions import broadcast_object, broadcast_parameters

        for k in self._tree_keys:
            setattr(self, k, broadcast_parameters(getattr(self, k),
                                                  root_rank=0))
        if self._obj_keys:
            objs = {k: getattr(self, k) for k in self._obj_keys}
            synced = broadcast_object(objs, root_rank=0,
                                      name="elastic.jax_state")
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()


def _is_pytree_of_arrays(value: Any) -> bool:
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(value)
    return bool(leaves) and all(
        isinstance(l, (jax.Array, np.ndarray)) for l in leaves)
