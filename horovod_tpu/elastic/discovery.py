"""Host discovery for elastic jobs.

Reference surface: ``horovod/runner/elastic/discovery.py`` (164 LoC) —
``HostDiscoveryScript`` runs a user script that prints ``host[:slots]``
lines; ``HostManager`` diffs consecutive results, tracks a blacklist, and
classifies each update as added/removed/mixed (HostUpdateResult).
"""

from __future__ import annotations

import logging
import math
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..chaos import injector as chaos
from ..common import counters


class HostUpdateResult:
    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return {hostname: slots} currently available."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user-provided discovery script (reference
    discovery.py:40-77). Each stdout line is ``host`` or ``host:slots``;
    ``default_slots`` fills in bare hostnames."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(self._script, shell=True, text=True,
                                      stderr=subprocess.DEVNULL)
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.split(":", 1)
                hosts[host] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set (reference discovery.py:80-89) — elastic semantics
    (fault tolerance, blacklist) over a fixed pool."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current/blacklisted hosts and diffs discovery results
    (reference discovery.py:92-164).

    Blacklist cooldown: the reference blacklists forever — one crash and
    the host's capacity is lost for the life of the job. With
    ``cooldown_secs > 0`` (constructor arg, or the
    ``HOROVOD_BLACKLIST_COOLDOWN_SECS`` env var) a blacklisted host is
    re-admitted after the cooldown elapses: the next discovery diff
    reports it as *added*, so the driver builds a new world that includes
    it. A host that fails again is re-blacklisted with a fresh cooldown.
    Default is 0 → infinite blacklist, the reference behavior.

    Health-gated readmission: with a ``readmission_probe`` installed
    (``host → bool``, set by the resilience supervisor), a cooled-down
    host re-enters only after the probe passes; a failing probe re-arms
    the cooldown instead of readmitting (docs/robustness.md). No probe →
    cooldown expiry alone readmits, the pre-supervisor behavior.
    """

    def __init__(self, discovery: HostDiscovery,
                 cooldown_secs: Optional[float] = None,
                 readmission_probe:
                 Optional[Callable[[str], bool]] = None):
        if cooldown_secs is None:
            try:
                cooldown_secs = float(os.environ.get(
                    "HOROVOD_BLACKLIST_COOLDOWN_SECS", "0"))
            except ValueError:
                cooldown_secs = 0.0
        self._cooldown = cooldown_secs
        self._readmission_probe = readmission_probe
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current_hosts: Dict[str, int] = {}
        self._blacklist: Dict[str, float] = {}  # host → expiry (monotonic)
        # Hosts whose cooldown expired since the last diff: the next
        # update_available_hosts must report them as added even though the
        # raw discovery result never changed.
        self._readmitted_pending: Set[str] = set()

    def set_readmission_probe(
            self, probe: Optional[Callable[[str], bool]]) -> None:
        """Install (or clear) the readmission health gate."""
        with self._lock:
            self._readmission_probe = probe

    def _prune_expired_locked(self) -> None:
        """Drop expired blacklist entries (caller holds the lock)."""
        now = time.monotonic()
        for host in [h for h, exp in self._blacklist.items() if exp <= now]:
            probe = self._readmission_probe
            if probe is not None:
                try:
                    healthy = bool(probe(host))
                except Exception:
                    healthy = False
                if not healthy:
                    # Probe failed: the host stays out for another full
                    # cooldown (or forever when cooldown is 0).
                    self._blacklist[host] = (
                        now + self._cooldown if self._cooldown > 0
                        else math.inf)
                    counters.increment("elastic.blacklist.probe_fail",
                                       attrs={"host": host})
                    logging.warning(
                        f"blacklist cooldown expired for host {host} but "
                        f"the readmission probe failed — re-arming")
                    continue
            del self._blacklist[host]
            self._readmitted_pending.add(host)
            counters.increment("elastic.blacklist.readmit",
                               attrs={"host": host})
            logging.warning(
                f"blacklist cooldown expired for host {host} — "
                f"re-admitting")

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            self._prune_expired_locked()
            return {h: s for h, s in self._current_hosts.items()
                    if h not in self._blacklist}

    def blacklist(self, host: str) -> None:
        """Reference discovery.py:128-136, plus cooldown: without one the
        failed host never returns; with one it may rejoin after
        ``cooldown_secs`` (a fresh failure re-arms the timer)."""
        with self._lock:
            expiry = time.monotonic() + self._cooldown \
                if self._cooldown > 0 else math.inf
            fresh = host not in self._blacklist
            self._blacklist[host] = expiry
            self._readmitted_pending.discard(host)
            if fresh:
                counters.increment("elastic.blacklist",
                                   attrs={"host": host})
                logging.warning(
                    f"blacklisting host {host}"
                    + (f" for {self._cooldown:.0f}s"
                       if self._cooldown > 0 else ""))

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            self._prune_expired_locked()
            return host in self._blacklist

    def update_available_hosts(self) -> int:
        """Run discovery once; return a HostUpdateResult mask."""
        if chaos.inject("discovery.update") == "flap":
            # Injected flap: the discovery source transiently reports an
            # empty world (DNS blip, control-plane hiccup).
            new_hosts: Dict[str, int] = {}
        else:
            new_hosts = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            self._prune_expired_locked()
            readmitted = self._readmitted_pending
            self._readmitted_pending = set()
            # A just-readmitted host is excluded from `old` so the diff
            # reports it as added (the raw result may not have changed).
            old = {h: s for h, s in self._current_hosts.items()
                   if h not in self._blacklist and h not in readmitted}
            new = {h: s for h, s in new_hosts.items()
                   if h not in self._blacklist}
            self._current_hosts = new_hosts
        res = HostUpdateResult.no_update
        for h, s in new.items():
            if h not in old or old[h] < s:
                res |= HostUpdateResult.added
        for h, s in old.items():
            if h not in new or new[h] < s:
                res |= HostUpdateResult.removed
        return res
