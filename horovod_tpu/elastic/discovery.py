"""Host discovery for elastic jobs.

Reference surface: ``horovod/runner/elastic/discovery.py`` (164 LoC) —
``HostDiscoveryScript`` runs a user script that prints ``host[:slots]``
lines; ``HostManager`` diffs consecutive results, tracks a blacklist, and
classifies each update as added/removed/mixed (HostUpdateResult).
"""

from __future__ import annotations

import logging
import subprocess
import threading
from typing import Dict, List, Optional, Set


class HostUpdateResult:
    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return {hostname: slots} currently available."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user-provided discovery script (reference
    discovery.py:40-77). Each stdout line is ``host`` or ``host:slots``;
    ``default_slots`` fills in bare hostnames."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(self._script, shell=True, text=True,
                                      stderr=subprocess.DEVNULL)
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.split(":", 1)
                hosts[host] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set (reference discovery.py:80-89) — elastic semantics
    (fault tolerance, blacklist) over a fixed pool."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current/blacklisted hosts and diffs discovery results
    (reference discovery.py:92-164)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current_hosts: Dict[str, int] = {}
        self._blacklist: Set[str] = set()

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return {h: s for h, s in self._current_hosts.items()
                    if h not in self._blacklist}

    def blacklist(self, host: str) -> None:
        """Reference discovery.py:128-136 — a failed host never returns."""
        with self._lock:
            if host not in self._blacklist:
                logging.warning(f"blacklisting host {host}")
                self._blacklist.add(host)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    def update_available_hosts(self) -> int:
        """Run discovery once; return a HostUpdateResult mask."""
        new_hosts = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            old = {h: s for h, s in self._current_hosts.items()
                   if h not in self._blacklist}
            new = {h: s for h, s in new_hosts.items()
                   if h not in self._blacklist}
            self._current_hosts = new_hosts
        res = HostUpdateResult.no_update
        for h, s in new.items():
            if h not in old or old[h] < s:
                res |= HostUpdateResult.added
        for h, s in old.items():
            if h not in new or new[h] < s:
                res |= HostUpdateResult.removed
        return res
