"""Elastic job launch: wires the CLI to the ElasticDriver.

Reference surface: ``horovod/runner/gloo_run.py:282-331``
(``launch_gloo_elastic``): build the discovery object from
--host-discovery-script (or fixed hosts), start the driver, and exec one
worker per slot with the elastic env contract. Worker commands are built
like static slots, but identity env is (hostname, local_rank) only — the
rank/size contract arrives later via rendezvous.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional, Sequence

from ..runner import safe_shell_exec
from ..runner.hosts import SlotInfo, parse_host_files, parse_hosts
from ..runner.static_run import get_run_command, is_local_host
from .discovery import FixedHosts, HostDiscoveryScript
from .driver import ElasticDriver


def _worker_env(slot: SlotInfo, driver: ElasticDriver,
                base_env: Dict[str, str]) -> Dict[str, str]:
    env = dict(base_env)
    env.update({
        "HOROVOD_HOSTNAME": slot.hostname,
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_ELASTIC": "1",
        "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1"
        if is_local_host(slot.hostname) else _driver_addr(),
        "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
        "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
    })
    return env


def _driver_addr() -> str:
    import socket

    return socket.getfqdn()


def make_exec_worker_fn(command: Sequence[str], env: Dict[str, str],
                        driver: ElasticDriver, verbose: int = 0,
                        ssh_port: Optional[int] = None):
    """create_worker_fn for ElasticDriver: exec the training command for a
    slot, return its exit code (reference gloo_run.py:282-320)."""

    def _exec(slot: SlotInfo, world_id: int) -> int:
        senv = _worker_env(slot, driver, env)
        cmd = get_run_command(command, slot.hostname, senv,
                              ssh_port=ssh_port)
        if verbose >= 2:
            print(f"[elastic] spawn {slot.hostname}:{slot.local_rank} "
                  f"world {world_id}: {cmd}", file=sys.stderr)
        return safe_shell_exec.execute(
            cmd, env=senv, index=f"{slot.hostname}:{slot.local_rank}")

    return _exec


def launch_elastic(args, env: Optional[Dict[str, str]] = None) -> None:
    """CLI entry (reference launch.py:575 _run_elastic →
    gloo_run_elastic)."""
    env = dict(env if env is not None else os.environ)
    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots or 1)
    else:
        hosts = parse_host_files(args.hostfile) if args.hostfile \
            else parse_hosts(args.hosts)
        discovery = FixedHosts({h.hostname: h.slots for h in hosts})

    min_np = args.min_np or args.np
    max_np = args.max_np
    # --stall-check-* flags drive the driver's formation watchdog directly
    # (the env copies from config_parser only reach worker processes).
    driver = ElasticDriver(
        discovery, min_np=min_np, max_np=max_np,
        reset_limit=args.reset_limit, verbose=args.verbose,
        stall_check_disable=getattr(args, "no_stall_check", None),
        stall_warn_secs=getattr(args, "stall_check_warning_time_seconds",
                                None),
        stall_shutdown_secs=getattr(
            args, "stall_check_shutdown_time_seconds", None))
    try:
        driver.start(make_exec_worker_fn(
            args.command, env, driver, verbose=args.verbose,
            ssh_port=getattr(args, "ssh_port", None)))
        ok = driver.join()
        if not ok:
            raise RuntimeError("elastic job failed (no successful worker)")
    finally:
        driver.stop()
        driver.shutdown_service()
