"""ElasticSampler: re-shards remaining samples when the world changes.

Reference surface: ``horovod/torch/elastic/sampler.py`` — a distributed
sampler that records processed indices; after a reset the *unprocessed*
remainder of the epoch is re-partitioned over the new world so no sample is
dropped or double-trained.
"""

from __future__ import annotations

import random
from typing import Iterator, List, Optional, Set

from ..common import basics


class ElasticSampler:
    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0, rank: Optional[int] = None,
                 size: Optional[int] = None):
        self.dataset_size = dataset_size
        self.shuffle = shuffle
        self.seed = seed
        self._fixed_world = (rank, size) if size is not None else None
        self.epoch = 0
        self.processed_indices: Set[int] = set()
        self._reshard()

    # -- State protocol hooks (registered via state.register_reset_callbacks
    #    or stored inside an ObjectState) --

    def state_dict(self) -> dict:
        return {"epoch": self.epoch,
                "processed_indices": set(self.processed_indices)}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state["epoch"]
        self.processed_indices = set(state["processed_indices"])
        self._reshard()

    def set_epoch(self, epoch: int) -> None:
        """New epoch: forget processed set, reshuffle (reference
        sampler.py set_epoch)."""
        self.epoch = epoch
        self.processed_indices = set()
        self._reshard()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the batch's global indices processed (call after commit)."""
        begin = batch_idx * batch_size
        self.processed_indices.update(self.indices[begin:begin + batch_size])

    def reset(self) -> None:
        """World changed: re-partition the unprocessed remainder."""
        self._reshard()

    def _world(self):
        if self._fixed_world is not None:
            return self._fixed_world
        if basics.is_initialized():
            return basics.rank(), basics.size()
        return 0, 1

    def _reshard(self) -> None:
        rank, size = self._world()
        remaining = [i for i in range(self.dataset_size)
                     if i not in self.processed_indices]
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(remaining)
        # Truncate so every rank has the same number of batches (the
        # reference pads instead; truncation keeps steps aligned without
        # duplicating samples).
        per_rank = len(remaining) // size
        self.indices: List[int] = remaining[rank * per_rank:(rank + 1) *
                                            per_rank] if per_rank else []

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices)

    def __len__(self) -> int:
        return len(self.indices)
