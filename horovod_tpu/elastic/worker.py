"""Worker-side elastic plumbing: notification channel + driver RPC client.

Reference surface: ``horovod/runner/elastic/worker.py`` —
``WorkerNotificationService`` (runs inside each worker; the driver pushes
``HostsUpdatedRequest`` when discovery sees churn) and
``WorkerNotificationManager`` (worker-global registry of listening States).
The driver-side client lives here too, mirroring
``WorkerNotificationClient``.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from ..runner import network
from .discovery import HostUpdateResult


class HostsUpdatedRequest:
    def __init__(self, timestamp: int, res: int = HostUpdateResult.added):
        self.timestamp = timestamp
        self.res = res


class WorkerNotificationService(network.BasicService):
    """Listens inside the worker for driver pushes (reference
    worker.py:40-74)."""

    def __init__(self, key: bytes, manager: "WorkerNotificationManager"):
        super().__init__("worker notification service", key)
        self._manager = manager

    def _handle(self, req, client_address):
        if isinstance(req, HostsUpdatedRequest):
            self._manager.handle_hosts_updated(req.timestamp, req.res)
            return network.AckResponse()
        return super()._handle(req, client_address)


class WorkerNotificationClient(network.BasicClient):
    """Driver-side handle to one worker's notification service."""

    def notify_hosts_updated(self, timestamp: int, res: int) -> None:
        self._send(HostsUpdatedRequest(timestamp, res))


class WorkerNotificationManager:
    """Worker-global singleton: registered States get host-update events
    (reference worker.py:77-130)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._service: Optional[WorkerNotificationService] = None
        self._listeners: List[object] = []

    def init(self, key: bytes) -> WorkerNotificationService:
        with self._lock:
            if self._service is None:
                self._service = WorkerNotificationService(key, self)
            return self._service

    @property
    def service(self) -> Optional[WorkerNotificationService]:
        return self._service

    def register_listener(self, state) -> None:
        with self._lock:
            self._listeners.append(state)

    def remove_listener(self, state) -> None:
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)

    def handle_hosts_updated(self, timestamp: int, res: int) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for state in listeners:
            state.on_hosts_updated(timestamp, res)


notification_manager = WorkerNotificationManager()
