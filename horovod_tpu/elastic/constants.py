"""Elastic tuning knobs (reference: horovod/runner/elastic/constants.py).

All overridable via env so integration tests can accelerate discovery the
same way the reference mocks DISCOVER_HOSTS_FREQUENCY_SECS
(test/integration/elastic_common.py).
"""

import os

DISCOVER_HOSTS_FREQUENCY_SECS = float(
    os.environ.get("HOROVOD_ELASTIC_DISCOVER_HOSTS_FREQUENCY_SECS", "1.0"))

ELASTIC_TIMEOUT_SECS = float(
    os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))

START_TIMEOUT_SECS = float(
    os.environ.get("HOROVOD_ELASTIC_START_TIMEOUT", "600"))

WORKER_RENDEZVOUS_RETRY_SECS = 0.2
