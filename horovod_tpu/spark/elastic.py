"""Elastic Spark jobs (reference: horovod.spark.run_elastic,
spark/runner.py:303-417).

The reference runs `num_proc` long-lived Spark tasks, each hosting a task
service; the gloo elastic launcher then execs workers *through* those task
services, with host discovery reading the set of live tasks
(spark/driver/host_discovery.py). This module is the same architecture on
horovod_tpu's primitives:

- ``TaskDispatcher`` — an HMAC RPC service on the Spark driver. Spark tasks
  register (host), then poll for commands; the ElasticDriver's
  ``create_worker_fn`` dispatches a "spawn worker for slot X" command to an
  idle task on the right host and blocks until the task reports the worker's
  exit code (the role of the reference's ``SparkTaskService.run_command``).
- ``SparkTaskDiscovery`` — elastic host discovery = hosts with live
  registered tasks (reference: host_discovery.py). A task that stops
  polling (executor lost) ages out, so Spark executor loss shows up as a
  host-removed event and triggers the normal elastic reshuffle.
- ``task_loop`` — runs inside each Spark task: register → poll → spawn the
  worker **subprocess** (crashes must kill the worker, not the task) →
  report rc (+ pickled fn result) → repeat until shutdown.
- ``run_elastic`` — the thin pyspark wrapper: launch the task stage in a
  background thread and drive ``ElasticDriver`` over the dispatcher. The
  pyspark-free core (``run_elastic_core``) is what the tests exercise with
  plain subprocess "tasks", mirroring the reference's mocked-ssh strategy
  (SURVEY §4).

Workers receive only identity + driver-service env (hostname, local_rank,
HOROVOD_ELASTIC_DRIVER_ADDR/PORT/KEY); rank/size arrive via rendezvous, so
resizes stay correct. ``fn`` is expected to use ``hvd.elastic.run`` with
committed state, as in the reference.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..elastic.driver import ElasticDriver
from ..elastic.discovery import HostDiscovery
from ..runner import network, secret

_POLL_INTERVAL_SECS = 0.2
_TASK_STALE_SECS = 10.0


# ---------------------------------------------------------------- wire types


class RegisterTaskRequest:
    def __init__(self, host: str):
        self.host = host


class RegisterTaskResponse:
    def __init__(self, task_id: int):
        self.task_id = task_id


class PollCommandRequest:
    def __init__(self, task_id: int):
        self.task_id = task_id


class CommandResponse:
    # command ∈ None | {"type": "spawn", "command_id": int, "env": dict}
    #         | {"type": "shutdown"}
    def __init__(self, command: Optional[dict]):
        self.command = command


class ReportResultRequest:
    def __init__(self, task_id: int, command_id: int, rc: int,
                 result: Optional[bytes] = None):
        self.task_id = task_id
        self.command_id = command_id
        self.rc = rc
        self.result = result


# ---------------------------------------------------------------- dispatcher


class _TaskState:
    def __init__(self, host: str):
        self.host = host
        self.last_seen = time.monotonic()
        self.queue: List[dict] = []
        self.busy = False


class TaskDispatcher(network.BasicService):
    """Driver-side command dispatch to registered Spark tasks."""

    def __init__(self, key: Optional[bytes] = None):
        self.key = key or secret.make_secret_key()
        super().__init__("spark task dispatcher", self.key)
        self._lock = threading.Condition()
        self._tasks: Dict[int, _TaskState] = {}
        self._next_task = 0
        self._next_command = 0
        self._results: Dict[int, Tuple[int, Optional[bytes]]] = {}
        self._shutdown = False

    # -- RPC ----------------------------------------------------------------

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._lock:
                tid = self._next_task
                self._next_task += 1
                self._tasks[tid] = _TaskState(req.host)
                self._lock.notify_all()
            return RegisterTaskResponse(tid)
        if isinstance(req, PollCommandRequest):
            with self._lock:
                t = self._tasks.get(req.task_id)
                if t is None:
                    return CommandResponse({"type": "shutdown"})
                t.last_seen = time.monotonic()
                if self._shutdown:
                    return CommandResponse({"type": "shutdown"})
                if t.queue:
                    return CommandResponse(t.queue.pop(0))
                return CommandResponse(None)
        if isinstance(req, ReportResultRequest):
            with self._lock:
                t = self._tasks.get(req.task_id)
                if t is not None:
                    t.busy = False
                    t.last_seen = time.monotonic()
                self._results[req.command_id] = (req.rc, req.result)
                self._lock.notify_all()
            return network.AckResponse()
        return super()._handle(req, client_address)

    # -- driver-side API ----------------------------------------------------

    def hosts(self) -> Dict[str, int]:
        """Live hosts → slot counts (tasks that polled recently)."""
        now = time.monotonic()
        with self._lock:
            out: Dict[str, int] = {}
            for t in self._tasks.values():
                if now - t.last_seen <= _TASK_STALE_SECS:
                    out[t.host] = out.get(t.host, 0) + 1
            return out

    def wait_for_tasks(self, count: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._tasks) < count:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._lock.wait(remain)
            return True

    def dispatch(self, host: str, env: Dict[str, str],
                 timeout: Optional[float] = None) -> Tuple[int, Any]:
        """Run a worker with ``env`` on an idle task at ``host``; block for
        its exit code. Returns (rc, unpickled fn result or None)."""
        cid = None
        task = None
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._shutdown:
                    return 1, None
                now = time.monotonic()
                for t in self._tasks.values():
                    if (t.host == host and not t.busy
                            and now - t.last_seen <= _TASK_STALE_SECS):
                        task = t
                        break
                if task is not None:
                    break
                remain = 5.0 if deadline is None else deadline - now
                if remain <= 0:
                    return 1, None
                self._lock.wait(min(remain, 1.0))
            cid = self._next_command
            self._next_command += 1
            task.busy = True
            task.queue.append({"type": "spawn", "command_id": cid,
                               "env": dict(env)})
            while cid not in self._results:
                if self._shutdown:
                    return 1, None
                # A task that stopped polling (lost executor) never reports;
                # surface that as a failed worker so the driver reshuffles.
                if (time.monotonic() - task.last_seen > _TASK_STALE_SECS
                        and cid not in self._results):
                    task.busy = False
                    return 1, None
                self._lock.wait(1.0)
            rc, blob = self._results.pop(cid)
        result = pickle.loads(blob) if (rc == 0 and blob) else None
        return rc, result

    def shutdown_tasks(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()


class SparkTaskDiscovery(HostDiscovery):
    """Host discovery from the dispatcher's live-task registry (reference:
    spark/driver/host_discovery.py — hosts of running Spark tasks)."""

    def __init__(self, dispatcher: TaskDispatcher):
        self._dispatcher = dispatcher

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return self._dispatcher.hosts()


# ---------------------------------------------------------------- task side


def _spawn_worker(fn_path: str, env: Dict[str, str]) -> Tuple[int, bytes]:
    """Run the pickled fn in a subprocess with the worker env; return
    (rc, pickled result bytes)."""
    out_path = tempfile.mktemp(prefix="hvd_spark_res_")
    child = (
        "import sys, pickle\n"
        "import cloudpickle\n"
        f"fn, args, kwargs = cloudpickle.load(open({fn_path!r}, 'rb'))\n"
        "res = fn(*args, **kwargs)\n"
        f"pickle.dump(res, open({out_path!r}, 'wb'))\n")
    full_env = dict(os.environ)
    full_env.update(env)
    try:
        proc = subprocess.run([sys.executable, "-c", child], env=full_env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
        if proc.returncode != 0:
            sys.stderr.write(proc.stdout.decode(errors="replace")[-4000:])
            return proc.returncode, b""
        with open(out_path, "rb") as f:
            return 0, f.read()
    finally:
        if os.path.exists(out_path):
            os.unlink(out_path)


def task_loop(dispatcher_addr: str, dispatcher_port: int, key: bytes,
              fn_blob: bytes, hostname: Optional[str] = None) -> int:
    """Body of one long-lived Spark task (reference: the task service loop,
    spark/task/task_service.py): register, poll, exec workers, until the
    driver says shutdown. Returns the number of workers executed."""
    import socket as _socket

    host = hostname or _socket.gethostbyname(_socket.gethostname())
    client = network.BasicClient("spark task dispatcher", dispatcher_addr,
                                 dispatcher_port, key, attempts=5,
                                 timeout=10.0)
    tid = client._send(RegisterTaskRequest(host)).task_id

    fd, fn_path = tempfile.mkstemp(prefix="hvd_spark_fn_")
    with os.fdopen(fd, "wb") as f:
        f.write(fn_blob)
    executed = 0
    # The worker runs in a thread so this loop keeps polling — the poll IS
    # the liveness heartbeat the dispatcher uses to distinguish "busy" from
    # "executor lost"; a blocking exec here would read as a dead task.
    worker: List = []  # [(command_id, thread, result_box)]
    try:
        while True:
            if worker:
                cid, th, box = worker[0]
                if not th.is_alive():
                    worker.pop(0)
                    rc, result = box[0]
                    client._send(ReportResultRequest(tid, cid, rc, result))
                    continue
            resp = client._send(PollCommandRequest(tid))
            cmd = resp.command
            if cmd is None:
                time.sleep(_POLL_INTERVAL_SECS)
                continue
            if cmd["type"] == "shutdown":
                # Let an in-flight worker finish before exiting (the driver
                # only shuts tasks down after driver.join()).
                if worker:
                    cid, th, box = worker.pop(0)
                    th.join()
                    rc, result = box[0]
                    client._send(ReportResultRequest(tid, cid, rc, result))
                return executed
            box = [(1, b"")]

            def _run(env=cmd["env"], box=box):
                box[0] = _spawn_worker(fn_path, env)

            th = threading.Thread(target=_run, daemon=True)
            th.start()
            worker.append((cmd["command_id"], th, box))
            executed += 1
    finally:
        os.unlink(fn_path)


# ---------------------------------------------------------------- driver side


def run_elastic_core(
    launch_tasks: Callable[[bytes, str, int, bytes], Any],
    fn: Callable[..., Any],
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: int = 2,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    reset_limit: Optional[int] = None,
    driver_addr: Optional[str] = None,
    controller_addr_override: Optional[str] = None,
    start_timeout: float = 60.0,
) -> List[Any]:
    """pyspark-free elastic job core. ``launch_tasks(fn_blob, addr, port,
    key)`` must start the long-lived tasks (Spark stage, subprocesses, ...)
    and return an object with ``join()``."""
    import cloudpickle

    kwargs = kwargs or {}
    fn_blob = cloudpickle.dumps((fn, args, kwargs))
    dispatcher = TaskDispatcher()
    if driver_addr is None:
        import socket as _socket

        driver_addr = _socket.gethostbyname(_socket.gethostname())

    handle = launch_tasks(fn_blob, driver_addr, dispatcher.port,
                          dispatcher.key)
    if not dispatcher.wait_for_tasks(min_np or num_proc,
                                     timeout=start_timeout):
        dispatcher.shutdown_tasks()
        raise RuntimeError(
            f"only {len(dispatcher.hosts())} spark tasks registered within "
            f"{start_timeout}s (need {min_np or num_proc})")

    driver = ElasticDriver(
        SparkTaskDiscovery(dispatcher),
        min_np=min_np or num_proc, max_np=max_np,
        reset_limit=reset_limit,
        controller_addr_override=controller_addr_override)
    # Keyed by slot identity (host, local_rank): a worker process can span
    # several world incarnations (survivors re-rendezvous in place), so its
    # spawn-time world_id/rank may be stale by the time it returns.
    results: Dict[Tuple[str, int], Any] = {}
    results_lock = threading.Lock()
    service_env = {
        "HOROVOD_ELASTIC_DRIVER_ADDR": driver_addr,
        "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
        "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
    }

    def create_worker(slot, world_id):
        env = {
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_ELASTIC": "1",
            **service_env,
        }
        rc, result = dispatcher.dispatch(slot.hostname, env)
        if rc == 0:
            with results_lock:
                results[(slot.hostname, slot.local_rank)] = result
        return rc

    final_slots = []
    try:
        driver.start(create_worker)
        ok = driver.join()
        final_slots = driver.current_assignments()
        if not ok:
            raise RuntimeError("elastic spark job failed "
                               "(no successful worker)")
    finally:
        driver.stop()
        driver.shutdown_service()
        dispatcher.shutdown_tasks()
        try:
            handle.join()
        except Exception:  # pragma: no cover - task teardown is best-effort
            pass
        dispatcher.shutdown()

    with results_lock:
        # Final world's rank-ordered results (reference run_elastic returns
        # per-rank fn results the same way).
        out = [(s.rank, results[(s.hostname, s.local_rank)])
               for s in final_slots
               if (s.hostname, s.local_rank) in results]
        if not out and results:
            return list(results.values())
        return [v for _, v in sorted(out)]
