"""Artifact stores for Spark estimators (reference:
horovod/spark/common/store.py:30-480 — Store/LocalStore/HDFSStore manage
train-data, checkpoint, and run-output locations)."""

from __future__ import annotations

import os
from typing import Optional


class Store:
    """Base artifact store (reference: store.py:30-120)."""

    def get_train_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_test_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        """Parent directory of all run artifacts (reference:
        store.py get_runs_path)."""
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        """One run's artifact directory (reference: store.py
        get_run_path)."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def sync_fn(self, run_id: str):
        """An ``fn(local_dir)`` that mirrors a worker-local run directory
        into this store's run path (reference: store.py sync_fn — the
        estimators' checkpoint/logs upload hook). Shipped to executors via
        cloudpickle like every worker fn, so it must close over plain data
        (paths, connection tuples), never live handles."""
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Pick a store from the path scheme (reference: store.py:99-110 —
        hdfs:// → HDFSStore, dbfs:/ or /dbfs → DBFSLocalStore, else
        LocalStore)."""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if DBFSLocalStore.matches_dbfs(prefix_path):
            return DBFSLocalStore(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem-backed store (reference: store.py:123-230 — the default
    for single-node and NFS setups)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)
        os.makedirs(self.prefix_path, exist_ok=True)

    def _sub(self, *parts: str) -> str:
        p = os.path.join(self.prefix_path, *parts)
        os.makedirs(os.path.dirname(p) if "." in os.path.basename(p)
                    else p, exist_ok=True)
        return p

    def get_train_data_path(self, idx=None) -> str:
        return self._sub("intermediate_train_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_val_data_path(self, idx=None) -> str:
        return self._sub("intermediate_val_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_test_data_path(self, idx=None) -> str:
        return self._sub("intermediate_test_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._sub("runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._sub("runs", run_id, "logs")

    def get_runs_path(self) -> str:
        return self._sub("runs")

    def get_run_path(self, run_id: str) -> str:
        return self._sub("runs", run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def fn(local_run_path: str) -> None:
            import shutil

            shutil.copytree(local_run_path, run_path, dirs_exist_ok=True)

        return fn


class DBFSLocalStore(LocalStore):
    """Databricks DBFS store (reference: store.py DBFSLocalStore) —
    ``dbfs:/...`` and ``file:///dbfs/...`` URIs map onto the FUSE mount at
    ``/dbfs``, after which everything is plain filesystem I/O."""

    def __init__(self, prefix_path: str):
        super().__init__(self.normalize_path(prefix_path))

    @staticmethod
    def matches_dbfs(path: str) -> bool:
        return (path.startswith("dbfs:/")
                or path.startswith("/dbfs/")
                or path.startswith("file:///dbfs/"))

    @staticmethod
    def normalize_path(path: str) -> str:
        """Rewrite any DBFS URI form to the FUSE path (reference:
        store.py DBFSLocalStore.normalize_datasets_path)."""
        if path.startswith("dbfs:///"):
            return "/dbfs/" + path[len("dbfs:///"):]
        if path.startswith("dbfs:/"):
            return "/dbfs/" + path[len("dbfs:/"):]
        if path.startswith("file:///dbfs/"):
            return path[len("file://"):]
        return path


class HDFSStore(Store):
    """HDFS-backed store (reference: store.py:233-480). Requires pyarrow's
    HadoopFileSystem; gated at construction."""

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None):
        try:
            from pyarrow import fs as pafs
        except ImportError as e:
            raise ImportError(
                "HDFSStore requires pyarrow with HDFS support") from e
        self.prefix_path = prefix_path
        self._conn = (host or "default", port or 0, user)
        self._fs = pafs.HadoopFileSystem(
            host=self._conn[0], port=self._conn[1], user=self._conn[2])

    # The pyarrow filesystem handle is not picklable; estimators ship the
    # Store to executors (reference store.py does the same dance via
    # __getstate__), so reconnect on unpickle.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_fs", None)
        return state

    def __setstate__(self, state):
        from pyarrow import fs as pafs

        self.__dict__.update(state)
        self._fs = pafs.HadoopFileSystem(
            host=self._conn[0], port=self._conn[1], user=self._conn[2])

    def _sub(self, *parts: str) -> str:
        base = self.prefix_path.rstrip("/")
        return "/".join([base, *parts])

    def get_train_data_path(self, idx=None) -> str:
        return self._sub("intermediate_train_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_val_data_path(self, idx=None) -> str:
        return self._sub("intermediate_val_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_test_data_path(self, idx=None) -> str:
        return self._sub("intermediate_test_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._sub("runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._sub("runs", run_id, "logs")

    def get_runs_path(self) -> str:
        return self._sub("runs")

    def get_run_path(self, run_id: str) -> str:
        return self._sub("runs", run_id)

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id).replace("hdfs://", "")
        conn = self._conn  # close over plain data: fn ships pickled

        def fn(local_run_path: str) -> None:
            import os as _os

            from pyarrow import fs as pafs

            hdfs = pafs.HadoopFileSystem(host=conn[0], port=conn[1],
                                         user=conn[2])
            for root, _, files in _os.walk(local_run_path):
                rel = _os.path.relpath(root, local_run_path)
                for name in files:
                    parts = [run_path] + \
                        ([] if rel == "." else rel.split(_os.sep)) + [name]
                    dst = "/".join(parts)
                    with open(_os.path.join(root, name), "rb") as src:
                        with hdfs.open_output_stream(dst) as out:
                            out.write(src.read())

        return fn

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs

        info = self._fs.get_file_info([path.replace("hdfs://", "")])[0]
        return info.type != pafs.FileType.NotFound

    def read(self, path: str) -> bytes:
        with self._fs.open_input_stream(path.replace("hdfs://", "")) as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        with self._fs.open_output_stream(path.replace("hdfs://", "")) as f:
            f.write(data)
