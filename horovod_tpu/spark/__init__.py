"""Spark integration (reference: horovod/spark/, SURVEY §2.4).

``horovod_tpu.spark.run(fn, ...)`` executes ``fn`` as a distributed
horovod_tpu job on a Spark cluster's executors.

TPU-native redesign: the reference predates Spark barrier scheduling and
hand-rolls driver/task RPC services plus an mpirun rsh bridge
(spark/runner.py:47-192). Spark ≥3 gives the same guarantees natively:
``run`` launches one **barrier stage** with ``num_proc`` tasks; tasks
exchange their controller endpoint via ``BarrierTaskContext.allGather``
(the role of the reference's task-to-driver registration), export the
standard ``HOROVOD_*`` env contract, and call ``fn`` — inside which
``hvd.init()`` joins the native control plane exactly as under the CLI
launcher. No ssh, no rsh agent, no separate rendezvous server.

pyspark is not bundled; every entry point raises a clear error without it,
while the task-side env construction stays importable and unit-testable.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, List, Optional

from .store import (  # noqa: F401
    DBFSLocalStore,
    HDFSStore,
    LocalStore,
    Store,
)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (>=3.0 for barrier "
            "scheduling); install pyspark or use horovod_tpu.runner / "
            "horovod_tpu.ray") from e


def build_task_env(rank: int, addresses: List[str],
                   controller_port: int,
                   base_env: Optional[dict] = None) -> dict:
    """The launcher env contract for one barrier task (reference:
    gloo_run.py:65-76 — HOROVOD_RANK/SIZE/LOCAL_RANK/... injected per
    slot). ``addresses`` is the rank-ordered list of task hostnames from
    ``allGather``; local/cross ranks derive from host grouping exactly like
    ``get_host_assignments`` (hosts.py:100-150)."""
    size = len(addresses)
    host = addresses[rank]
    local_rank = sum(1 for r in range(rank) if addresses[r] == host)
    local_size = sum(1 for a in addresses if a == host)
    unique_hosts = list(dict.fromkeys(addresses))
    cross_rank = unique_hosts.index(host)
    env = dict(base_env or {})
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(len(unique_hosts)),
        "HOROVOD_HOSTNAME": host,
        "HOROVOD_CONTROLLER_ADDR": addresses[0],
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
    })
    return env


def _barrier_task(fn, args, kwargs):
    """Runs inside each Spark barrier task."""
    import pickle

    from pyspark import BarrierTaskContext

    ctx = BarrierTaskContext.get()
    rank = ctx.partitionId()

    # Rank 0 picks the controller port; everyone learns everyone's address.
    from ..runner.network import find_free_port

    my_host = socket.gethostbyname(socket.gethostname())
    port = find_free_port() if rank == 0 else 0
    gathered = ctx.allGather(f"{my_host}:{port}")
    addresses = [g.rsplit(":", 1)[0] for g in gathered]
    controller_port = int(gathered[0].rsplit(":", 1)[1])

    env = build_task_env(rank, addresses, controller_port)
    os.environ.update(env)

    result = fn(*args, **kwargs)
    return [pickle.dumps((rank, result))]


def run(fn: Callable[..., Any],
        args: tuple = (),
        kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None,
        verbose: int = 0) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark executors as one horovod_tpu world
    (reference: horovod.spark.run, spark/runner.py:195-301). Returns the
    rank-ordered results."""
    import pickle

    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(1, int(sc.defaultParallelism))
    kwargs = kwargs or {}

    rdd = sc.parallelize(range(num_proc), num_proc)
    out = rdd.barrier().mapPartitions(
        lambda _: _barrier_task(fn, args, kwargs)).collect()
    by_rank = dict(pickle.loads(x) if isinstance(x, bytes) else x
                   for x in out)
    return [by_rank[r] for r in range(num_proc)]


def run_elastic(fn: Callable[..., Any],
                args: tuple = (),
                kwargs: Optional[dict] = None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                reset_limit: Optional[int] = None,
                verbose: int = 0) -> List[Any]:
    """Run ``fn`` elastically on Spark executors (reference:
    horovod.spark.run_elastic, spark/runner.py:303-417).

    Architecture (mirroring the reference's task-service design): a
    non-barrier stage of ``max_np`` long-lived tasks registers with a
    driver-side :class:`~horovod_tpu.spark.elastic.TaskDispatcher`; the
    :class:`~horovod_tpu.elastic.driver.ElasticDriver` discovers hosts from
    the live-task registry and execs workers *through* the tasks as
    subprocesses. Executor loss ages the host out of discovery and triggers
    the normal elastic reshuffle. ``fn`` must use ``hvd.elastic.run`` with
    committed state, exactly as in the reference.

    Returns the final world's rank-ordered ``fn`` results.
    """
    _require_pyspark()
    import threading

    from pyspark.sql import SparkSession

    from .elastic import run_elastic_core, task_loop

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(1, int(sc.defaultParallelism))
    n_tasks = max_np or num_proc

    def launch_tasks(fn_blob, addr, port, key):
        def _task(_):
            yield task_loop(addr, port, key, fn_blob)

        rdd = sc.parallelize(range(n_tasks), n_tasks)
        t = threading.Thread(
            target=lambda: rdd.mapPartitions(_task).collect(), daemon=True)
        t.start()
        return t

    return run_elastic_core(
        launch_tasks, fn, args=args, kwargs=kwargs, num_proc=num_proc,
        min_np=min_np, max_np=max_np, reset_limit=reset_limit)


from .estimator import KerasEstimator, TorchEstimator  # noqa: F401,E402
