"""Spark ML estimators (reference: horovod/spark/keras/estimator.py:105 and
spark/torch/estimator.py — fit(df) trains a distributed model on a Spark
DataFrame and returns a transformer holding the trained model).

TPU-native simplification: the reference materializes the DataFrame to
Parquet and feeds it back through Petastorm readers (spark/common/util.py).
Here each barrier task reads its own partition slice directly
(df → per-rank numpy via mapPartitions) — no Petastorm dependency, and the
feed path stays host-side numpy, which is what the TPU input pipeline
wants anyway. The estimator params mirror the reference's surface.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from .store import Store


class _EstimatorParams:
    """Shared param validation (reference: spark/common/params.py)."""

    def __init__(self, model=None, store: Optional[Store] = None,
                 feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: Optional[int] = None,
                 verbose: int = 1, run_id: Optional[str] = None,
                 loss=None, optimizer=None, validation=None,
                 validation_steps_per_epoch=None):
        if model is None:
            raise ValueError("model is required")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        if validation is not None and not isinstance(validation, str):
            validation = float(validation)
            if not 0.0 < validation < 1.0:
                raise ValueError(
                    f"validation fraction must be in (0, 1), got "
                    f"{validation}")
        if validation_steps_per_epoch is not None and \
                int(validation_steps_per_epoch) < 1:
            raise ValueError(
                f"validation_steps_per_epoch must be >= 1, got "
                f"{validation_steps_per_epoch}")
        self.model = model
        self.store = store
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.num_proc = num_proc
        self.verbose = verbose
        self.run_id = run_id or "run_1"
        self.loss = loss
        self.optimizer = optimizer
        # Reference: spark/keras/estimator.py:128-142 — a float is a
        # row fraction held out for validation; a string names a column
        # whose truthy rows are the validation set.
        self.validation = validation
        # Cap on validation batches evaluated per epoch (reference
        # keras/estimator.py:142); None = the full validation shard.
        self.validation_steps_per_epoch = validation_steps_per_epoch
        # Per-epoch metrics from the last fit(), rank-averaged
        # ({"loss": [...], "val_loss": [...]}).
        self.history_ = None


class _ModelTransformer:
    """Minimal Spark-ML-style transformer returned by fit() (reference:
    keras/estimator.py KerasModel / torch/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols: List[str],
                 label_cols: List[str], predict_fn: Callable):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self._predict_fn = predict_fn

    def _predict_pdf(self, pdf):
        import numpy as np

        feats = np.asarray(pdf[self.feature_cols].values, dtype="float32")
        preds = self._predict_fn(self.model, feats)
        pdf = pdf.copy()
        pdf["prediction"] = list(np.asarray(preds).reshape(len(pdf), -1))
        return pdf

    def transform(self, df):
        """Append a prediction column to ``df``.

        Spark DataFrames predict DISTRIBUTED via ``mapInPandas`` (the
        reference's pandas-UDF contract, spark/keras/estimator.py) — the
        driver never collects the dataset, so inference scales past driver
        memory. Plain pandas/lists fall through to a local batch predict.
        """
        if hasattr(df, "mapInPandas"):
            model_t = self

            def _predict_iter(batches):
                for pdf in batches:
                    yield model_t._predict_pdf(pdf)

            return df.mapInPandas(_predict_iter, self._output_schema(df))
        import pandas as pd

        return self._predict_pdf(pd.DataFrame(df))

    @staticmethod
    def _output_schema(df):
        """Input schema + an array<float> prediction column (pyspark
        types when available; the raw schema object otherwise, for
        pyspark-free test doubles)."""
        schema = getattr(df, "schema", None)
        try:
            from pyspark.sql.types import (ArrayType, FloatType,
                                           StructField, StructType)

            # Fresh StructType: StructType.add mutates (and returns) self,
            # and df.schema is cached — extending it in place would poison
            # the input DataFrame's schema with a phantom column.
            return StructType(list(schema.fields) + [
                StructField("prediction", ArrayType(FloatType()))])
        except ImportError:
            return schema


def _val_selector(validation):
    """(partition_row_index, row) -> True for validation rows (reference:
    spark/keras/estimator.py:128-142). A column name selects truthy
    rows; a fraction selects a deterministic interleaved subset whose
    density matches the fraction EXACTLY for any value in (0, 1) —
    row ``i`` is validation iff the running count ``floor((i+1)*f)``
    advances at ``i`` — so every rank's shard gets a proportional
    validation slice without a shuffle."""
    if validation is None:
        return lambda i, r: False
    if isinstance(validation, str):
        return lambda i, r: bool(r[validation])
    f = float(validation)
    return lambda i, r: int((i + 1) * f) - int(i * f) >= 1


def _collect_partition_numpy(df, feature_cols, label_cols, num_proc,
                             validation=None):
    """df → list of (features, labels, val_features, val_labels) numpy
    shards, one per rank, collected on the driver. Only used when no
    Store is configured (small-data convenience path); with a Store the
    scalable :func:`_materialize_shards` path is used instead."""
    import numpy as np

    cols = list(feature_cols) + list(label_cols)
    if isinstance(validation, str):
        cols.append(validation)
    rows = df.select(*cols).collect()
    is_val = _val_selector(validation)
    tr = [r for i, r in enumerate(rows) if not is_val(i, r)]
    va = [r for i, r in enumerate(rows) if is_val(i, r)]

    def to_np(rs, cs):
        return np.asarray([[r[c] for c in cs] for r in rs],
                          dtype="float32").reshape(len(rs), len(cs))

    shards = []
    per = max(1, len(tr) // num_proc)
    vper = max(1, len(va) // num_proc) if va else 0
    for i in range(num_proc):
        hi = len(tr) if i == num_proc - 1 else (i + 1) * per
        vhi = len(va) if i == num_proc - 1 else (i + 1) * vper
        t = tr[i * per:hi]
        v = va[i * vper:vhi] if va else []
        shards.append((to_np(t, feature_cols), to_np(t, label_cols),
                       to_np(v, feature_cols), to_np(v, label_cols)))
    return shards


# Rows per materialized chunk file: bounds worker memory — training streams
# one chunk at a time, so datasets larger than worker RAM train fine
# (reference: the Petastorm reader's row-group streaming,
# spark/common/util.py). Overridable for tests and small-RAM workers.
DEFAULT_CHUNK_ROWS = 65536


def _chunk_rows() -> int:
    import os

    return int(os.environ.get("HOROVOD_SPARK_CHUNK_ROWS",
                              DEFAULT_CHUNK_ROWS))


def _materialize_shards(df, feature_cols, label_cols, num_proc, store,
                        run_id, chunk_rows=None, validation=None):
    """Materialize ``df`` to ``num_proc`` per-rank shard directories *on
    the executors* (reference: spark/common/util.py prepare_data —
    DataFrame → Parquet → Petastorm readers). The driver never collects
    the dataset (round-1 verdict #5), and each shard is CHUNKED
    (``shard_i/chunk_XXXXX.npz`` + ``meta.json``) so workers stream it per
    epoch instead of loading the whole shard (round-2 missing #5: the
    whole-``.npz`` load capped dataset size at worker RAM). With
    ``validation`` set, each partition's validation rows stream to
    sibling ``val_chunk_XXXXX.npz`` files (reference
    keras/estimator.py:128-142 validation split).

    Returns ``(data_dir, rows_per_shard)``.
    """
    fcols, lcols = list(feature_cols), list(label_cols)
    data_dir = f"{store.get_train_data_path()}/{run_id}"
    chunk_rows = chunk_rows or _chunk_rows()
    is_val = _val_selector(validation)

    def _write(idx, rows):
        import io as _io
        import json as _json

        import numpy as _np

        def _flush(feats, labels, k, prefix):
            buf = _io.BytesIO()
            _np.savez(
                buf,
                features=_np.asarray(feats, "float32").reshape(
                    len(feats), len(fcols)),
                labels=_np.asarray(labels, "float32").reshape(
                    len(labels), len(lcols)))
            store.write(
                f"{data_dir}/shard_{idx}/{prefix}chunk_{k:05d}.npz",
                buf.getvalue())
            return len(feats)

        bufs = {"": ([], [], []), "val_": ([], [], [])}
        for i, r in enumerate(rows):
            prefix = "val_" if is_val(i, r) else ""
            feats, labels, sizes = bufs[prefix]
            feats.append([float(r[c]) for c in fcols])
            labels.append([float(r[c]) for c in lcols])
            if len(feats) >= chunk_rows:
                sizes.append(_flush(feats, labels, len(sizes), prefix))
                feats.clear()
                labels.clear()
        for prefix, (feats, labels, sizes) in bufs.items():
            if prefix == "val_" and validation is None:
                continue  # no val files at all without a split
            if feats or not sizes:  # empty split still gets chunk 0
                sizes.append(_flush(feats, labels, len(sizes), prefix))
        train_sizes = bufs[""][2]
        val_sizes = bufs["val_"][2]
        store.write(f"{data_dir}/shard_{idx}/meta.json", _json.dumps({
            "rows": sum(train_sizes), "chunk_sizes": train_sizes,
            "val_rows": sum(val_sizes), "val_chunk_sizes": val_sizes,
            "n_features": len(fcols), "n_labels": len(lcols),
        }).encode())
        yield (idx, sum(train_sizes))

    cols = fcols + lcols
    if isinstance(validation, str):
        cols = cols + [validation]
    rdd = df.select(*cols).repartition(num_proc).rdd
    counts = dict(rdd.mapPartitionsWithIndex(_write).collect())
    return data_dir, [counts.get(i, 0) for i in range(num_proc)]


class ShardReader:
    """Streaming per-epoch reader over one rank's chunked shard (the
    worker-side half of :func:`_materialize_shards`; reference analogue:
    the per-epoch Petastorm reader loop in spark/keras/remote.py +
    torch/remote.py). Holds at most one chunk in memory.

    ``max_resident_rows`` records the high-water mark of rows held, so
    tests can assert the memory bound."""

    def __init__(self, store, data_dir: str, rank: int,
                 split: str = "train"):
        import json as _json

        if split not in ("train", "val"):
            raise ValueError(f"split must be train|val, got {split!r}")
        self._store = store
        self._dir = f"{data_dir}/shard_{rank}"
        self._prefix = "" if split == "train" else "val_"
        meta = _json.loads(store.read(f"{self._dir}/meta.json"))
        rows_key = "rows" if split == "train" else "val_rows"
        sizes_key = ("chunk_sizes" if split == "train"
                     else "val_chunk_sizes")
        self.rows = int(meta.get(rows_key, 0))
        self.chunk_sizes = list(meta.get(sizes_key, []))
        self.max_resident_rows = 0

    def _load_chunk(self, k: int):
        import io as _io

        import numpy as _np

        with _np.load(_io.BytesIO(self._store.read(
                f"{self._dir}/{self._prefix}chunk_{k:05d}.npz"))) as z:
            x, y = z["features"], z["labels"]
        self.max_resident_rows = max(self.max_resident_rows, len(x))
        return x, y

    def iter_chunks(self):
        for k in range(len(self.chunk_sizes)):
            yield self._load_chunk(k)

    def iter_batches(self, batch_size: int):
        """One epoch of (x, y) batches; batches never span chunks (same
        tail-batch semantics as the reference's reader with
        rows-per-worker sharding)."""
        for x, y in self.iter_chunks():
            for i in range(0, len(x), batch_size):
                yield x[i:i + batch_size], y[i:i + batch_size]

    def steps_per_epoch(self, batch_size: int) -> int:
        return sum((s + batch_size - 1) // batch_size
                   for s in self.chunk_sizes if s)


def _load_shard(store, data_dir, rank):
    """Whole-shard convenience load (concatenates the chunks; prefer
    :class:`ShardReader` for anything big)."""
    import numpy as _np

    reader = ShardReader(store, data_dir, rank)
    xs, ys = zip(*reader.iter_chunks())
    return _np.concatenate(xs), _np.concatenate(ys)


def _prepare_data(df, params):
    """Pick the data path: Store-backed executor-side materialization when a
    Store is configured, driver-side collect otherwise. Returns
    ``(shards, store, data_dir)`` where exactly one of shards/data_dir is
    set."""
    num_proc = params.num_proc or 2
    if params.store is not None:
        data_dir, _ = _materialize_shards(
            df, params.feature_cols, params.label_cols, num_proc,
            params.store, params.run_id, validation=params.validation)
        return None, params.store, data_dir
    return _collect_partition_numpy(
        df, params.feature_cols, params.label_cols, num_proc,
        validation=params.validation), None, None


class KerasEstimator(_EstimatorParams):
    """Keras estimator (reference: spark/keras/estimator.py:105-544).

    ``fit(df)`` runs a barrier-stage horovod_tpu job: every rank trains the
    Keras model on its shard with the distributed optimizer + broadcast
    callbacks; rank 0's weights come back in the returned transformer.
    """

    def fit(self, df) -> _ModelTransformer:
        from . import run as spark_run

        num_proc = self.num_proc or 2
        shards, store, data_dir = _prepare_data(df, self)
        model_bytes = _serialize_keras(self.model)
        loss = self.loss or "mse"
        lr_opt = self.optimizer
        batch_size, epochs = self.batch_size, self.epochs
        has_val = self.validation is not None
        val_steps_cap = self.validation_steps_per_epoch

        def _train():
            import numpy as np

            import horovod_tpu.keras as hvd

            hvd.init()
            model = _deserialize_keras(model_bytes)
            import keras

            opt = lr_opt or keras.optimizers.Adam()
            model.compile(optimizer=hvd.DistributedOptimizer(opt),
                          loss=loss)
            callbacks = [
                hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                # Averages every epoch metric across ranks — incl.
                # val_loss, so rank 0's history is the GLOBAL
                # validation metric (reference: remote.py metric
                # aggregation).
                hvd.callbacks.MetricAverageCallback(),
            ]
            fit_kw = dict(epochs=epochs, verbose=0, callbacks=callbacks)
            if data_dir is not None:
                # Stream the chunked shard: one chunk resident at a time
                # (reference: the per-epoch Petastorm reader loop in
                # spark/keras/remote.py).
                reader = ShardReader(store, data_dir, hvd.rank())
                if reader.rows == 0:
                    # An empty shard must fail loudly: the infinite batch
                    # generator would otherwise spin without ever
                    # yielding, hanging the whole barrier job — and a
                    # rank running fewer optimizer steps deadlocks the
                    # per-batch gradient allreduce anyway.
                    raise ValueError(
                        f"rank {hvd.rank()} received an empty data "
                        f"shard; provide at least num_proc rows (or "
                        f"lower num_proc)")
                if has_val:
                    vreader = ShardReader(store, data_dir, hvd.rank(),
                                          split="val")
                    if vreader.rows == 0:
                        # Every rank must emit val metrics or the
                        # metric-average collective's key sets diverge.
                        raise ValueError(
                            f"rank {hvd.rank()} received an empty "
                            f"VALIDATION shard; provide more rows or a "
                            f"larger validation fraction")

                    vsteps = vreader.steps_per_epoch(batch_size)
                    if val_steps_cap is not None:
                        vsteps = min(vsteps, int(val_steps_cap))

                    def _vgen():
                        # Restart the shard every vsteps batches so each
                        # epoch evaluates the SAME leading subset (the
                        # Torch path's semantics — capped epochs must
                        # not drift through the shard).
                        while True:
                            count = 0
                            for b in vreader.iter_batches(batch_size):
                                if count >= vsteps:
                                    break
                                count += 1
                                yield b

                    fit_kw.update(validation_data=_vgen(),
                                  validation_steps=vsteps)

                def _gen():
                    while True:
                        yield from reader.iter_batches(batch_size)

                hist = model.fit(
                    _gen(),
                    steps_per_epoch=reader.steps_per_epoch(batch_size),
                    **fit_kw)
            else:
                x, y, xv, yv = shards[hvd.rank()]
                if has_val:
                    if len(xv) == 0:
                        raise ValueError(
                            f"rank {hvd.rank()} received an empty "
                            f"VALIDATION shard; provide more rows or a "
                            f"larger validation fraction")
                    fit_kw["validation_data"] = (xv, yv)
                hist = model.fit(x, y, batch_size=batch_size, **fit_kw)
            return ([np.asarray(w) for w in model.get_weights()],
                    {k: [float(v) for v in vs]
                     for k, vs in hist.history.items()})

        results = spark_run(_train, num_proc=num_proc)
        weights, self.history_ = results[0]
        self.model.set_weights(weights)
        if self.store is not None:
            ckpt = self.store.get_checkpoint_path(self.run_id)
            self.store.write(ckpt + "/model.keras",
                             _serialize_keras(self.model))
        return _ModelTransformer(
            self.model, self.feature_cols, self.label_cols,
            lambda m, f: m.predict(f, verbose=0))


class TorchEstimator(_EstimatorParams):
    """Torch estimator (reference: spark/torch/estimator.py:450)."""

    def fit(self, df) -> _ModelTransformer:
        import io

        import torch

        from . import run as spark_run

        num_proc = self.num_proc or 2
        shards, store, data_dir = _prepare_data(df, self)
        buf = io.BytesIO()
        torch.save(self.model, buf)
        model_bytes = buf.getvalue()
        loss_fn = self.loss or torch.nn.functional.mse_loss
        batch_size, epochs = self.batch_size, self.epochs
        opt_factory = self.optimizer or (
            lambda params: torch.optim.Adam(params))
        has_val = self.validation is not None
        val_steps_cap = self.validation_steps_per_epoch

        def _train():
            import io as _io

            import torch as T

            import horovod_tpu.torch as hvd

            hvd.init()
            model = T.load(_io.BytesIO(model_bytes), weights_only=False)
            opt = opt_factory(model.parameters())
            opt = hvd.DistributedOptimizer(
                opt, named_parameters=model.named_parameters())
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)

            def _step(xb, yb):
                opt.zero_grad()
                out = model(T.from_numpy(xb))
                loss = loss_fn(out, T.from_numpy(yb))
                loss.backward()
                opt.step()
                return float(loss.detach()), len(xb)

            def _rank_avg(local):
                """Rank-average a scalar metric — the same global
                metric MetricAverageCallback produces on the Keras
                path (applied to BOTH loss series so history_ is
                uniformly rank-averaged)."""
                return float(hvd.allreduce(T.tensor([float(local)]),
                                           average=True)[0])

            def _row_mean(pairs):
                """Sample-weighted mean of (batch_mean, batch_rows) —
                partial tail batches must not skew the metric (Keras
                weights by sample count the same way)."""
                total, n = 0.0, 0
                for mean, rows in pairs:
                    total += mean * rows
                    n += rows
                return total / max(n, 1)

            def _val_loss(batches):
                if val_steps_cap is not None:
                    import itertools

                    batches = itertools.islice(batches,
                                               int(val_steps_cap))
                model.eval()  # freeze dropout/BN: no val-data leakage
                try:
                    with T.no_grad():
                        pairs = [
                            (float(loss_fn(model(T.from_numpy(xb)),
                                           T.from_numpy(yb))), len(xb))
                            for xb, yb in batches]
                finally:
                    model.train()
                return _rank_avg(_row_mean(pairs))

            history = {"loss": []}
            if has_val:
                history["val_loss"] = []
            if data_dir is not None:
                # Stream the chunked shard per epoch (reference:
                # spark/torch/remote.py reader loop).
                reader = ShardReader(store, data_dir, hvd.rank())
                vreader = ShardReader(store, data_dir, hvd.rank(),
                                      split="val") if has_val else None
                if has_val and vreader.rows == 0:
                    raise ValueError(
                        f"rank {hvd.rank()} received an empty "
                        f"VALIDATION shard; provide more rows or a "
                        f"larger validation fraction")
                for _ in range(epochs):
                    ep = [_step(xb, yb)
                          for xb, yb in reader.iter_batches(batch_size)]
                    history["loss"].append(_rank_avg(_row_mean(ep)))
                    if has_val:
                        history["val_loss"].append(
                            _val_loss(vreader.iter_batches(batch_size)))
            else:
                x, y, xv, yv = shards[hvd.rank()]
                if has_val and len(xv) == 0:
                    raise ValueError(
                        f"rank {hvd.rank()} received an empty "
                        f"VALIDATION shard; provide more rows or a "
                        f"larger validation fraction")
                for _ in range(epochs):
                    ep = [_step(x[i:i + batch_size], y[i:i + batch_size])
                          for i in range(0, len(x), batch_size)]
                    history["loss"].append(_rank_avg(_row_mean(ep)))
                    if has_val:
                        history["val_loss"].append(_val_loss(
                            (xv[i:i + batch_size], yv[i:i + batch_size])
                            for i in range(0, len(xv), batch_size)))
            return ({k: v.numpy() for k, v in model.state_dict().items()},
                    history)

        results = spark_run(_train, num_proc=num_proc)
        import torch as T

        state, self.history_ = results[0]
        self.model.load_state_dict(
            {k: T.from_numpy(v) for k, v in state.items()})
        return _ModelTransformer(
            self.model, self.feature_cols, self.label_cols,
            lambda m, f: m(__import__("torch").from_numpy(f))
            .detach().numpy())


def _serialize_keras(model) -> bytes:
    import io

    import keras

    buf = io.BytesIO()
    try:
        keras.saving.save_model(model, buf, save_format="keras")
        return buf.getvalue()
    except (TypeError, ValueError):
        # Keras 3 rejects save_format / non-path targets: use a temp
        # .keras file path instead.
        import os
        import tempfile

        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            model.save(path)
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)


def _deserialize_keras(data: bytes):
    import io
    import os
    import tempfile

    import keras

    try:
        return keras.saving.load_model(io.BytesIO(data))
    except (TypeError, ValueError):
        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            with open(path, "wb") as f:
                f.write(data)
            return keras.saving.load_model(path)
        finally:
            os.unlink(path)
