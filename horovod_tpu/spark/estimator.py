"""Spark ML estimators (reference: horovod/spark/keras/estimator.py:105 and
spark/torch/estimator.py — fit(df) trains a distributed model on a Spark
DataFrame and returns a transformer holding the trained model).

TPU-native simplification: the reference materializes the DataFrame to
Parquet and feeds it back through Petastorm readers (spark/common/util.py).
Here each barrier task reads its own partition slice directly
(df → per-rank numpy via mapPartitions) — no Petastorm dependency, and the
feed path stays host-side numpy, which is what the TPU input pipeline
wants anyway. The estimator params mirror the reference's surface.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from .store import Store


class _EstimatorParams:
    """Shared param validation (reference: spark/common/params.py)."""

    def __init__(self, model=None, store: Optional[Store] = None,
                 feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: Optional[int] = None,
                 verbose: int = 1, run_id: Optional[str] = None,
                 loss=None, optimizer=None):
        if model is None:
            raise ValueError("model is required")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        self.model = model
        self.store = store
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.num_proc = num_proc
        self.verbose = verbose
        self.run_id = run_id or "run_1"
        self.loss = loss
        self.optimizer = optimizer


class _ModelTransformer:
    """Minimal Spark-ML-style transformer returned by fit() (reference:
    keras/estimator.py KerasModel / torch/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols: List[str],
                 label_cols: List[str], predict_fn: Callable):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self._predict_fn = predict_fn

    def transform(self, df):
        """Append prediction columns to ``df`` (driver-side batch predict;
        the reference uses a pandas UDF — same contract)."""
        import numpy as np
        import pandas as pd

        pdf = df.toPandas() if hasattr(df, "toPandas") else pd.DataFrame(df)
        feats = np.asarray(pdf[self.feature_cols].values, dtype="float32")
        preds = self._predict_fn(self.model, feats)
        pdf = pdf.copy()
        pdf["prediction"] = list(np.asarray(preds).reshape(len(pdf), -1))
        return pdf


def _collect_partition_numpy(df, feature_cols, label_cols, num_proc):
    """df → list of (features, labels) numpy shards, one per rank, collected
    on the driver. Only used when no Store is configured (small-data
    convenience path); with a Store the scalable
    :func:`_materialize_shards` path is used instead."""
    import numpy as np

    rows = df.select(*feature_cols, *label_cols).collect()
    feats = np.asarray([[r[c] for c in feature_cols] for r in rows],
                       dtype="float32")
    labels = np.asarray([[r[c] for c in label_cols] for r in rows],
                        dtype="float32")
    shards = []
    per = max(1, len(rows) // num_proc)
    for i in range(num_proc):
        lo = i * per
        hi = len(rows) if i == num_proc - 1 else (i + 1) * per
        shards.append((feats[lo:hi], labels[lo:hi]))
    return shards


def _materialize_shards(df, feature_cols, label_cols, num_proc, store,
                        run_id):
    """Materialize ``df`` to ``num_proc`` per-rank shard files *on the
    executors* (reference: spark/common/util.py prepare_data — DataFrame →
    Parquet → Petastorm readers). The driver never collects the dataset
    (round-1 verdict #5): each repartitioned partition is converted to
    numpy where it lives and written to the shared Store
    (LocalStore = single-node/NFS, HDFSStore = cluster — the same contract
    as the reference's store.py:30-480).

    Returns ``(data_dir, rows_per_shard)``.
    """
    fcols, lcols = list(feature_cols), list(label_cols)
    data_dir = f"{store.get_train_data_path()}/{run_id}"

    def _write(idx, rows):
        import io as _io

        import numpy as _np

        feats, labels = [], []
        for r in rows:
            feats.append([float(r[c]) for c in fcols])
            labels.append([float(r[c]) for c in lcols])
        buf = _io.BytesIO()
        _np.savez(
            buf,
            features=_np.asarray(feats, "float32").reshape(
                len(feats), len(fcols)),
            labels=_np.asarray(labels, "float32").reshape(
                len(labels), len(lcols)))
        store.write(f"{data_dir}/shard_{idx}.npz", buf.getvalue())
        yield (idx, len(feats))

    rdd = df.select(*fcols, *lcols).repartition(num_proc).rdd
    counts = dict(rdd.mapPartitionsWithIndex(_write).collect())
    return data_dir, [counts.get(i, 0) for i in range(num_proc)]


def _load_shard(store, data_dir, rank):
    """Read one rank's materialized shard back as numpy (the worker-side
    half of :func:`_materialize_shards`; reference: the per-epoch Petastorm
    reader in keras/remote.py / torch/remote.py)."""
    import io as _io

    import numpy as _np

    with _np.load(_io.BytesIO(
            store.read(f"{data_dir}/shard_{rank}.npz"))) as z:
        return z["features"], z["labels"]


def _prepare_data(df, params):
    """Pick the data path: Store-backed executor-side materialization when a
    Store is configured, driver-side collect otherwise. Returns
    ``(shards, store, data_dir)`` where exactly one of shards/data_dir is
    set."""
    num_proc = params.num_proc or 2
    if params.store is not None:
        data_dir, _ = _materialize_shards(
            df, params.feature_cols, params.label_cols, num_proc,
            params.store, params.run_id)
        return None, params.store, data_dir
    return _collect_partition_numpy(df, params.feature_cols,
                                    params.label_cols, num_proc), None, None


class KerasEstimator(_EstimatorParams):
    """Keras estimator (reference: spark/keras/estimator.py:105-544).

    ``fit(df)`` runs a barrier-stage horovod_tpu job: every rank trains the
    Keras model on its shard with the distributed optimizer + broadcast
    callbacks; rank 0's weights come back in the returned transformer.
    """

    def fit(self, df) -> _ModelTransformer:
        from . import run as spark_run

        num_proc = self.num_proc or 2
        shards, store, data_dir = _prepare_data(df, self)
        model_bytes = _serialize_keras(self.model)
        loss = self.loss or "mse"
        lr_opt = self.optimizer
        batch_size, epochs = self.batch_size, self.epochs

        def _train():
            import numpy as np

            import horovod_tpu.keras as hvd

            hvd.init()
            model = _deserialize_keras(model_bytes)
            import keras

            opt = lr_opt or keras.optimizers.Adam()
            model.compile(optimizer=hvd.DistributedOptimizer(opt),
                          loss=loss)
            if data_dir is not None:
                x, y = _load_shard(store, data_dir, hvd.rank())
            else:
                x, y = shards[hvd.rank()]
            model.fit(x, y, batch_size=batch_size, epochs=epochs,
                      verbose=0, callbacks=[
                          hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                          hvd.callbacks.MetricAverageCallback(),
                      ])
            return [np.asarray(w) for w in model.get_weights()]

        results = spark_run(_train, num_proc=num_proc)
        self.model.set_weights(results[0])
        if self.store is not None:
            ckpt = self.store.get_checkpoint_path(self.run_id)
            self.store.write(ckpt + "/model.keras",
                             _serialize_keras(self.model))
        return _ModelTransformer(
            self.model, self.feature_cols, self.label_cols,
            lambda m, f: m.predict(f, verbose=0))


class TorchEstimator(_EstimatorParams):
    """Torch estimator (reference: spark/torch/estimator.py:450)."""

    def fit(self, df) -> _ModelTransformer:
        import io

        import torch

        from . import run as spark_run

        num_proc = self.num_proc or 2
        shards, store, data_dir = _prepare_data(df, self)
        buf = io.BytesIO()
        torch.save(self.model, buf)
        model_bytes = buf.getvalue()
        loss_fn = self.loss or torch.nn.functional.mse_loss
        batch_size, epochs = self.batch_size, self.epochs
        opt_factory = self.optimizer or (
            lambda params: torch.optim.Adam(params))

        def _train():
            import io as _io

            import torch as T

            import horovod_tpu.torch as hvd

            hvd.init()
            model = T.load(_io.BytesIO(model_bytes), weights_only=False)
            opt = opt_factory(model.parameters())
            opt = hvd.DistributedOptimizer(
                opt, named_parameters=model.named_parameters())
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)
            if data_dir is not None:
                x, y = _load_shard(store, data_dir, hvd.rank())
            else:
                x, y = shards[hvd.rank()]
            xt, yt = T.from_numpy(x), T.from_numpy(y)
            for _ in range(epochs):
                for i in range(0, len(xt), batch_size):
                    opt.zero_grad()
                    out = model(xt[i:i + batch_size])
                    loss = loss_fn(out, yt[i:i + batch_size])
                    loss.backward()
                    opt.step()
            return {k: v.numpy() for k, v in model.state_dict().items()}

        results = spark_run(_train, num_proc=num_proc)
        import torch as T

        self.model.load_state_dict(
            {k: T.from_numpy(v) for k, v in results[0].items()})
        return _ModelTransformer(
            self.model, self.feature_cols, self.label_cols,
            lambda m, f: m(__import__("torch").from_numpy(f))
            .detach().numpy())


def _serialize_keras(model) -> bytes:
    import io

    import keras

    buf = io.BytesIO()
    try:
        keras.saving.save_model(model, buf, save_format="keras")
        return buf.getvalue()
    except TypeError:
        # Older keras: save to a temp file path
        import os
        import tempfile

        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            model.save(path)
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)


def _deserialize_keras(data: bytes):
    import io
    import os
    import tempfile

    import keras

    try:
        return keras.saving.load_model(io.BytesIO(data))
    except TypeError:
        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            with open(path, "wb") as f:
                f.write(data)
            return keras.saving.load_model(path)
        finally:
            os.unlink(path)
