"""Gradient compression for torch tensors.

Reference: ``horovod/torch/compression.py`` — fp16 cast before allreduce,
cast back after. On TPU-adjacent hosts bf16 is the natural wire format (same
exponent range as fp32, native MXU dtype), so a ``bf16`` compressor is added
beyond the reference's fp16.
"""

import torch


class Compressor:
    """Interface: compress(tensor) -> (tensor, ctx); decompress(tensor, ctx)
    (reference: compression.py:23-34)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py:37-47)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: torch.dtype = torch.float16

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class FP16Compressor(_CastCompressor):
    """Reference: compression.py:50-69."""
    wire_dtype = torch.float16


class BF16Compressor(_CastCompressor):
    """TPU-native wire format (no reference analogue; bf16 keeps fp32's
    exponent range so gradient overflow handling is unnecessary)."""
    wire_dtype = torch.bfloat16


class Compression:
    """Namespace mirroring the reference (compression.py:72-78)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
