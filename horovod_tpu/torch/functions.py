"""State synchronization helpers for PyTorch.

Reference: ``horovod/torch/functions.py`` — ``broadcast_parameters``
(functions.py:30-68), ``broadcast_optimizer_state`` (functions.py:70-160),
``broadcast_object`` / ``allgather_object`` via cloudpickle→byte tensor.
"""

from __future__ import annotations

import io
from typing import Any, List

import torch

from . import mpi_ops
from .mpi_ops import synchronize, broadcast_async_


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast model parameters from ``root_rank`` to every rank, in
    place. Accepts a ``state_dict`` or an iterable of ``(name, tensor)``
    (reference: functions.py:30-68)."""
    if isinstance(params, dict):
        params = sorted(params.items())
    elif isinstance(params, list):
        params = sorted(params)
    else:
        params = sorted(list(params))

    handles = []
    for name, p in params:
        if p is None:
            continue
        if not torch.is_tensor(p):
            raise ValueError(f"invalid param type {type(p)} for {name}")
        handles.append(broadcast_async_(p, root_rank, name=f"bp.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast an optimizer's full state from ``root_rank`` (reference:
    functions.py:70-160 — tensors broadcast in place, non-tensor scalars
    shipped as pickled objects so freshly-constructed optimizers on other
    ranks match the root exactly)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    # Newly constructed optimizers have empty state: run a dummy step on
    # zero grads first so every rank has state entries to receive into
    # (the reference's trick, functions.py:86-107).
    if not state_dict["state"]:
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = p.data.new_zeros(p.shape)
        optimizer.step()
        state_dict = optimizer.state_dict()

    params = []
    scalars = {}
    for pid, pstate in state_dict["state"].items():
        for key, value in pstate.items():
            name = f"os.{pid}.{key}"
            if torch.is_tensor(value):
                params.append((name, value))
            else:
                scalars[name] = value
    for gi, group in enumerate(state_dict["param_groups"]):
        for key, value in group.items():
            if key == "params":
                continue
            scalars[f"og.{gi}.{key}"] = value

    broadcast_parameters(params, root_rank)
    scalars = broadcast_object(scalars, root_rank, name="opt_scalars")

    for pid, pstate in state_dict["state"].items():
        for key in list(pstate.keys()):
            name = f"os.{pid}.{key}"
            if name in scalars:
                pstate[key] = scalars[name]
    for gi, group in enumerate(state_dict["param_groups"]):
        for key in list(group.keys()):
            name = f"og.{gi}.{key}"
            if name in scalars:
                group[key] = scalars[name]
    optimizer.load_state_dict(state_dict)


def _torch_dumps(obj: Any) -> bytes:
    buf = io.BytesIO()
    torch.save(obj, buf)
    return buf.getvalue()


def _torch_loads(data: bytes) -> Any:
    return torch.load(io.BytesIO(data), weights_only=False)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None) -> Any:
    """torch.save ``obj`` on the root and broadcast it (reference:
    functions.py:122-160 — size broadcast first, then the payload; framing
    shared with the other host bindings via common/object_transport.py)."""
    from ..common.object_transport import broadcast_bytes

    name = name or "broadcast_object"
    if mpi_ops._world() == 1:
        return obj
    data = _torch_dumps(obj) if mpi_ops.rank() == root_rank else None
    return _torch_loads(broadcast_bytes(data, root_rank, name))


def allgather_object(obj: Any, name: str = None) -> List[Any]:
    """Gather a picklable object from every rank (reference:
    tensorflow/functions.py:136-177; torch parity added in v0.21)."""
    from ..common.object_transport import allgather_bytes

    name = name or "allgather_object"
    if mpi_ops._world() == 1:
        return [obj]
    return [_torch_loads(b) for b in
            allgather_bytes(_torch_dumps(obj), name)]
