"""PyTorch binding for horovod_tpu.

Reference surface: ``horovod/torch/__init__.py`` — init/rank/size queries,
handle-based collective ops, DistributedOptimizer with backward hooks,
broadcast_parameters/broadcast_optimizer_state, Compression, SyncBatchNorm,
elastic TorchState/ElasticSampler.

Torch here is a host-side framework: its tensors ride the same native C++
controller + TCP data plane (horovod_tpu/cc/) as the eager JAX API, so torch
processes participate in the same world as JAX training processes.

Usage (the reference's README recipe)::

    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    model = ...
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size())
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    for batch in loader:
        optimizer.zero_grad()
        loss = model(batch).loss
        loss.backward()
        optimizer.step()
"""

from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    shutdown,
)
from ..common import basics as _basics


def rank() -> int:
    """Global rank of this process (reference: torch → horovod_rank)."""
    return int(_basics.rank())


def size() -> int:
    """World size (reference: torch → horovod_size)."""
    return int(_basics.size())


from .compression import Compression  # noqa: F401,E402
from .functions import (  # noqa: F401,E402
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from .mpi_ops import (  # noqa: F401,E402
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    join,
    poll,
    synchronize,
)
from .optimizer import DistributedOptimizer  # noqa: F401,E402
from .sync_batch_norm import SyncBatchNorm  # noqa: F401,E402
from . import elastic  # noqa: F401,E402
