"""DistributedOptimizer for PyTorch.

Reference: ``horovod/torch/optimizer.py`` — dynamically subclasses the
wrapped optimizer; registers per-parameter gradient-accumulation hooks that
fire ``allreduce_async_`` as gradients become ready during ``backward()``;
``step()`` synchronizes all outstanding handles before applying updates
(optimizer.py:103-200).
"""

from __future__ import annotations

import contextlib
import os

import torch

from ..common.exceptions import NotInitializedError
from .compression import Compression
from . import mpi_ops
from .mpi_ops import Average, Adasum, Sum


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin body copied onto a dynamic subclass of the user's optimizer
    class (reference: optimizer.py:29-101 __init__ structure)."""

    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1,
                 op=Average,
                 gradient_predivide_factor=1.0):
        super(self.__class__, self).__init__(params)

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [(f"allreduce.noname.{i}.{j}", v)
                                for i, group in enumerate(self.param_groups)
                                for j, v in enumerate(group["params"])]
        # Guard against duplicate and missing names (reference:
        # optimizer.py:47-62): an unnamed parameter would fall back to an
        # arrival-order auto name, which silently mismatches tensors across
        # ranks if hook firing order ever differs.
        all_params = {id(v) for group in self.param_groups
                      for v in group["params"]}
        named = {id(v) for _, v in named_parameters}
        if len(named_parameters) != len(named):
            raise ValueError("named_parameters contains duplicate parameters")
        unnamed = all_params - named
        if unnamed:
            raise ValueError(
                f"named_parameters is missing {len(unnamed)} parameter(s) "
                "managed by the optimizer; pass model.named_parameters() "
                "covering every optimized parameter")

        self._parameter_names = {id(v): k for k, v in named_parameters}
        self._compression = compression
        self.op = op
        self.gradient_predivide_factor = gradient_predivide_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._handles = {}
        self._grad_accs = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        self._allreduce_delay = {}
        # Register hooks for any world that can ever exceed 1: a static
        # world > 1, or an elastic job (reference optimizer.py:77 gates on
        # `size() > 1 or HOROVOD_ELASTIC == '1'`). Elastic scripts build
        # the optimizer BEFORE the first rendezvous initializes the world
        # (examples/pytorch_elastic.py), so a construction-time world
        # check must tolerate the uninitialized state — and an elastic
        # world that starts at 1 can grow, so hooks must exist anyway.
        # Strictly == "1", matching both the reference check and the
        # launcher contract (elastic/launcher.py:30, spark, ray, and
        # config_parser all export exactly "1"): a truthy-but-nonstandard
        # value like "true" must not diverge this gate from the other
        # HOROVOD_ELASTIC consumers (docs/troubleshooting.md).
        elastic = os.environ.get("HOROVOD_ELASTIC") == "1"
        try:
            world = mpi_ops._world()
        except NotInitializedError:
            if not elastic:
                raise
            world = 0
        if world > 1 or elastic:
            self._register_hooks()

    # -- hook plumbing (reference: optimizer.py:103-149) --

    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    if hasattr(p, "register_post_accumulate_grad_hook"):
                        p.register_post_accumulate_grad_hook(
                            self._make_post_hook(p))
                    else:  # pragma: no cover - older torch
                        p_tmp = p.expand_as(p)
                        grad_acc = p_tmp.grad_fn.next_functions[0][0]
                        grad_acc.register_hook(self._make_hook(p))
                        self._grad_accs.append(grad_acc)

    def _make_post_hook(self, p):
        def hook(param):
            self._on_grad_ready(p)
        return hook

    def _make_hook(self, p):
        def hook(*ignore):
            self._on_grad_ready(p)
        return hook

    def _on_grad_ready(self, p):
        if p in self._handles and self._handles[p][0] is not None:
            if self._allreduce_delay[p] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")  # reference: optimizer.py:135-141
        assert not p.grad.requires_grad
        assert self._allreduce_delay[p] > 0
        handle, ctx = None, None
        self._allreduce_delay[p] -= 1
        if self._allreduce_delay[p] == 0:
            handle, ctx = self._allreduce_grad_async(p)
        self._handles[p] = (handle, ctx)

    def _allreduce_grad_async(self, p):
        """Reference: optimizer.py:114-131 — prescale by 1/predivide for
        Average (so the wire carries predivided sums), fire async in-place
        allreduce on the (compressed) gradient."""
        name = self._parameter_names.get(id(p))
        tensor = p.grad
        tensor_compressed, ctx = self._compression.compress(tensor)
        if self.op == Average:
            prescale = 1.0 / self.gradient_predivide_factor
            postscale = self.gradient_predivide_factor
            handle = mpi_ops.allreduce_async_(
                tensor_compressed, name=name, op=Average,
                prescale_factor=prescale, postscale_factor=postscale)
        else:
            handle = mpi_ops.allreduce_async_(
                tensor_compressed, name=name, op=self.op)
        return handle, (tensor_compressed, ctx)

    # -- synchronization (reference: optimizer.py:151-200) --

    def synchronize(self):
        """Wait for all outstanding allreduces; decompress results back into
        ``p.grad`` (reference: optimizer.py:151-167)."""
        missing = [p for p in self._requires_update
                   if p not in self._handles and p.grad is not None]
        for p in missing:
            # step() without a full backward (e.g. joined rank): reduce now.
            self._allreduce_delay[p] = self.backward_passes_per_step
            self._handles[p] = self._allreduce_grad_async(p)
        # Flush params still mid-accumulation (handle None): step() means
        # the accumulation window ends now, so the partial sum must be
        # reduced — skipping it would apply an un-reduced gradient and
        # leave the delay counter torn (reference: optimizer.py:155-160).
        for p, (handle, ctx) in list(self._handles.items()):
            if handle is None:
                self._handles[p] = self._allreduce_grad_async(p)
        for p, (handle, ctx) in list(self._handles.items()):
            output = mpi_ops.synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            if ctx is not None:
                tensor_compressed, cctx = ctx
                p.grad.copy_(
                    self._compression.decompress(output, cctx)
                    .to(p.grad.dtype))
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """User already called synchronize(); don't repeat it inside step()
        (reference: optimizer.py:169-181)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called without a prior backward; "
                    "called synchronize() twice (reference warning, "
                    "optimizer.py:185-192)")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition. "
                "(reference: optimizer.py:202-207)")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         op=Average,
                         gradient_predivide_factor=1.0):
    """Wrap a torch optimizer so gradients are averaged across ranks before
    ``step()`` (reference: torch/optimizer.py:387-445).

    Returns an instance of a dynamically created class that inherits from
    the wrapped optimizer's class, so ``isinstance`` checks and LR schedulers
    keep working (the reference's exact trick, optimizer.py:420-445).
    """
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if op == Adasum:
        # Adasum-as-optimizer-op needs the delta-optimizer formulation
        # (reference: _DistributedAdasumOptimizer, optimizer.py:210-384).
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedAdasumOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Adasum delta optimizer (reference: optimizer.py:210-384): each rank
    applies the local step to a scratch copy, then Adasum-combines the
    *delta* (new - old) across ranks and applies the combined delta to the
    start point. Convergence-preserving mixing without a learning-rate
    rescale."""

    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [(f"allreduce.noname.{i}.{j}", v)
                                for i, group in enumerate(self.param_groups)
                                for j, v in enumerate(group["params"])]
        self._parameter_names = {id(v): k for k, v in named_parameters}
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        self._handles = {}
        self._starting_models = {}
        self._synchronized = False
        self._should_synchronize = True

    def _compute_delta(self, p, start):
        return p.data - start

    def synchronize(self):
        for p, (handle, start) in list(self._handles.items()):
            output = mpi_ops.synchronize(handle)
            p.data.copy_(start + output.to(p.dtype))
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        # Run the local optimizer step first, then Adasum the deltas.
        starts = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is not None:
                    starts[p] = p.data.clone()
        loss = super(self.__class__, self).step(closure)
        if mpi_ops._world() > 1:
            for p, start in starts.items():
                delta = self._compute_delta(p, start)
                p.data.copy_(start)
                name = self._parameter_names.get(id(p))
                handle = mpi_ops.allreduce_async(
                    delta, name=name, op=Adasum)
                self._handles[p] = (handle, start)
            if self._should_synchronize:
                self.synchronize()
            self._synchronized = False
        return loss

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "zero_grad() called with outstanding Adasum handles")
        return super(self.__class__, self).zero_grad(*args, **kwargs)
