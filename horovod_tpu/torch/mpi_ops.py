"""PyTorch collective ops over the native control plane.

Reference surface: ``horovod/torch/mpi_ops.py`` (handle-based async API,
mpi_ops.py:66-161) backed by ``torch/mpi_ops_v2.cc`` — per-dtype enqueue
functions returning integer handles, ``synchronize`` blocking on the
HandleManager.

TPU-native redesign: torch is a *host* framework here (the compute path is
JAX/XLA); torch tensors ride the same native C++ controller + TCP data plane
(horovod_tpu/cc/) the eager JAX API uses, so a torch data-loading or
fine-tuning script interoperates with JAX training processes in the same
world. Tensors cross the boundary as zero-copy numpy views wherever torch
allows it.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

try:
    import torch
except ImportError as e:  # pragma: no cover - torch is in the image
    raise ImportError(
        "horovod_tpu.torch requires pytorch (install torch)") from e

from ..common import basics
from ..common.exceptions import DuplicateTensorNameError
from ..ops import collective_ops as C
from ..ops.collective_ops import ReduceOp

# Reduce op handles (reference: torch/mpi_ops.py:40-48 re-exports the op
# constants from the native module).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT

__all__ = [
    "Average", "Sum", "Adasum", "Min", "Max", "Product",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall", "alltoall_async",
    "join", "poll", "synchronize",
]


# --------------------------------------------------------------------------
# torch <-> numpy bridges
# --------------------------------------------------------------------------


def _to_numpy(tensor: "torch.Tensor") -> np.ndarray:
    """Contiguous numpy view of a torch tensor (zero-copy when possible;
    bf16 goes over the wire bit-exact via ml_dtypes)."""
    t = tensor.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    t = t.contiguous()
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _from_numpy(arr: np.ndarray, like: "torch.Tensor") -> "torch.Tensor":
    if like.dtype == torch.bfloat16:
        out = torch.from_numpy(np.ascontiguousarray(arr.view(np.int16)))
        return out.view(torch.bfloat16).to(like.device)
    return torch.from_numpy(np.ascontiguousarray(arr)).to(like.device)


# --------------------------------------------------------------------------
# Handle manager (reference: torch/handle_manager.{h,cc} — int handles map to
# in-flight collectives; synchronize pops and blocks).
# --------------------------------------------------------------------------


class _TorchHandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries = {}
        self._names = set()
        self._next = 0

    def allocate(self, starter, name: Optional[str] = None) -> int:
        """Reserve the name FIRST, then dispatch via ``starter()`` — a
        duplicate name must be rejected before anything reaches the native
        core, or the orphaned in-flight collective is never waited on."""
        with self._lock:
            if name is not None:
                if name in self._names:
                    raise DuplicateTensorNameError(
                        f"Tensor name {name!r} already in an in-flight "
                        "collective (reference: DUPLICATE_NAME_ERROR, "
                        "common.h:163)")
                self._names.add(name)
        try:
            finisher, native_handle = starter()
        except BaseException:
            if name is not None:
                with self._lock:
                    self._names.discard(name)
            raise
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = (finisher, native_handle, name)
            return h

    def poll(self, handle: int) -> bool:
        with self._lock:
            entry = self._entries.get(handle)
        if entry is None:
            return True  # finished handles report done (handle_manager.cc)
        _, native, _ = entry
        return True if native is None else bool(native.poll())

    def wait_and_clear(self, handle: int):
        with self._lock:
            if handle not in self._entries:
                raise ValueError(f"unknown or already-synchronized handle "
                                 f"{handle}")
            finisher, native, name = self._entries.pop(handle)
            if name is not None:
                self._names.discard(name)
        return finisher()


_handles = _TorchHandleManager()


def poll(handle: int) -> bool:
    """True when the collective behind ``handle`` completed
    (reference: torch/mpi_ops.py:88-99)."""
    return _handles.poll(handle)


def synchronize(handle: int) -> "torch.Tensor":
    """Block until the collective completes, return its output tensor
    (reference: torch/mpi_ops.py:101-127)."""
    return _handles.wait_and_clear(handle)


def _world() -> int:
    return C._eager_world()


def _ctrl_ctx():
    return C._eager_ctx()


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------


def _start_allreduce(tensor, output, op, name, prescale_factor,
                     postscale_factor):
    """Dispatch; returns (finisher, native_handle)."""
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "torch.allreduce")
    if world == 1:
        # Every op is identity over a world of one modulo the pre/postscale
        # factors, which the native core applies around the reduction for
        # all ops — match that here so numerics don't depend on world size.
        scale = prescale_factor * postscale_factor
        result = tensor.detach().clone() if scale == 1.0 \
            else tensor.detach() * scale

        def finish():
            output.copy_(result)
            return output
        return finish, None
    opmap = {Sum: ctrl.SUM, Average: ctrl.SUM, Min: ctrl.MIN, Max: ctrl.MAX,
             Product: ctrl.PRODUCT, Adasum: ctrl.ADASUM}
    post = postscale_factor / world if op == Average else postscale_factor
    # The native core reduces in place on the wire buffer; feed it the
    # *output* tensor's storage (a clone for the out-of-place variant, the
    # input itself for the in-place one) so inputs are never clobbered.
    native = ctrl.allreduce_async(
        _to_numpy(output), opname, op=opmap[op],
        prescale=float(prescale_factor), postscale=float(post))

    def finish():
        out = native.wait()
        output.copy_(_from_numpy(out, output).view(output.shape))
        return output
    return finish, native


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0) -> int:
    """Async allreduce into a fresh output tensor; returns a handle
    (reference: torch/mpi_ops.py:119-161)."""
    rop = _normalize_op(average, op)
    output = tensor.detach().clone()
    return _handles.allocate(
        lambda: _start_allreduce(tensor, output, rop, name,
                                 prescale_factor, postscale_factor), name)


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0) -> int:
    """In-place async allreduce (reference: torch/mpi_ops.py:223-259)."""
    rop = _normalize_op(average, op)

    def starter():
        finish, native = _start_allreduce(tensor, tensor.data, rop, name,
                                          prescale_factor, postscale_factor)
        return (lambda: (finish(), tensor)[1]), native
    return _handles.allocate(starter, name)


class _HorovodAllreduce(torch.autograd.Function):
    """Differentiable allreduce (reference: HorovodAllreduce in
    torch/mpi_ops.py:163-179 — the gradient of an allreduce is an allreduce
    of the gradient with the same op)."""

    @staticmethod
    def forward(ctx, tensor, op, name, prescale_factor, postscale_factor):
        ctx.op = op
        ctx.prescale_factor = prescale_factor
        ctx.postscale_factor = postscale_factor
        return synchronize(allreduce_async(
            tensor, op=op, name=name, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor))

    @staticmethod
    def backward(ctx, grad_output):
        grad = synchronize(allreduce_async(
            grad_output, op=ctx.op, prescale_factor=ctx.prescale_factor,
            postscale_factor=ctx.postscale_factor))
        return grad, None, None, None, None


def allreduce(tensor, average=None, name=None, compression=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0) -> "torch.Tensor":
    """Synchronous, differentiable allreduce (reference:
    torch/mpi_ops.py:181-221)."""
    from .compression import Compression

    op = _normalize_op(average, op)
    compression = compression or Compression.none
    compressed, cctx = compression.compress(tensor)
    reduced = _HorovodAllreduce.apply(compressed, op, name, prescale_factor,
                                      postscale_factor)
    return compression.decompress(reduced, cctx)


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0) -> "torch.Tensor":
    """Synchronous in-place allreduce (reference: torch/mpi_ops.py:261-292)."""
    return synchronize(allreduce_async_(
        tensor, average, name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


def _normalize_op(average, op):
    """Reconcile the legacy ``average=`` flag with ``op=`` (reference:
    torch/mpi_ops.py:52-64 handle_average_backwards_compatibility)."""
    if average is not None and op is not None:
        raise ValueError("both average and op are specified")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


# --------------------------------------------------------------------------
# allgather
# --------------------------------------------------------------------------


def _start_allgather(tensor, name):
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "torch.allgather")
    if world == 1:
        result = tensor.detach().clone()
        return (lambda: result), None
    native = ctrl.allgather_async(
        np.ascontiguousarray(_to_numpy(tensor)), opname)

    def finish():
        return _from_numpy(native.wait(), tensor)
    return finish, native


def allgather_async(tensor, name=None) -> int:
    """Async first-dim concatenation across ranks (reference:
    torch/mpi_ops.py:294-317); ranks may differ in dim 0."""
    return _handles.allocate(lambda: _start_allgather(tensor, name), name)


class _HorovodAllgather(torch.autograd.Function):
    """Reference: HorovodAllgather (torch/mpi_ops.py) — backward allreduces
    the gradient and slices out this rank's segment."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0] if tensor.dim() > 0 else 1
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        grad = synchronize(allreduce_async(grad_output, op=Sum))
        dims = synchronize(allgather_async(
            torch.tensor([ctx.dim0], dtype=torch.int64)))
        r = rank()
        offset = int(dims[:r].sum().item()) if r > 0 else 0
        return grad.narrow(0, offset, ctx.dim0), None


def allgather(tensor, name=None) -> "torch.Tensor":
    """Synchronous, differentiable allgather (reference:
    torch/mpi_ops.py:319-343)."""
    return _HorovodAllgather.apply(tensor, name)


# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------


def _start_broadcast(tensor, output, root_rank, name):
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "torch.broadcast")
    if world == 1:
        result = tensor.detach().clone()

        def finish():
            output.copy_(result)
            return output
        return finish, None
    native = ctrl.broadcast_async(_to_numpy(output), opname, root=root_rank)

    def finish():
        output.copy_(_from_numpy(native.wait(), output).view(output.shape))
        return output
    return finish, native


def broadcast_async(tensor, root_rank, name=None) -> int:
    """Reference: torch/mpi_ops.py:345-369."""
    output = tensor.detach().clone()
    return _handles.allocate(
        lambda: _start_broadcast(tensor, output, root_rank, name), name)


def broadcast_async_(tensor, root_rank, name=None) -> int:
    """In-place async broadcast (reference: torch/mpi_ops.py:399-424)."""

    def starter():
        finish, native = _start_broadcast(tensor, tensor.data, root_rank,
                                          name)
        return (lambda: (finish(), tensor)[1]), native
    return _handles.allocate(starter, name)


class _HorovodBroadcast(torch.autograd.Function):
    """Reference: HorovodBroadcast — backward sums gradients to the root,
    zeros elsewhere."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name))

    @staticmethod
    def backward(ctx, grad_output):
        grad = synchronize(allreduce_async(grad_output, op=Sum))
        if rank() != ctx.root_rank:
            grad = grad * 0
        return grad, None, None


def broadcast(tensor, root_rank, name=None) -> "torch.Tensor":
    """Synchronous, differentiable broadcast (reference:
    torch/mpi_ops.py:371-397)."""
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_(tensor, root_rank, name=None) -> "torch.Tensor":
    """Synchronous in-place broadcast (reference: torch/mpi_ops.py:426-450)."""
    return synchronize(broadcast_async_(tensor, root_rank, name))


# --------------------------------------------------------------------------
# alltoall
# --------------------------------------------------------------------------


def _start_alltoall(tensor, splits, name):
    ctrl, world = _ctrl_ctx()
    opname = C._eager_name(name, "torch.alltoall")
    if world == 1:
        result = tensor.detach().clone()
        rsplits = torch.tensor(
            [tensor.shape[0] if tensor.dim() > 0 else 1], dtype=torch.int32)
        return (lambda: (result, rsplits)), None
    sp = None if splits is None else [int(x) for x in splits]
    native = ctrl.alltoall_async(
        np.ascontiguousarray(_to_numpy(tensor)), opname, splits=sp)

    def finish():
        out = native.wait()
        return (_from_numpy(out, tensor),
                torch.from_numpy(np.asarray(native.recv_splits(),
                                            dtype=np.int32)))
    return finish, native


def alltoall_async(tensor, splits=None, name=None) -> int:
    """Async alltoall with optional uneven splits (reference:
    torch/mpi_ops.py:452-487)."""
    return _handles.allocate(
        lambda: _start_alltoall(tensor, splits, name), name)


def alltoall(tensor, splits=None, name=None):
    """Synchronous alltoall; returns (output, received_splits) (reference:
    torch/mpi_ops.py:489-518)."""
    return synchronize(alltoall_async(tensor, splits, name))


# --------------------------------------------------------------------------
# join
# --------------------------------------------------------------------------


def join(device=-1) -> int:
    """Signal that this rank has no more tensors to reduce; blocks until all
    ranks join and returns the last joined rank (reference:
    torch/mpi_ops.py:520-548; JoinOp collective_operations.cc:256-264).
    ``device`` is accepted for API parity (the reference uses it to place the
    zero-fill tensors on a GPU)."""
    return C.join()


def rank() -> int:
    return int(basics.rank())
