"""Synchronous batch normalization across ranks for PyTorch.

Reference: ``horovod/torch/sync_batch_norm.py`` — a ``_BatchNorm`` subclass
whose training-mode forward computes batch statistics over the *global*
batch via collectives, with a custom autograd Function for the backward
reduction (sync_batch_norm.py:29-199).
"""

from __future__ import annotations

import torch
import torch.nn.functional as F
from torch.nn.modules.batchnorm import _BatchNorm

from . import mpi_ops
from .mpi_ops import Sum


class SyncBatchNorm(_BatchNorm):
    """Applies BatchNorm over the global (cross-rank) batch (reference:
    sync_batch_norm.py:29-110)."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        self._check_input_dim(input)
        # momentum=None means cumulative moving average; resolve it to a
        # concrete factor for BOTH paths (F.batch_norm rejects None).
        exponential_average_factor = \
            0.0 if self.momentum is None else self.momentum
        if self.training and self.track_running_stats:
            self.num_batches_tracked += 1
            if self.momentum is None:
                exponential_average_factor = \
                    1.0 / float(self.num_batches_tracked)
        if not self.training or mpi_ops._world() == 1:
            # Eval mode / single rank: plain batch norm
            # (reference: sync_batch_norm.py:97-103).
            return F.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, self.training, exponential_average_factor,
                self.eps)
        return _SyncBatchNorm.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, exponential_average_factor)


class _SyncBatchNorm(torch.autograd.Function):
    """Reference: sync_batch_norm.py:113-199 — forward allgathers per-rank
    mean/invstd/count; here the equivalent sufficient statistics (sum,
    sqsum, count) ride one fused allreduce, which is the TPU-shaped version
    of the same reduction."""

    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        dims = [0] + list(range(2, input.dim()))
        n_local = input.numel() // input.size(1)
        stats = torch.cat([
            input.sum(dims).float(),
            (input * input).sum(dims).float(),
            torch.tensor([float(n_local)], dtype=torch.float32,
                         device=input.device),
        ])
        stats = mpi_ops.allreduce(stats, op=Sum, name="sync_bn.fwd_stats")
        c = input.size(1)
        count = stats[-1]
        mean = stats[:c] / count
        var = stats[c:2 * c] / count - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            unbiased_var = var * (count / (count - 1).clamp(min=1))
            running_mean.mul_(1 - momentum).add_(mean, alpha=momentum)
            running_var.mul_(1 - momentum).add_(unbiased_var, alpha=momentum)

        ctx.save_for_backward(input, weight, mean, invstd, count)
        shape = [1, c] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape).to(input.dtype)) * \
            invstd.view(shape).to(input.dtype)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape)
        if bias is not None:
            out = out + bias.view(shape)
        ctx.xhat = None  # recomputed in backward from saved stats
        return out

    @staticmethod
    def backward(ctx, grad_output):
        input, weight, mean, invstd, count = ctx.saved_tensors
        dims = [0] + list(range(2, input.dim()))
        c = input.size(1)
        shape = [1, c] + [1] * (input.dim() - 2)
        xmu = input - mean.view(shape).to(input.dtype)

        # Local weight/bias grads (world-averaged later by the
        # DistributedOptimizer like any other parameter grad).
        grad_weight = None
        if weight is not None and ctx.needs_input_grad[1]:
            grad_weight = (grad_output * xmu *
                           invstd.view(shape).to(input.dtype)).sum(dims)
        grad_bias = None
        if ctx.needs_input_grad[2]:
            grad_bias = grad_output.sum(dims)

        # Global reduction of dy statistics (reference:
        # sync_batch_norm.py:163-199 allreduces sum_dy / sum_dy_xmu).
        red = torch.cat([
            grad_output.sum(dims).float(),
            (grad_output * xmu).sum(dims).float(),
        ])
        red = mpi_ops.allreduce(red, op=Sum, name="sync_bn.bwd_stats")
        sum_dy = red[:c]
        sum_dy_xmu = red[c:]

        w = weight.view(shape).to(input.dtype) if weight is not None else 1.0
        iv = invstd.view(shape).to(input.dtype)
        m = count.to(input.dtype)
        grad_input = w * iv * (
            grad_output
            - (sum_dy.view(shape).to(input.dtype) / m)
            - xmu * (iv ** 2) *
            (sum_dy_xmu.view(shape).to(input.dtype) / m))
        return grad_input, grad_weight, grad_bias, None, None, None, None
