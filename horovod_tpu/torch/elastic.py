"""Elastic training state for PyTorch.

Reference: ``horovod/torch/elastic/state.py`` (TorchState with per-attribute
handlers, state.py:27-179) and ``horovod/torch/elastic/sampler.py``
(ElasticSampler re-sharding remaining samples on world change).
"""

from __future__ import annotations

import copy
from typing import Dict

import torch

from ..elastic.state import State
from ..elastic import run as run  # noqa: F401  (hvd.elastic.run parity)
from . import functions as _fn
from . import mpi_ops


class TorchState(State):
    """Elastic state holding torch models/optimizers plus scalar attrs
    (reference: torch/elastic/state.py:27-118). Usage::

        state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=0)

    ``save``/``restore`` keep in-memory copies; ``sync`` broadcasts from the
    new rank 0 after a reset.
    """

    def __init__(self, model=None, optimizer=None, **kwargs):
        self._handlers: Dict[str, "_StateHandler"] = {}
        if model is not None:
            self._handlers["model"] = _ModelStateHandler(model)
            self.model = model
        if optimizer is not None:
            self._handlers["optimizer"] = _OptimizerStateHandler(optimizer)
            self.optimizer = optimizer
        self._obj_attrs = dict(kwargs)
        for k, v in kwargs.items():
            if isinstance(v, torch.nn.Module):
                self._handlers[k] = _ModelStateHandler(v)
            elif isinstance(v, torch.optim.Optimizer):
                self._handlers[k] = _OptimizerStateHandler(v)
            elif hasattr(v, "state_dict") and hasattr(v, "load_state_dict"):
                self._handlers[k] = _SamplerStateHandler(v)
            setattr(self, k, v)
        self._saved_obj_state = {}
        super().__init__()
        self.save()

    def _plain_keys(self):
        return [k for k in self._obj_attrs if k not in self._handlers]

    def save(self) -> None:
        for handler in self._handlers.values():
            handler.save()
        self._saved_obj_state = {
            k: copy.deepcopy(getattr(self, k)) for k in self._plain_keys()}

    def restore(self) -> None:
        for handler in self._handlers.values():
            handler.restore()
        for k, v in self._saved_obj_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        for handler in self._handlers.values():
            handler.sync()
        plain = {k: getattr(self, k) for k in self._plain_keys()}
        if plain:
            synced = _fn.broadcast_object(plain, root_rank=0,
                                          name="elastic.torch_state")
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()

    def __setattr__(self, name, value):
        # Keep handlers pointed at replaced modules/optimizers
        # (reference: state.py:96-108 __setattr__ hook).
        if not name.startswith("_") and hasattr(self, "_handlers") \
                and name in self._handlers:
            self._handlers[name].set_value(value)
        super().__setattr__(name, value)


class _StateHandler:
    def __init__(self, value):
        self.value = value

    def set_value(self, value):
        self.value = value
        self.save()

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class _ModelStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:121-140."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        _fn.broadcast_parameters(self.value.state_dict(), root_rank=0)
        self.save()


class _OptimizerStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:143-160."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        _fn.broadcast_optimizer_state(self.value, root_rank=0)
        self.save()


class _SamplerStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:163-179."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        state = _fn.broadcast_object(self.value.state_dict(), root_rank=0,
                                     name="elastic.sampler_state")
        self.value.load_state_dict(state)
        self.save()


class ElasticSampler(torch.utils.data.Sampler):
    """Distributed sampler that re-shards *remaining* (unprocessed) samples
    when the world changes (reference: torch/elastic/sampler.py)."""

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices = set()
        self.num_replicas = 0
        self.rank = 0
        self.remaining_indices = []
        self.num_samples = 0
        self.total_size = 0
        self.reset()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark a batch consumed so a post-reset reshard skips it."""
        processed = self.indices[batch_idx * batch_size:
                                 (batch_idx + 1) * batch_size]
        self.processed_indices.update(processed)

    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "processed_indices": self.processed_indices,
        }

    def load_state_dict(self, state_dict: dict) -> None:
        self.epoch = state_dict["epoch"]
        self.processed_indices = set(state_dict["processed_indices"])
        self.reset()

    def reset(self) -> None:
        self.num_replicas = mpi_ops._world() \
            if _initialized() else 1
        self.rank = mpi_ops.rank() if _initialized() else 0

        remaining = [idx for idx in range(len(self.dataset))
                     if idx not in self.processed_indices]
        if self.shuffle:
            g = torch.Generator()
            g.manual_seed(self.seed + self.epoch)
            perm = torch.randperm(len(remaining), generator=g).tolist()
            remaining = [remaining[i] for i in perm]
        self.remaining_indices = remaining

        self.num_samples = len(self.remaining_indices) // self.num_replicas
        self.total_size = self.num_samples * self.num_replicas
        shard = self.remaining_indices[:self.total_size]
        self.indices = shard[self.rank:self.total_size:self.num_replicas]

    def __iter__(self):
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples


def _initialized() -> bool:
    from ..common import basics

    return basics.is_initialized()
