"""Elastic training state for PyTorch.

Reference: ``horovod/torch/elastic/state.py`` (TorchState with per-attribute
handlers, state.py:27-179) and ``horovod/torch/elastic/sampler.py``
(ElasticSampler re-sharding remaining samples on world change).
"""

from __future__ import annotations

import copy
from typing import Dict

import torch

from ..elastic.state import State
from ..elastic.sampler import ElasticSampler as _CoreElasticSampler
from ..elastic import run as run  # noqa: F401  (hvd.elastic.run parity)
from . import functions as _fn


class TorchState(State):
    """Elastic state holding torch models/optimizers plus scalar attrs
    (reference: torch/elastic/state.py:27-118). Usage::

        state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=0)

    ``save``/``restore`` keep in-memory copies; ``sync`` broadcasts from the
    new rank 0 after a reset.
    """

    def __init__(self, model=None, optimizer=None, **kwargs):
        self._handlers: Dict[str, "_StateHandler"] = {}
        if model is not None:
            self._handlers["model"] = _ModelStateHandler(model)
            self.model = model
        if optimizer is not None:
            self._handlers["optimizer"] = _OptimizerStateHandler(optimizer)
            self.optimizer = optimizer
        self._obj_attrs = dict(kwargs)
        for k, v in kwargs.items():
            if isinstance(v, torch.nn.Module):
                self._handlers[k] = _ModelStateHandler(v)
            elif isinstance(v, torch.optim.Optimizer):
                self._handlers[k] = _OptimizerStateHandler(v)
            elif hasattr(v, "state_dict") and hasattr(v, "load_state_dict"):
                self._handlers[k] = _SamplerStateHandler(v)
            setattr(self, k, v)
        self._saved_obj_state = {}
        super().__init__()
        self.save()

    def _plain_keys(self):
        return [k for k in self._obj_attrs if k not in self._handlers]

    def save(self) -> None:
        for handler in self._handlers.values():
            handler.save()
        self._saved_obj_state = {
            k: copy.deepcopy(getattr(self, k)) for k in self._plain_keys()}

    def restore(self) -> None:
        for handler in self._handlers.values():
            handler.restore()
        for k, v in self._saved_obj_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        for handler in self._handlers.values():
            handler.sync()
        plain = {k: getattr(self, k) for k in self._plain_keys()}
        if plain:
            synced = _fn.broadcast_object(plain, root_rank=0,
                                          name="elastic.torch_state")
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()

    def __setattr__(self, name, value):
        # Keep handlers pointed at replaced modules/optimizers
        # (reference: state.py:96-108 __setattr__ hook).
        if not name.startswith("_") and hasattr(self, "_handlers") \
                and name in self._handlers:
            self._handlers[name].set_value(value)
        super().__setattr__(name, value)


class _StateHandler:
    def __init__(self, value):
        self.value = value

    def set_value(self, value):
        self.value = value
        self.save()

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class _ModelStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:121-140."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        _fn.broadcast_parameters(self.value.state_dict(), root_rank=0)
        self.save()


class _OptimizerStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:143-160."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        _fn.broadcast_optimizer_state(self.value, root_rank=0)
        self.save()


class _SamplerStateHandler(_StateHandler):
    """Reference: torch/elastic/state.py:163-179."""

    def save(self):
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(copy.deepcopy(self._saved_state))

    def sync(self):
        state = _fn.broadcast_object(self.value.state_dict(), root_rank=0,
                                     name="elastic.sampler_state")
        self.value.load_state_dict(state)
        self.save()


class ElasticSampler(_CoreElasticSampler, torch.utils.data.Sampler):
    """Distributed sampler that re-shards *remaining* (unprocessed) samples
    when the world changes (reference: torch/elastic/sampler.py).

    Thin torch-Sampler adapter over the framework-neutral
    :class:`horovod_tpu.elastic.sampler.ElasticSampler` — one resharding
    implementation, two framework surfaces.
    """

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        _CoreElasticSampler.__init__(self, len(dataset), shuffle=shuffle,
                                     seed=seed)

    def _world(self):
        # Torch ranks are *processes* (the reference's model), not mesh
        # chips: shard over the eager/process world, unlike the JAX
        # sampler which shards batches across chips.
        from ..common import basics

        if not basics.is_initialized():
            return 0, 1
        s = basics._require_init()
        if s.controller is not None:
            return s.controller.rank(), s.controller.size()
        return s.process_index, s.process_count
