#!/usr/bin/env python
"""Gate check for scripts/perf_gate.sh: one bench JSON line on argv[1].

Serve leg: compares against the seeded ``BENCH_serve_baseline.json``
(created on first run; refresh with PERF_GATE_UPDATE=1), after hard
correctness assertions (no dropped requests, parity probe present).
Train legs: compares against the best SAME-platform, same-metric value
recorded in the ``BENCH_r*.json`` trajectory (each of those wraps the
bench's one-line JSON under ``parsed`` or inside ``tail``).
cost leg (``--quantized`` A/B): gates the cost model's predicted
wire-ms against the traced program's accounted bytes at the modeled
bandwidths — |predicted − measured| / measured ≤ PERF_GATE_COST_DRIFT
(default 0.25, docs/cost-model.md) — then throughput like a train leg.
zero<stage> legs (``--zero-stage`` A/B): structural memory gates against
the replicated baseline measured in the SAME run — each component the
stage claims to shard must be within PERF_GATE_ZERO_SLACK (default 1.30,
bucket padding headroom) of its 1/world share — the stage-parity probe
must have passed, the async checkpoint probe must have committed with a
save stall under PERF_GATE_CKPT_STALL_FRAC (default 0.10) of a step,
and throughput gates against the trajectory like a train leg.

Exit 0 = within tolerance, 1 = regression, 2 = usage/baseline error.

Every verdict is ALSO appended as a metrics-JSONL snapshot (the same
schema the monitor registry's JsonlSink writes, so obs_report.py and any
JSONL consumer can query the gate history) to PERF_GATE_METRICS_JSONL
(default: .perf_gate/metrics.jsonl — a gitignored directory, so the
artifact can never land in the repo root again): per-leg measured vs
baseline gauges, the tolerance, and pass/fail — regressions become
queryable data, not just an exit code.

pp4d leg (``--pp x --moe x --zero-stage 3`` combined): hard-gates the
pipelined-MoE-vs-dense parity, the T3 bubble-fill contract — nonzero
``bubble_hidden_bytes`` with accounted == predicted fill bytes
(docs/pipeline.md) — engaged a2a AND send wire, and the a2a
predicted-vs-modeled wire-ms drift — then throughput vs the
trajectory.

moe leg (``--moe`` A/B): hard-gates the forced-routing parity probe,
the dropped-token fraction (<= PERF_GATE_MOE_DROPPED, default 0.25),
and the a2a predicted-vs-modeled wire-ms drift (<=
PERF_GATE_COST_DRIFT) — then throughput vs the trajectory
(docs/moe.md).

soak leg: takes the scripts/soak.py report JSON instead of a bench
line and hard-fails when ANY of the soak gates (recovery, loss
trajectory, commit cadence, deadline-met priority snapshot, ...) is
false — every soak gate also lands in the verdict snapshot
(docs/robustness.md).

Training legs with an EMPTY trajectory (no same-metric, same-platform
``BENCH_r*.json`` record — e.g. the cpu trajectory was benched on a
different model) fall back to the committed
``BENCH_train_baseline.json``, keyed ``metric|platform``: missing keys
self-seed on first run (refresh with PERF_GATE_UPDATE=1), so the leg
still gates instead of silently passing.
"""

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_BASELINE = os.path.join(REPO, "BENCH_serve_baseline.json")
TRAIN_BASELINE = os.path.join(REPO, "BENCH_train_baseline.json")

sys.path.insert(0, REPO)

_VERDICTS = []


def record_verdict(leg, what, measured, baseline, tol, ok):
    _VERDICTS.append({"leg": leg, "what": what, "measured": measured,
                      "baseline": baseline, "tol": tol, "ok": ok})


def write_verdict_snapshot():
    """One metrics snapshot (monitor-registry schema) per gate run."""
    path = os.environ.get(
        "PERF_GATE_METRICS_JSONL",
        os.path.join(REPO, ".perf_gate", "metrics.jsonl"))
    if not path or path == "0":
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    from horovod_tpu.monitor import JsonlSink, MetricsRegistry

    reg = MetricsRegistry(enabled=True)
    for v in _VERDICTS:
        labels = {"leg": v["leg"], "what": v["what"].replace(" ", "_")}
        reg.gauge("perf_gate.measured", **labels).set(v["measured"])
        reg.gauge("perf_gate.baseline", **labels).set(v["baseline"])
        reg.gauge("perf_gate.tolerance", **labels).set(v["tol"])
        reg.gauge("perf_gate.pass", **labels).set(1.0 if v["ok"] else 0.0)
        if not v["ok"]:
            reg.counter("perf_gate.regressions", **labels).inc()
    snap = reg.snapshot()
    snap["perf_gate"] = {"legs": sorted({v["leg"] for v in _VERDICTS}),
                         "pass": all(v["ok"] for v in _VERDICTS)}
    JsonlSink(path).write(snap)
    print(f"perf gate: verdict snapshot appended to {path}")


def trajectory_records():
    """Bench metric lines embedded in the recorded BENCH_r*.json trail."""
    out = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r[0-9]*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            out.append((os.path.basename(path), parsed))
            continue
        for line in reversed(rec.get("tail", "").splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    out.append((os.path.basename(path), json.loads(line)))
                except ValueError:
                    pass
                break
    return out


def gate(measured, baseline, tol, what, leg=None):
    floor = tol * baseline
    ok = measured >= floor
    verdict = "OK" if ok else "REGRESSION"
    print(f"perf gate [{what}]: measured {measured:.2f} vs baseline "
          f"{baseline:.2f} (floor {floor:.2f} at tol {tol}) -> {verdict}")
    record_verdict(leg or os.environ.get("PERF_GATE_LEG", "serve"), what,
                   measured, baseline, tol, ok)
    return ok


def _zero_leg(rec, leg, tol):
    """Structural gates for a ``--zero-stage`` A/B record; returns 0 when
    every sharding/checkpoint invariant holds, 1 on regression."""
    stage = int(rec.get("zero_stage") or leg[4:])
    world = int(rec.get("chips") or 0)
    slack = float(os.environ.get("PERF_GATE_ZERO_SLACK", "1.30"))
    stall_cap = float(os.environ.get("PERF_GATE_CKPT_STALL_FRAC", "0.10"))
    mine = rec.get("bytes_per_rank") or {}
    base = rec.get("bytes_per_rank_baseline") or {}
    if world < 2 or not mine or not base:
        print(f"perf gate [{leg}]: record lacks bytes_per_rank A/B "
              f"(chips={world}) — hard fail")
        record_verdict(leg, "bytes_per_rank_present", 0, 1, tol, False)
        return 1
    ok = True

    def shard_gate(component):
        # "must not regress" for a byte count means staying at its
        # 1/world share (plus padding slack) — gate() is >=, so compare
        # the achieved reduction factor against world/slack.
        b = float(base.get(component, 0.0))
        if b <= 0:
            return  # component absent in this config (e.g. no grad
            # accumulation at backward_passes_per_step=1)
        m = max(1.0, float(mine.get(component, 0.0)))
        nonlocal ok
        ok &= gate(b / m, float(world), 1.0 / slack,
                   f"{component} reduction x", leg=leg)

    shard_gate("opt_state")
    if stage >= 2:
        shard_gate("grad_accum")
    if stage >= 3:
        shard_gate("params")

    parity = rec.get("stage_parity") or {}
    if not parity.get("stage12_bit_identical"):
        print(f"perf gate [{leg}]: stage-1/2 parity probe failed — "
              f"hard fail")
        record_verdict(leg, "stage12_bit_identical", 0, 1, tol, False)
        ok = False
    rel3 = parity.get("stage3_max_rel_err")
    if rel3 is None or rel3 > 1e-5:
        print(f"perf gate [{leg}]: stage-3 parity {rel3} exceeds 1e-5 — "
              f"hard fail")
        record_verdict(leg, "stage3_max_rel_err", rel3 or -1, 1e-5, tol,
                       False)
        ok = False

    if int(rec.get("ckpt_commits") or 0) < 1:
        print(f"perf gate [{leg}]: no checkpoint commits — hard fail")
        record_verdict(leg, "ckpt_commits", rec.get("ckpt_commits", 0), 1,
                       tol, False)
        ok = False
    frac = rec.get("ckpt_stall_frac")
    if frac is not None:
        # gate() is a >= check; bound the stall from above by gating the
        # headroom (cap - frac) against zero... keep it direct instead:
        within = frac <= stall_cap
        print(f"perf gate [{leg} ckpt_stall_frac]: measured {frac:.4f} "
              f"vs cap {stall_cap} -> "
              f"{'OK' if within else 'REGRESSION'}")
        record_verdict(leg, "ckpt_stall_frac", frac, stall_cap, tol,
                       within)
        ok &= within
    return 0 if ok else 1


def main():
    try:
        return _main()
    finally:
        try:
            write_verdict_snapshot()
        except Exception as e:  # the snapshot must never mask the verdict
            print(f"perf gate: verdict snapshot failed: {e}",
                  file=sys.stderr)


def _main():
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    rec = json.loads(sys.argv[1])
    leg = os.environ.get("PERF_GATE_LEG", "serve")
    tol = float(os.environ.get("PERF_GATE_TOL", "0.60"))
    update = os.environ.get("PERF_GATE_UPDATE") == "1"

    if leg == "serve":
        if rec.get("requests_dropped", 1) != 0:
            print(f"perf gate [serve]: dropped requests "
                  f"{rec.get('requests_dropped')} — hard fail")
            record_verdict("serve", "dropped_requests",
                           rec.get("requests_dropped", -1), 0, tol, False)
            return 1
        if rec.get("goodput_tokens_per_sec", 0) <= 0:
            print("perf gate [serve]: zero goodput — hard fail")
            record_verdict("serve", "goodput_tokens_per_sec", 0,
                           rec.get("goodput_tokens_per_sec", 0), tol, False)
            return 1
        if update or not os.path.exists(SERVE_BASELINE):
            with open(SERVE_BASELINE, "w") as f:
                json.dump(rec, f, indent=1)
            print(f"perf gate [serve]: seeded baseline "
                  f"{os.path.basename(SERVE_BASELINE)} at goodput "
                  f"{rec['goodput_tokens_per_sec']} tok/s")
            return 0
        with open(SERVE_BASELINE) as f:
            base = json.load(f)
        if base.get("platform") != rec.get("platform"):
            print(f"perf gate [serve]: platform changed "
                  f"({base.get('platform')} -> {rec.get('platform')}); "
                  f"re-seed with PERF_GATE_UPDATE=1")
            return 2
        ok = gate(rec["goodput_tokens_per_sec"],
                  base["goodput_tokens_per_sec"], tol, "serve goodput")
        ok &= gate(rec["tokens_per_sec"], base["tokens_per_sec"], tol,
                   "serve throughput")
        return 0 if ok else 1

    if leg == "serve_disagg":
        # Disaggregated serving (docs/serving.md): the record carries its
        # OWN symmetric baseline (measured in the same run), so every
        # gate is structural — no seeded baseline file needed.
        ok = True
        if rec.get("requests_dropped", 1) != 0:
            print(f"perf gate [serve_disagg]: dropped requests "
                  f"{rec.get('requests_dropped')} — hard fail")
            record_verdict(leg, "dropped_requests",
                           rec.get("requests_dropped", -1), 0, tol, False)
            ok = False
        if not rec.get("spec_parity_ok"):
            print("perf gate [serve_disagg]: greedy spec-decode parity "
                  "probe failed — hard fail")
            record_verdict(leg, "spec_parity_ok", 0, 1, tol, False)
            ok = False
        hit_rate = float(rec.get("prefix_hit_rate") or 0)
        if hit_rate <= 0:
            print("perf gate [serve_disagg]: prefix cache never hit — "
                  "hard fail")
            record_verdict(leg, "prefix_hit_rate", hit_rate, 0, tol,
                           False)
            ok = False
        else:
            record_verdict(leg, "prefix_hit_rate", hit_rate, 0, tol, True)
        if int(rec.get("kv_migrations") or 0) < 1:
            print("perf gate [serve_disagg]: no KV migrations — the "
                  "prefill/decode handoff never engaged — hard fail")
            record_verdict(leg, "kv_migrations",
                           rec.get("kv_migrations", 0), 1, tol, False)
            ok = False
        drift = rec.get("kv_bytes_drift")
        drift_tol = float(os.environ.get("PERF_GATE_COST_DRIFT", "0.25"))
        if drift is None or abs(drift) > drift_tol:
            print(f"perf gate [serve_disagg]: migration byte drift "
                  f"{drift} exceeds cap {drift_tol} — hard fail")
            record_verdict(leg, "kv_bytes_drift",
                           drift if drift is not None else -1, drift_tol,
                           tol, False)
            ok = False
        else:
            record_verdict(leg, "kv_bytes_drift", drift, drift_tol, tol,
                           True)
        stalls = int(rec.get("kv_stall_steps") or 0)
        stall_cap = int(os.environ.get("PERF_GATE_DISAGG_STALLS", "5"))
        within = stalls <= stall_cap
        print(f"perf gate [serve_disagg stalls]: {stalls} decode steps "
              f"stalled on migration vs budget {stall_cap} -> "
              f"{'OK' if within else 'REGRESSION'}")
        record_verdict(leg, "kv_stall_steps", stalls, stall_cap, tol,
                       within)
        ok &= within
        base_goodput = float(
            rec.get("baseline_goodput_tokens_per_sec") or 0)
        if base_goodput <= 0:
            print("perf gate [serve_disagg]: record lacks the symmetric "
                  "baseline leg — hard fail")
            record_verdict(leg, "baseline_present", 0, 1, tol, False)
            ok = False
        else:
            # The disaggregated split must not lose to the symmetric
            # baseline it displaced (PERF_GATE_DISAGG_GOODPUT scales the
            # floor; 1.0 = must match or beat).
            floor_x = float(
                os.environ.get("PERF_GATE_DISAGG_GOODPUT", "1.0"))
            ok &= gate(rec.get("goodput_tokens_per_sec", 0),
                       base_goodput, floor_x, "disagg goodput vs baseline")
        base_p99 = float(rec.get("baseline_latency_p99_ms") or 0)
        if base_p99 > 0:
            # Tail latency must stay within PERF_GATE_DISAGG_P99 x the
            # symmetric baseline's p99 (default 1.5 — the CPU mesh's
            # tails are noisy; on hardware the split should WIN the
            # tail, since decode never queues behind a prefill burst).
            p99_cap = base_p99 * float(
                os.environ.get("PERF_GATE_DISAGG_P99", "1.5"))
            p99 = float(rec.get("latency_p99_ms") or 0)
            within = 0 < p99 <= p99_cap
            print(f"perf gate [serve_disagg p99]: {p99} ms vs cap "
                  f"{p99_cap:.2f} ms (baseline {base_p99} ms) -> "
                  f"{'OK' if within else 'REGRESSION'}")
            record_verdict(leg, "latency_p99_ms", p99, p99_cap, tol,
                           within)
            ok &= within
        return 0 if ok else 1

    if leg == "fused":
        # Fused compute-collective kernels (docs/fused-kernels.md):
        # correctness is hard-gated — the fused-vs-unfused parity probe
        # must have passed and the kernels must actually have engaged
        # (nonzero saved HBM round-trip) — then step time gates against
        # the trajectory's best (MINIMUM — the metric is ms/step, lower
        # is better).
        ok = True
        parity = rec.get("parity") or {}
        if not parity.get("ok"):
            print(f"perf gate [fused]: parity probe failed "
                  f"(max_rel_err {parity.get('max_rel_err')}) — "
                  f"hard fail")
            record_verdict("fused", "parity",
                           parity.get("max_rel_err", -1),
                           parity.get("tol", 0), tol, False)
            ok = False
        saved = float(rec.get("hbm_saved_bytes_per_step") or 0)
        if saved <= 0 or int(rec.get("fused_kernel_calls") or 0) < 1:
            print("perf gate [fused]: kernels never engaged (zero saved "
                  "HBM bytes / zero kernel calls) — hard fail")
            record_verdict("fused", "hbm_saved_bytes", saved, 1, tol,
                           False)
            ok = False
        else:
            record_verdict("fused", "hbm_saved_bytes", saved, 1, tol,
                           True)
        candidates = [
            (src, r["value"]) for src, r in trajectory_records()
            if r.get("metric") == rec.get("metric")
            and r.get("platform") == rec.get("platform")
            and isinstance(r.get("value"), (int, float))]
        if candidates:
            src, best = min(candidates, key=lambda c: c[1])
            within = rec["value"] <= best / tol
            print(f"perf gate [fused step_ms]: measured {rec['value']} "
                  f"vs trajectory best {best} ({src}), cap "
                  f"{best / tol:.4f} -> "
                  f"{'OK' if within else 'REGRESSION'}")
            record_verdict("fused", "step_ms", rec["value"], best, tol,
                           within)
            ok &= within
        else:
            print(f"perf gate [fused]: no recorded "
                  f"{rec.get('metric')!r} in the trajectory — step time "
                  f"not gated (pass)")
        return 0 if ok else 1

    if leg == "pp":
        # Pipeline-parallel leg (docs/pipeline.md): three hard gates —
        # (1) pipelined-vs-dense parity within its documented tolerance,
        # (2) measured bubble fraction at or under PERF_GATE_PP_BUBBLE
        # (default 1.0 = the analytic no-overlap GPipe bound
        # (S-1)/(M+S-1); the interleaved schedule sits well below it),
        # (3) the send-leg predicted-vs-measured wire-ms drift within
        # the PERF_GATE_COST_DRIFT contract — then throughput gates
        # against the trajectory like a train leg.
        ok = True
        par = rec.get("parity_rel_err")
        ptol = rec.get("parity_tol", 1e-4)
        if par is None or par > ptol:
            print(f"perf gate [pp]: parity {par} exceeds tolerance "
                  f"{ptol} — hard fail")
            record_verdict("pp", "parity_rel_err", par or -1, ptol, tol,
                           False)
            ok = False
        else:
            record_verdict("pp", "parity_rel_err", par, ptol, tol, True)
        bubble = rec.get("bubble_fraction")
        bound = rec.get("bubble_bound_gpipe")
        bcap = float(os.environ.get("PERF_GATE_PP_BUBBLE", "1.0"))
        if bubble is None or bound is None or bubble > bcap * bound:
            print(f"perf gate [pp bubble]: measured {bubble} vs cap "
                  f"{bcap} x gpipe bound {bound} — hard fail")
            record_verdict("pp", "bubble_fraction", bubble or -1,
                           (bound or 0) * bcap, tol, False)
            ok = False
        else:
            print(f"perf gate [pp bubble]: measured {bubble:.4f} <= "
                  f"{bcap} x gpipe bound {bound:.4f} -> OK")
            record_verdict("pp", "bubble_fraction", bubble, bound * bcap,
                           tol, True)
        wm = rec.get("wire_ms") or {}
        pred, mod = wm.get("predicted"), wm.get("modeled")
        drift_tol = float(os.environ.get("PERF_GATE_COST_DRIFT", "0.25"))
        if pred is None or mod is None or mod <= 0:
            print(f"perf gate [pp]: record lacks the send-leg wire_ms "
                  f"pair ({wm}) — hard fail")
            record_verdict("pp", "send_wire_ms_present", 0, 1, drift_tol,
                           False)
            ok = False
        else:
            drift = abs(pred - mod) / mod
            within = drift <= drift_tol
            print(f"perf gate [pp send drift]: predicted {pred:.4f} ms "
                  f"vs measured-model {mod:.4f} ms (|drift| {drift:.3f} "
                  f"vs cap {drift_tol}) -> "
                  f"{'OK' if within else 'REGRESSION'}")
            record_verdict("pp", "send_wire_ms_drift", drift, drift_tol,
                           drift_tol, within)
            ok &= within
        if not ok:
            return 1
        # fall through: throughput still gates against the trajectory

    if leg == "pp4d":
        # 4-D composition leg (docs/pipeline.md, docs/moe.md): PP x EP
        # x ZeRO-3 x quantized x overlap in ONE compiled step. Hard
        # gates: (1) pipelined-MoE-vs-dense parity within its recorded
        # tolerance, (2) the bubble-fill contract — the ZeRO-3 bucket
        # flights must actually have streamed into the pipeline's idle
        # ticks (nonzero filled_ticks / bubble_hidden_bytes when the
        # schedule has capacity) and the accounted fill bytes must
        # EQUAL the planner's prediction, (3) engaged a2a and send
        # wire, (4) the a2a predicted-vs-modeled wire-ms drift — then
        # throughput gates against the trajectory like a train leg.
        ok = True
        par = rec.get("parity_rel_err")
        ptol = rec.get("parity_tol", 1e-4)
        if par is None or par > ptol:
            print(f"perf gate [pp4d]: parity {par} exceeds tolerance "
                  f"{ptol} — hard fail")
            record_verdict("pp4d", "parity_rel_err", par or -1, ptol,
                           tol, False)
            ok = False
        else:
            record_verdict("pp4d", "parity_rel_err", par, ptol, tol,
                           True)
        cap = int(rec.get("fill_capacity_ticks") or 0)
        filled = int(rec.get("filled_ticks") or 0)
        hidden = float(rec.get("bubble_hidden_bytes") or 0)
        if cap > 0 and (filled < 1 or hidden <= 0):
            print(f"perf gate [pp4d fill]: schedule has {cap} idle "
                  f"ticks but fill never engaged (filled {filled}, "
                  f"hidden {hidden} B) — hard fail")
            record_verdict("pp4d", "bubble_fill_engaged", filled, 1,
                           tol, False)
            ok = False
        else:
            record_verdict("pp4d", "bubble_fill_engaged", filled,
                           min(1, cap), tol, True)
        pred_fill = float(rec.get("fill_predicted_bytes") or 0)
        fdrift = abs(pred_fill - hidden) / max(1.0, pred_fill)
        if fdrift > 1e-6:
            print(f"perf gate [pp4d fill]: accounted {hidden} B != "
                  f"predicted {pred_fill} B (drift {fdrift:.2e}) — "
                  f"hard fail")
            record_verdict("pp4d", "fill_bytes_drift", fdrift, 1e-6,
                           tol, False)
            ok = False
        else:
            print(f"perf gate [pp4d fill]: {filled}/{cap} idle ticks "
                  f"filled, {hidden:.0f} B accounted == predicted -> "
                  f"OK")
            record_verdict("pp4d", "fill_bytes_drift", fdrift, 1e-6,
                           tol, True)
        if float(rec.get("a2a_bytes") or 0) <= 0:
            print("perf gate [pp4d]: zero a2a wire bytes — the expert "
                  "exchange never engaged — hard fail")
            record_verdict("pp4d", "a2a_bytes", 0, 1, tol, False)
            ok = False
        if float(rec.get("pp_send_bytes") or 0) <= 0:
            print("perf gate [pp4d]: zero send-leg wire bytes — the "
                  "pipeline hop never engaged — hard fail")
            record_verdict("pp4d", "pp_send_bytes", 0, 1, tol, False)
            ok = False
        wm = rec.get("wire_ms") or {}
        pred, mod = wm.get("predicted"), wm.get("modeled")
        drift_tol = float(os.environ.get("PERF_GATE_COST_DRIFT", "0.25"))
        if pred is None or mod is None or mod <= 0:
            print(f"perf gate [pp4d]: record lacks the a2a wire_ms "
                  f"pair ({wm}) — hard fail")
            record_verdict("pp4d", "a2a_wire_ms_present", 0, 1,
                           drift_tol, False)
            ok = False
        else:
            drift = abs(pred - mod) / mod
            within = drift <= drift_tol
            print(f"perf gate [pp4d a2a drift]: predicted {pred:.4f} "
                  f"ms vs measured-model {mod:.4f} ms (|drift| "
                  f"{drift:.3f} vs cap {drift_tol}) -> "
                  f"{'OK' if within else 'REGRESSION'}")
            record_verdict("pp4d", "a2a_wire_ms_drift", drift,
                           drift_tol, drift_tol, within)
            ok &= within
        if not ok:
            return 1
        # fall through: throughput still gates against the trajectory

    if leg == "moe":
        # MoE leg (docs/moe.md): three hard gates — (1) the
        # forced-routing parity probe within its documented tolerance,
        # (2) dropped-token fraction at or under PERF_GATE_MOE_DROPPED
        # (default 0.25 — the capacity factor must actually carry the
        # traffic), (3) the a2a predicted-vs-measured wire-ms drift
        # within the PERF_GATE_COST_DRIFT contract — then throughput
        # gates against the trajectory like a train leg.
        ok = True
        par = rec.get("parity_rel_err")
        ptol = rec.get("parity_tol", 1e-5)
        if par is None or par > ptol:
            print(f"perf gate [moe]: parity {par} exceeds tolerance "
                  f"{ptol} — hard fail")
            record_verdict("moe", "parity_rel_err", par or -1, ptol, tol,
                           False)
            ok = False
        else:
            record_verdict("moe", "parity_rel_err", par, ptol, tol, True)
        dropped = rec.get("dropped_token_fraction")
        dcap = float(os.environ.get("PERF_GATE_MOE_DROPPED", "0.25"))
        if dropped is None or dropped > dcap:
            print(f"perf gate [moe dropped]: fraction {dropped} vs cap "
                  f"{dcap} — hard fail")
            record_verdict("moe", "dropped_token_fraction",
                           dropped if dropped is not None else -1, dcap,
                           tol, False)
            ok = False
        else:
            print(f"perf gate [moe dropped]: fraction {dropped:.4f} <= "
                  f"cap {dcap} -> OK")
            record_verdict("moe", "dropped_token_fraction", dropped,
                           dcap, tol, True)
        if float(rec.get("a2a_bytes") or 0) <= 0:
            print("perf gate [moe]: zero a2a wire bytes — the expert "
                  "exchange never engaged — hard fail")
            record_verdict("moe", "a2a_bytes", 0, 1, tol, False)
            ok = False
        wm = rec.get("wire_ms") or {}
        pred, mod = wm.get("predicted"), wm.get("modeled")
        drift_tol = float(os.environ.get("PERF_GATE_COST_DRIFT", "0.25"))
        if pred is None or mod is None or mod <= 0:
            print(f"perf gate [moe]: record lacks the a2a wire_ms pair "
                  f"({wm}) — hard fail")
            record_verdict("moe", "a2a_wire_ms_present", 0, 1, drift_tol,
                           False)
            ok = False
        else:
            drift = abs(pred - mod) / mod
            within = drift <= drift_tol
            print(f"perf gate [moe a2a drift]: predicted {pred:.4f} ms "
                  f"vs measured-model {mod:.4f} ms (|drift| {drift:.3f} "
                  f"vs cap {drift_tol}) -> "
                  f"{'OK' if within else 'REGRESSION'}")
            record_verdict("moe", "a2a_wire_ms_drift", drift, drift_tol,
                           drift_tol, within)
            ok &= within
        if not ok:
            return 1
        # fall through: throughput still gates against the trajectory

    if leg == "cost":
        # Cost-model drift gate (docs/cost-model.md): the analytic
        # planner's predicted wire-ms for this leg's knob set must stay
        # within PERF_GATE_COST_DRIFT (relative) of the measured side —
        # the traced program's actual wire bytes at the modeled
        # bandwidths. Drift means the byte model diverged from what the
        # compiler charges (a planner/accounting regression).
        wm = rec.get("wire_ms") or {}
        pred, mod = wm.get("predicted"), wm.get("modeled")
        drift_tol = float(os.environ.get("PERF_GATE_COST_DRIFT", "0.25"))
        if pred is None or mod is None or mod <= 0:
            print(f"perf gate [cost]: record lacks the wire_ms "
                  f"predicted/modeled pair ({wm}) — hard fail")
            record_verdict("cost", "wire_ms_present", 0, 1, drift_tol,
                           False)
            return 1
        drift = abs(pred - mod) / mod
        within = drift <= drift_tol
        print(f"perf gate [cost wire-ms drift]: predicted {pred:.4f} ms "
              f"vs measured-model {mod:.4f} ms (|drift| {drift:.3f} vs "
              f"cap {drift_tol}) -> "
              f"{'OK' if within else 'REGRESSION'}")
        record_verdict("cost", "wire_ms_drift", drift, drift_tol,
                       drift_tol, within)
        if not within:
            return 1
        # fall through: throughput still gates against the trajectory

    if leg.startswith("zero"):
        code = _zero_leg(rec, leg, tol)
        if code:
            return code
        # fall through: throughput still gates against the trajectory

    if leg == "soak":
        return _soak_leg(rec)

    if leg == "compile":
        return _compile_leg(rec)

    # Training legs: best same-platform value for this metric across the
    # recorded trajectory; an empty trajectory falls back to the
    # committed (self-seeding) train baseline instead of passing.
    candidates = [
        (src, r["value"]) for src, r in trajectory_records()
        if r.get("metric") == rec.get("metric")
        and r.get("platform") == rec.get("platform")
        and isinstance(r.get("value"), (int, float))]
    if not candidates:
        return _train_baseline_gate(rec, leg, tol, update)
    src, best = max(candidates, key=lambda c: c[1])
    print(f"perf gate [{leg}]: trajectory anchor {src}")
    return 0 if gate(rec["value"], best, tol, rec["metric"]) else 1


def _train_baseline_gate(rec, leg, tol, update):
    """Empty-trajectory fallback: gate against (or seed) the committed
    ``BENCH_train_baseline.json``, keyed ``metric|platform``."""
    metric, platform = rec.get("metric"), rec.get("platform")
    value = rec.get("value")
    if not isinstance(value, (int, float)):
        print(f"perf gate [{leg}]: record has no numeric 'value' — "
              f"cannot gate or seed")
        return 2
    key = f"{metric}|{platform}"
    baselines = {}
    if os.path.exists(TRAIN_BASELINE):
        try:
            with open(TRAIN_BASELINE) as f:
                baselines = json.load(f)
        except ValueError:
            print(f"perf gate [{leg}]: unreadable "
                  f"{os.path.basename(TRAIN_BASELINE)} — re-seeding")
            baselines = {}
    entry = baselines.get(key)
    if update or entry is None:
        baselines[key] = {"metric": metric, "platform": platform,
                          "value": value}
        with open(TRAIN_BASELINE, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"perf gate [{leg}]: no trajectory anchor for {key!r} — "
              f"seeded {os.path.basename(TRAIN_BASELINE)} at {value}")
        return 0
    print(f"perf gate [{leg}]: empty trajectory — baseline anchor "
          f"{os.path.basename(TRAIN_BASELINE)}[{key}]")
    return 0 if gate(value, entry["value"], tol, metric) else 1


def _compile_leg(rec):
    """Compile-once gate (docs/compile.md) over the
    scripts/compile_smoke.sh report: the warm rerun must pay ZERO
    compiles, its time-to-first-step must sit at least
    PERF_GATE_COMPILE_TTFS (default 0.30) below the cold run's, and the
    background-precompiled elastic resize must stall strictly under the
    cold-rebuild baseline measured in the same serve leg."""
    ttfs_cut = float(os.environ.get("PERF_GATE_COMPILE_TTFS", "0.30"))
    ok = True
    warm_compiles = rec.get("warm_compile_count")
    within = warm_compiles == 0
    print(f"perf gate [compile]: warm rerun compiled "
          f"{warm_compiles} executable(s) (cache "
          f"{rec.get('warm_compile_cache')}) -> "
          f"{'OK' if within else 'REGRESSION'}")
    record_verdict("compile", "warm_compile_count",
                   -1 if warm_compiles is None else warm_compiles, 0,
                   ttfs_cut, within)
    ok &= within
    reduction = rec.get("ttfs_reduction")
    within = reduction is not None and reduction >= ttfs_cut
    print(f"perf gate [compile]: warm TTFS {rec.get('ttfs_warm_ms')} ms "
          f"vs cold {rec.get('ttfs_cold_ms')} ms (reduction {reduction} "
          f"vs floor {ttfs_cut}) -> {'OK' if within else 'REGRESSION'}")
    record_verdict("compile", "ttfs_reduction",
                   -1.0 if reduction is None else reduction, ttfs_cut,
                   ttfs_cut, within)
    ok &= within
    bg = rec.get("resize_stall_ms_bg")
    cold = rec.get("resize_stall_ms_cold")
    if bg is None or cold is None:
        print("perf gate [compile]: report lacks the resize stall pair "
              "— the serve leg did not run — hard fail")
        record_verdict("compile", "resize_stall_present", 0, 1, ttfs_cut,
                       False)
        ok = False
    else:
        within = bg < cold
        print(f"perf gate [compile]: resize stall background "
              f"{bg} ms vs cold rebuild {cold} ms -> "
              f"{'OK' if within else 'REGRESSION'}")
        record_verdict("compile", "resize_stall_ms_bg", bg, cold,
                       ttfs_cut, within)
        ok &= within
    return 0 if ok else 1


def _soak_leg(rec):
    """The soak-report JSON (scripts/soak.py) is its own gate set: every
    named gate must pass; each one also lands in the verdict snapshot."""
    gates = rec.get("gates") or {}
    if not gates:
        print("perf gate [soak]: report has no gates — hard fail")
        record_verdict("soak", "report_present", 0.0, 1.0, 0.0, False)
        return 1
    failed = []
    for name, g in sorted(gates.items()):
        ok = bool(g.get("pass"))
        record_verdict("soak", name, 1.0 if ok else 0.0, 1.0, 0.0, ok)
        if not ok:
            failed.append(name)
            print(f"perf gate [soak]: gate {name} FAILED "
                  f"({g.get('detail')})")
    if failed:
        return 1
    print(f"perf gate [soak]: all {len(gates)} soak gates passed "
          f"(wall {rec.get('wall_s')}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
