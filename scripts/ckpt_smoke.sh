#!/usr/bin/env bash
# Checkpoint smoke (CI brick for docs/checkpoint.md): prove the
# kill→restore contract end to end on CPU meshes, in three processes:
#
#   1. shadow: uninterrupted ZeRO-3 run on the 8-device 2x4 mesh,
#      recording a per-step parameter digest (the truth trajectory);
#   2. train:  the same run checkpointing asynchronously every step,
#      hard-killed (os._exit) right after submitting the save at step 5 —
#      the background writer dies mid-flight, so only atomically
#      committed steps survive;
#   3. resume: a 4-device 2x2 mesh (DIFFERENT world size) restores the
#      latest committed step, reshards params + optimizer state 8→4, and
#      trains to completion — asserting the restored state and every
#      resumed step (including the first) are bit-identical to the
#      uninterrupted truth run, and that the process recorded nonzero
#      ckpt.commits.
#
# Bitwise comparability across worlds is by construction (integer data +
# dyadic hyperparameters → exact fp32 reductions); see the worker's
# docstring. Runtime ~1 min.
#
# Usage: scripts/ckpt_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="${CKPT_SMOKE_TMP:-$(mktemp -d)}"
mkdir -p "$TMP"
trap '[ -z "${CKPT_SMOKE_TMP:-}" ] && rm -rf "$TMP"' EXIT
echo "== ckpt smoke: artifacts in $TMP ==" >&2

WORKER=scripts/_ckpt_smoke_worker.py
KILL_RC=17

run_phase() {  # run_phase <phase> <devices> <mesh CxL>
    JAX_PLATFORMS=cpu \
    JAX_ENABLE_X64=1 \
    XLA_FLAGS="--xla_force_host_platform_device_count=$2" \
    python "$WORKER" "$1" "$TMP" "$3"
}

echo "== phase 1/3: uninterrupted truth run (world 8) ==" >&2
run_phase shadow 8 2x4

echo "== phase 2/3: checkpointing run, killed mid-save (world 8) ==" >&2
rc=0
run_phase train 8 2x4 || rc=$?
if [ "$rc" -ne "$KILL_RC" ]; then
    echo "ckpt smoke: train phase exited rc=$rc, expected the injected" \
         "kill (rc=$KILL_RC)" >&2
    exit 1
fi
committed=$(ls -d "$TMP"/ckpt/step_*/ 2>/dev/null | wc -l)
if [ "$committed" -lt 1 ]; then
    echo "ckpt smoke: no committed checkpoint survived the kill" >&2
    exit 1
fi
echo "ckpt smoke: kill landed (rc=$rc), $committed committed step(s)" >&2

echo "== phase 3/3: restore + reshard at world 4, resume to the end ==" >&2
run_phase resume 4 2x2

echo "ckpt smoke OK" >&2
