#!/usr/bin/env bash
# Compile-once smoke (CI brick for docs/compile.md): run the SAME train
# leg twice on the 2x4 virtual CPU mesh against a fresh persistent
# executable cache. The cold run populates it (framework executable
# index + XLA persistent cache); the warm rerun — a fresh process —
# must pay ZERO compiles (compile_count == 0, every executable a disk
# hit) and reach its first step at least COMPILE_SMOKE_TTFS_CUT
# (default 30%) faster than cold. Then the serve resize leg: the
# background-precompiled elastic resize must stall strictly less than
# the cold-rebuild baseline (bench.py hard-gates that itself; the
# report carries both numbers). Runtime ~3 min.
#
# Usage: scripts/compile_smoke.sh [--report /path/report.json]
#   COMPILE_SMOKE_TMP=/path scripts/compile_smoke.sh  # keep the cache
#   COMPILE_SMOKE_SERVE=0 scripts/compile_smoke.sh    # train legs only
set -euo pipefail
cd "$(dirname "$0")/.."

REPORT=""
if [ "${1:-}" = "--report" ]; then
    REPORT="$2"; shift 2
fi

TMP="${COMPILE_SMOKE_TMP:-$(mktemp -d)}"
mkdir -p "$TMP"
trap '[ -z "${COMPILE_SMOKE_TMP:-}" ] && rm -rf "$TMP"' EXIT
echo "== compile smoke: executable cache in $TMP/cache ==" >&2

BENCH_ARGS=(--platform cpu --cpu-devices 8 --mesh-shape 2x4
    --model resnet18 --batch-size 2 --image-size 64
    --num-warmup 1 --num-iters 2 --num-batches-per-iter 2)

echo "== compile smoke: cold leg (empty cache) ==" >&2
COLD=$(JAX_PLATFORMS=cpu HOROVOD_COMPILE_CACHE_DIR="$TMP/cache" \
    python bench.py "${BENCH_ARGS[@]}" | tail -n 1)
echo "$COLD"

echo "== compile smoke: warm leg (fresh process, populated cache) ==" >&2
WARM=$(JAX_PLATFORMS=cpu HOROVOD_COMPILE_CACHE_DIR="$TMP/cache" \
    python bench.py "${BENCH_ARGS[@]}" | tail -n 1)
echo "$WARM"

SERVE="null"
if [ "${COMPILE_SMOKE_SERVE:-1}" = "1" ]; then
    echo "== compile smoke: serve resize leg (background precompile vs cold rebuild) ==" >&2
    SERVE=$(JAX_PLATFORMS=cpu HOROVOD_COMPILE_CACHE_DIR="$TMP/cache-serve" \
        python bench.py --serve --platform cpu --cpu-devices 8 \
        --serve-requests "${COMPILE_SMOKE_SERVE_REQUESTS:-24}" \
        --serve-rate 50 | tail -n 1)
    echo "$SERVE"
fi

python - "$COLD" "$WARM" "$SERVE" "${REPORT:-}" <<'EOF'
import json
import sys

cold, warm = json.loads(sys.argv[1]), json.loads(sys.argv[2])
serve = json.loads(sys.argv[3]) if sys.argv[3] != "null" else None
import os
cut = float(os.environ.get("COMPILE_SMOKE_TTFS_CUT", "0.30"))

assert cold["compile_count"] > 0, \
    "cold leg compiled nothing — the cache dir was not fresh"
assert warm["compile_count"] == 0, (
    f"warm rerun COMPILED {warm['compile_count']} executable(s) — the "
    f"persistent cache missed (cache {warm['compile_cache']})")
assert warm["compile_cache"]["hits"] > 0, \
    f"warm rerun never hit the cache: {warm['compile_cache']}"
t_cold = cold["time_to_first_step_ms"]
t_warm = warm["time_to_first_step_ms"]
reduction = 1.0 - t_warm / t_cold
assert reduction >= cut, (
    f"warm TTFS {t_warm:.0f} ms is only {100 * reduction:.1f}% below "
    f"cold {t_cold:.0f} ms (need >= {100 * cut:.0f}%)")
report = {
    "ttfs_cold_ms": round(t_cold, 3),
    "ttfs_warm_ms": round(t_warm, 3),
    "ttfs_reduction": round(reduction, 4),
    "warm_compile_count": warm["compile_count"],
    "cold_compile_count": cold["compile_count"],
    "warm_compile_cache": warm["compile_cache"],
    "compile_ms_total_cold": cold["compile_ms_total"],
}
if serve is not None:
    # bench.py already hard-gated bg < cold; re-assert and record.
    bg = serve["resize_stall_ms_bg"]
    cold_stall = serve["resize_stall_ms_cold"]
    assert bg < cold_stall, f"resize stall bg {bg} >= cold {cold_stall}"
    report.update({
        "resize_stall_ms_bg": bg,
        "resize_stall_ms_cold": cold_stall,
        "resize_stall_speedup": serve.get("resize_stall_speedup"),
        "serve_ttfs_ms": serve.get("time_to_first_step_ms"),
    })
print(f"compile smoke: warm TTFS {t_warm:.0f} ms vs cold "
      f"{t_cold:.0f} ms (-{100 * reduction:.1f}%), warm compiles 0 "
      f"({warm['compile_cache']['hits']} cache hits)"
      + (f"; resize stall bg {report['resize_stall_ms_bg']:.0f} ms vs "
         f"cold {report['resize_stall_ms_cold']:.0f} ms"
         if serve is not None else ""))
if sys.argv[4]:
    with open(sys.argv[4], "w") as f:
        json.dump(report, f, indent=1)
    print(f"compile smoke: report written to {sys.argv[4]}")
EOF

echo "COMPILE SMOKE: OK" >&2
