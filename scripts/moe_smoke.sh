#!/usr/bin/env bash
# Expert-parallel MoE smoke (docs/moe.md): the `bench.py --moe` A/B on
# the 8-device virtual CPU mesh — a dedicated hvd_ep mesh axis of 4
# expert groups, dispatch/combine lowered as wire-plan a2a legs.
#
# Asserts: rc 0 (the bench itself hard-fails on forced-routing parity
# loss), a passed parity probe, nonzero `comm.moe.bytes{hop}` /
# a2a_bytes accounting, a populated per-expert load histogram, a bounded
# dropped-token fraction, zero a2a cost-model drift, and balanced MOE:*
# spans in a timeline probe. Runtime ~1 min.
#
# Usage: scripts/moe_smoke.sh [extra bench.py args...]
#   MOE_SMOKE_KNOBS="--quantized" scripts/moe_smoke.sh   # int8+EF a2a
set -euo pipefail
cd "$(dirname "$0")/.."

TL_DIR=$(mktemp -d)
trap 'rm -rf "$TL_DIR"' EXIT

OUT=$(JAX_PLATFORMS=cpu HOROVOD_TIMELINE="$TL_DIR/moe_timeline.json" \
    python bench.py --moe 4 ${MOE_SMOKE_KNOBS:-} \
    --platform cpu --cpu-devices 8 \
    --num-iters 2 --num-batches-per-iter 2 \
    "$@" | tail -n 1)
echo "$OUT"

python - "$OUT" "$TL_DIR/moe_timeline.json" <<'EOF'
import json
import sys

rec = json.loads(sys.argv[1])
assert rec["metric"].startswith("moe"), rec["metric"]
assert rec["parity_rel_err"] <= rec["parity_tol"], \
    f"parity failed: {rec['parity_rel_err']} > {rec['parity_tol']}"
assert rec["a2a_bytes"] > 0, "zero a2a wire bytes"
assert rec["a2a_calls"] >= 2, "dispatch+combine never both engaged"
counters = rec["metrics_snapshot"]["counters"]
moe_bytes = {k: v for k, v in counters.items()
             if k.startswith("comm.moe.bytes")}
assert moe_bytes and all(v > 0 for v in moe_bytes.values()), \
    f"comm.moe.bytes missing or zero: {moe_bytes}"
load = {k: v for k, v in counters.items()
        if k.startswith("moe.expert_tokens")}
assert len(load) == rec["moe"]["experts"] and sum(load.values()) > 0, \
    f"expert-load histogram not populated: {load}"
assert rec["dropped_token_fraction"] <= 0.25, \
    f"dropped fraction {rec['dropped_token_fraction']} > 0.25"
drift = abs(rec["wire_ms"]["predicted"] - rec["wire_ms"]["modeled"]) \
    / max(1e-9, rec["wire_ms"]["modeled"])
assert drift <= 0.25, f"a2a cost-model drift {drift}"

# MOE:* spans balance in the timeline (strict vocabulary check).
from horovod_tpu.monitor import span_audit

audit = span_audit.audit_spans(sys.argv[2], prefix="MOE:",
                               require_balanced=True,
                               require_spans=True, strict=True)
n = sum(audit.count.values())
assert audit.count.get("MOE:DISPATCH", 0) > 0, audit.count
assert audit.count.get("MOE:COMBINE", 0) > 0, audit.count
print(f"moe smoke OK: parity {rec['parity_rel_err']:.2e}, "
      f"{rec['a2a_calls']} a2a exchanges "
      f"({rec['a2a_bytes'] / 1e3:.1f} kB/step/dev), dropped "
      f"{rec['dropped_token_fraction']:.4f}, drift {drift:.4f}, "
      f"{n} balanced MOE spans, load {rec['expert_load']}")
EOF
