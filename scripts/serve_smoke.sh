#!/usr/bin/env bash
# Serve-path smoke: a short Poisson trace on the 8-device virtual CPU
# mesh through `bench.py --serve` (continuous batching + paged KV cache +
# one elastic replica resize down/up mid-trace, docs/serving.md).
# Asserts: rc 0 (the bench itself aborts on dropped requests or a
# decode/full-context parity failure), nonzero goodput, and a clean
# drain (requests_completed == requests). Runtime ~1 min.
#
# Usage: scripts/serve_smoke.sh [extra bench.py args...]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(JAX_PLATFORMS=cpu python bench.py --serve --platform cpu \
    --cpu-devices 8 \
    --serve-requests "${SERVE_SMOKE_REQUESTS:-12}" \
    --serve-rate "${SERVE_SMOKE_RATE:-50}" \
    "$@" | tail -n 1)
echo "$OUT"

python - "$OUT" <<'EOF'
import json
import sys

rec = json.loads(sys.argv[1])
assert rec["metric"] == "gpt_serve_goodput_tokens_per_sec", rec["metric"]
assert rec["goodput_tokens_per_sec"] > 0, "zero goodput"
assert rec["tokens_per_sec"] > 0, "zero throughput"
assert rec["requests_dropped"] == 0, f"dropped {rec['requests_dropped']}"
assert rec["requests_completed"] == rec["requests"], "trace did not drain"
assert rec["latency_p99_ms"] >= rec["latency_p50_ms"] > 0
print(f"serve smoke OK: goodput {rec['goodput_tokens_per_sec']} tok/s, "
      f"p50 {rec['latency_p50_ms']} ms, p99 {rec['latency_p99_ms']} ms, "
      f"{len(rec['resize_events'])} resizes, clean shutdown")
EOF
