#!/bin/sh
# The CI shape of the soak gauntlet: one preemption + one flap + one
# resize against the durable elastic run, training legs only (no serve
# trace, no replan leg). Fast enough for the perf-gate `soak` leg;
# scripts/soak.sh is the full gauntlet. Exit code = failed gates.
set -e
cd "$(dirname "$0")/.."
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
JAX_PLATFORMS=cpu \
exec python scripts/soak.py --smoke "$@"
