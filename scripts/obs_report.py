#!/usr/bin/env python
"""Join a Timeline JSON and a metrics JSONL into one observability report.

Usage:
    scripts/obs_report.py --timeline TL.json --metrics METRICS.jsonl \
        [--flight FLIGHT_DIR] [--json OUT.json]

Produce the artifacts with any bench/training run::

    HOROVOD_TIMELINE=tl.json HOROVOD_METRICS_JSONL=metrics.jsonl \
        python bench.py --overlap ...

Report sections (docs/observability.md):

* **Phase breakdown** — per-activity span time from the Timeline
  (OVERLAP:*, SERVE:*, PROFILE:*, ...), audited for B/E balance
  (monitor/span_audit.py);
* **Stall table** — every STALL:* instant with rank attribution, plus
  the stall.warnings counters;
* **Overlap** — comm_hidden_fraction recomputed from the registry's
  comm.wire.* gauges (overlap / (ici + dcn) bytes of the last traced
  program) — must reproduce the bench-reported value within 1%;
* **Wire budget** — measured per-device wire bytes per hop vs the
  modeled transfer time at HOROVOD_BENCH_ICI_GBPS/DCN_GBPS (the same
  bandwidth model behind bench.py's step_time_breakdown), and the DCN
  fp-equivalent reduction of the quantized wire;
* **Straggler table** — per-rank per-phase skew from the
  ``straggler.*`` gauges (monitor/straggler.py), detections, step-skew
  gauges, and the cost-model-backed ``link.health{hop}`` scores;
* **Flight records** — with ``--flight DIR`` (or
  HOROVOD_FLIGHT_RECORDER_DIR set), the ``scripts/postmortem.py``
  cross-rank join of any dumps present.

Exit 0 on success, 2 on usage/artifact errors. ``--json`` additionally
writes the report as one machine-readable dict (what obs_smoke.sh
asserts on).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.monitor.span_audit import (  # noqa: E402
    SpanImbalanceError, audit_spans, load_events)


def load_metrics(path):
    """All snapshots in the JSONL; the LAST one is the report's state."""
    snaps = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "metrics":
                snaps.append(rec)
    return snaps


def hidden_fraction(gauges):
    total = (gauges.get("comm.wire.ici_bytes", 0.0)
             + gauges.get("comm.wire.dcn_bytes", 0.0)
             + gauges.get("comm.wire.pod_bytes", 0.0))
    if not total:
        return 0.0
    return gauges.get("comm.wire.overlap_bytes", 0.0) / total


def straggler_section(counters, gauges):
    """Per-rank per-phase matrix + detections + link health from the
    registry families monitor/straggler.py publishes."""
    import re

    phase_re = re.compile(
        r"^straggler\.phase_ms\{phase=([^,}]+),rank=(\d+)\}$")
    matrix = {}
    for k, v in gauges.items():
        m = phase_re.match(k)
        if m:
            matrix.setdefault(int(m.group(2)), {})[m.group(1)] = v
    det_re = re.compile(
        r"^straggler\.detected\{phase=([^,}]+),rank=(\d+)\}$")
    detected = [{"rank": int(m.group(2)), "phase": m.group(1), "count": v}
                for k, v in counters.items()
                for m in [det_re.match(k)] if m]
    skew = {k.split("phase=", 1)[1].rstrip("}"): v
            for k, v in gauges.items() if k.startswith("step.skew_ms{")}
    link = {k.split("hop=", 1)[1].rstrip("}"): v
            for k, v in gauges.items() if k.startswith("link.health{")}
    degraded = {k.split("hop=", 1)[1].rstrip("}"): v
                for k, v in counters.items()
                if k.startswith("straggler.link_degraded{")}
    return {
        "phase_ms_by_rank": {str(r): matrix[r] for r in sorted(matrix)},
        "detected": sorted(detected,
                           key=lambda d: (d["rank"], d["phase"])),
        "step_skew_ms": skew,
        "link_health": link,
        "link_degraded": degraded,
    }


def flight_section(flight_dir):
    """The postmortem join of any flight dumps present (None when the
    directory is unset/empty — a healthy run has no dumps)."""
    if not flight_dir or not os.path.isdir(flight_dir):
        return None
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "postmortem.py")
    spec = importlib.util.spec_from_file_location("_postmortem", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.build_report(flight_dir)
    return report if report["dumps"] else None


def prometheus_discovery(metrics_path):
    """The ``<jsonl>.port`` endpoint-discovery file the PrometheusSink
    leaves when HOROVOD_METRICS_PORT resolves a port (0 = ephemeral)."""
    try:
        with open(metrics_path + ".port") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_report(timeline_path, metrics_path, flight_dir=None):
    events = load_events(timeline_path)
    try:
        audit = audit_spans(events)
        balanced, imbalance = True, None
    except SpanImbalanceError as e:
        audit = audit_spans(events, require_balanced=False)
        balanced, imbalance = False, str(e)

    snaps = load_metrics(metrics_path)
    if not snaps:
        raise SystemExit(f"no metrics snapshots in {metrics_path}")
    snap = snaps[-1]
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    hists = snap.get("histograms", {})

    stalls = [
        {"name": ev["name"], "ts_us": ev.get("ts"),
         **(ev.get("args") or {})}
        for ev in events
        if ev.get("ph") == "i" and str(ev.get("name", "")).startswith("STALL:")]
    stall_warnings = sum(v for k, v in counters.items()
                         if k.startswith("stall.warnings"))

    ici = gauges.get("comm.wire.ici_bytes", 0.0)
    dcn = gauges.get("comm.wire.dcn_bytes", 0.0)
    dcn_fp = gauges.get("comm.wire.dcn_bytes_fp", 0.0)
    pod = gauges.get("comm.wire.pod_bytes", 0.0)
    from horovod_tpu.plan.accounting import bench_gbps

    ici_gbps, dcn_gbps, pod_gbps = bench_gbps()
    return {
        "timeline": os.path.abspath(timeline_path),
        "metrics": os.path.abspath(metrics_path),
        "snapshots": len(snaps),
        "events": len(events),
        "spans_balanced": balanced,
        "span_imbalance": imbalance,
        "total_spans": audit.total_spans,
        "phase_time_us": {k: round(v, 1)
                          for k, v in sorted(audit.by_phase().items())},
        "activity_time_us": {k: round(v, 1)
                             for k, v in sorted(audit.duration_us.items())},
        "stalls": stalls,
        "stall_warnings": stall_warnings,
        "comm_hidden_fraction": hidden_fraction(gauges),
        "wire_budget": {
            "ici_bytes_per_step_device": ici,
            "dcn_bytes_per_step_device": dcn,
            "dcn_bytes_fp_equiv": dcn_fp,
            "dcn_reduction": (dcn_fp / dcn) if dcn else None,
            "pod_bytes_per_step_device": pod,
            "fused_hbm_saved_bytes": gauges.get(
                "comm.wire.fused_hbm_saved_bytes", 0.0),
            "modeled_wire_ms": round(
                (ici / (ici_gbps * 1e9) + dcn / (dcn_gbps * 1e9)
                 + pod / (pod_gbps * 1e9)) * 1e3, 4),
            "model": {"ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps,
                      "pod_gbps": pod_gbps},
        },
        "streamed_buckets": gauges.get("comm.wire.streamed_buckets", 0.0),
        "bucket_latency_hist": hists.get("comm.bucket.latency_us"),
        "step_time_hist": hists.get("step.time_ms"),
        "eager_calls": {k: v for k, v in counters.items()
                        if k.startswith("comm.eager.calls")},
        "serve": {k: v for k, v in {**counters, **gauges}.items()
                  if k.startswith("serve.")},
        "straggler": straggler_section(counters, gauges),
        "flight": flight_section(
            flight_dir or os.environ.get("HOROVOD_FLIGHT_RECORDER_DIR")),
        "prometheus": prometheus_discovery(metrics_path),
    }


def print_report(r):
    w = print
    w(f"== observability report ==")
    w(f"timeline: {r['timeline']} ({r['events']} events, "
      f"{r['total_spans']} spans, "
      f"{'balanced' if r['spans_balanced'] else 'IMBALANCED: ' + str(r['span_imbalance'])})")
    w(f"metrics:  {r['metrics']} ({r['snapshots']} snapshots)")
    w("")
    w("-- phase time breakdown (host spans) --")
    if r["activity_time_us"]:
        for name, us in sorted(r["activity_time_us"].items(),
                               key=lambda kv: -kv[1]):
            w(f"  {name:<32} {us / 1e3:10.3f} ms")
    else:
        w("  (no spans)")
    w("")
    w("-- stalls --")
    if r["stalls"]:
        for s in r["stalls"]:
            w(f"  {s['name']:<40} rank {s.get('rank', '?')} "
              f"elapsed {s.get('elapsed_secs', '?')}s "
              f"missing {s.get('missing_ranks', '?')}")
    w(f"  stall warnings (registry): {r['stall_warnings']:g}")
    w("")
    w("-- overlap --")
    w(f"  comm_hidden_fraction: {r['comm_hidden_fraction']:.4f} "
      f"({r['streamed_buckets']:g} streamed buckets)")
    w("")
    w("-- wire budget (per step, per device) --")
    wb = r["wire_budget"]
    w(f"  ICI {wb['ici_bytes_per_step_device'] / 1e6:.3f} MB, "
      f"DCN {wb['dcn_bytes_per_step_device'] / 1e6:.3f} MB"
      + (f" (fp-equiv {wb['dcn_bytes_fp_equiv'] / 1e6:.3f} MB, "
         f"{wb['dcn_reduction']:.2f}x reduction)"
         if wb["dcn_reduction"] else ""))
    w(f"  modeled transfer: {wb['modeled_wire_ms']} ms at "
      f"ICI {wb['model']['ici_gbps']} GB/s / DCN {wb['model']['dcn_gbps']} GB/s")
    if r["serve"]:
        w("")
        w("-- serve --")
        for k, v in sorted(r["serve"].items()):
            w(f"  {k:<40} {v:g}")
    st = r.get("straggler") or {}
    if st.get("phase_ms_by_rank") or st.get("link_health"):
        w("")
        w("-- stragglers --")
        for rank, phases in st.get("phase_ms_by_rank", {}).items():
            row = "  ".join(f"{p}={ms:.1f}ms"
                            for p, ms in sorted(phases.items()) if ms)
            w(f"  rank {rank:<4} {row or '(no phases recorded)'}")
        for p, v in sorted(st.get("step_skew_ms", {}).items()):
            w(f"  skew {p:<12} {v:.2f} ms (max - median across ranks)")
        for d in st.get("detected", []):
            w(f"  DETECTED rank {d['rank']} phase {d['phase']} "
              f"(x{d['count']:g})")
        for hop, v in sorted(st.get("link_health", {}).items()):
            flag = "  DEGRADED" if st.get("link_degraded", {}).get(hop) \
                else ""
            w(f"  link {hop:<4} health {v:.2f} "
              f"(measured/predicted wire-ms){flag}")
    if r.get("prometheus"):
        w("")
        w(f"-- prometheus: {r['prometheus'].get('endpoint')} "
          f"(pid {r['prometheus'].get('pid')}) --")
    if r.get("flight"):
        fl = r["flight"]
        w("")
        w(f"-- flight records ({fl['dumps']} dump(s) in "
          f"{fl['directory']}) --")
        for key, row in fl["ranks"].items():
            mark = " CRASHED" if row["crashed"] else ""
            w(f"  {key:<14} reason={row['reason']} "
              f"last_step={row['last_step']}{mark}")
        if fl["crashed_ranks"]:
            w(f"  crashing rank(s): {', '.join(fl['crashed_ranks'])}; "
              f"last common step {fl['last_common_step']}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeline", required=True)
    ap.add_argument("--metrics", required=True)
    ap.add_argument("--flight", default=None,
                    help="flight-record dump dir (default: "
                         "HOROVOD_FLIGHT_RECORDER_DIR)")
    ap.add_argument("--json", help="also write the report dict here")
    args = ap.parse_args()
    for p in (args.timeline, args.metrics):
        if not os.path.exists(p):
            ap.error(f"no such file: {p}")
    report = build_report(args.timeline, args.metrics,
                          flight_dir=args.flight)
    print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
