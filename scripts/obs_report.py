#!/usr/bin/env python
"""Join a Timeline JSON and a metrics JSONL into one observability report.

Usage:
    scripts/obs_report.py --timeline TL.json --metrics METRICS.jsonl \
        [--json OUT.json]

Produce the artifacts with any bench/training run::

    HOROVOD_TIMELINE=tl.json HOROVOD_METRICS_JSONL=metrics.jsonl \
        python bench.py --overlap ...

Report sections (docs/observability.md):

* **Phase breakdown** — per-activity span time from the Timeline
  (OVERLAP:*, SERVE:*, PROFILE:*, ...), audited for B/E balance
  (monitor/span_audit.py);
* **Stall table** — every STALL:* instant with rank attribution, plus
  the stall.warnings counters;
* **Overlap** — comm_hidden_fraction recomputed from the registry's
  comm.wire.* gauges (overlap / (ici + dcn) bytes of the last traced
  program) — must reproduce the bench-reported value within 1%;
* **Wire budget** — measured per-device wire bytes per hop vs the
  modeled transfer time at HOROVOD_BENCH_ICI_GBPS/DCN_GBPS (the same
  bandwidth model behind bench.py's step_time_breakdown), and the DCN
  fp-equivalent reduction of the quantized wire.

Exit 0 on success, 2 on usage/artifact errors. ``--json`` additionally
writes the report as one machine-readable dict (what obs_smoke.sh
asserts on).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.monitor.span_audit import (  # noqa: E402
    SpanImbalanceError, audit_spans, load_events)


def load_metrics(path):
    """All snapshots in the JSONL; the LAST one is the report's state."""
    snaps = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "metrics":
                snaps.append(rec)
    return snaps


def hidden_fraction(gauges):
    total = (gauges.get("comm.wire.ici_bytes", 0.0)
             + gauges.get("comm.wire.dcn_bytes", 0.0)
             + gauges.get("comm.wire.pod_bytes", 0.0))
    if not total:
        return 0.0
    return gauges.get("comm.wire.overlap_bytes", 0.0) / total


def build_report(timeline_path, metrics_path):
    events = load_events(timeline_path)
    try:
        audit = audit_spans(events)
        balanced, imbalance = True, None
    except SpanImbalanceError as e:
        audit = audit_spans(events, require_balanced=False)
        balanced, imbalance = False, str(e)

    snaps = load_metrics(metrics_path)
    if not snaps:
        raise SystemExit(f"no metrics snapshots in {metrics_path}")
    snap = snaps[-1]
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    hists = snap.get("histograms", {})

    stalls = [
        {"name": ev["name"], "ts_us": ev.get("ts"),
         **(ev.get("args") or {})}
        for ev in events
        if ev.get("ph") == "i" and str(ev.get("name", "")).startswith("STALL:")]
    stall_warnings = sum(v for k, v in counters.items()
                         if k.startswith("stall.warnings"))

    ici = gauges.get("comm.wire.ici_bytes", 0.0)
    dcn = gauges.get("comm.wire.dcn_bytes", 0.0)
    dcn_fp = gauges.get("comm.wire.dcn_bytes_fp", 0.0)
    pod = gauges.get("comm.wire.pod_bytes", 0.0)
    from horovod_tpu.plan.accounting import bench_gbps

    ici_gbps, dcn_gbps, pod_gbps = bench_gbps()
    return {
        "timeline": os.path.abspath(timeline_path),
        "metrics": os.path.abspath(metrics_path),
        "snapshots": len(snaps),
        "events": len(events),
        "spans_balanced": balanced,
        "span_imbalance": imbalance,
        "total_spans": audit.total_spans,
        "phase_time_us": {k: round(v, 1)
                          for k, v in sorted(audit.by_phase().items())},
        "activity_time_us": {k: round(v, 1)
                             for k, v in sorted(audit.duration_us.items())},
        "stalls": stalls,
        "stall_warnings": stall_warnings,
        "comm_hidden_fraction": hidden_fraction(gauges),
        "wire_budget": {
            "ici_bytes_per_step_device": ici,
            "dcn_bytes_per_step_device": dcn,
            "dcn_bytes_fp_equiv": dcn_fp,
            "dcn_reduction": (dcn_fp / dcn) if dcn else None,
            "pod_bytes_per_step_device": pod,
            "fused_hbm_saved_bytes": gauges.get(
                "comm.wire.fused_hbm_saved_bytes", 0.0),
            "modeled_wire_ms": round(
                (ici / (ici_gbps * 1e9) + dcn / (dcn_gbps * 1e9)
                 + pod / (pod_gbps * 1e9)) * 1e3, 4),
            "model": {"ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps,
                      "pod_gbps": pod_gbps},
        },
        "streamed_buckets": gauges.get("comm.wire.streamed_buckets", 0.0),
        "bucket_latency_hist": hists.get("comm.bucket.latency_us"),
        "step_time_hist": hists.get("step.time_ms"),
        "eager_calls": {k: v for k, v in counters.items()
                        if k.startswith("comm.eager.calls")},
        "serve": {k: v for k, v in {**counters, **gauges}.items()
                  if k.startswith("serve.")},
    }


def print_report(r):
    w = print
    w(f"== observability report ==")
    w(f"timeline: {r['timeline']} ({r['events']} events, "
      f"{r['total_spans']} spans, "
      f"{'balanced' if r['spans_balanced'] else 'IMBALANCED: ' + str(r['span_imbalance'])})")
    w(f"metrics:  {r['metrics']} ({r['snapshots']} snapshots)")
    w("")
    w("-- phase time breakdown (host spans) --")
    if r["activity_time_us"]:
        for name, us in sorted(r["activity_time_us"].items(),
                               key=lambda kv: -kv[1]):
            w(f"  {name:<32} {us / 1e3:10.3f} ms")
    else:
        w("  (no spans)")
    w("")
    w("-- stalls --")
    if r["stalls"]:
        for s in r["stalls"]:
            w(f"  {s['name']:<40} rank {s.get('rank', '?')} "
              f"elapsed {s.get('elapsed_secs', '?')}s "
              f"missing {s.get('missing_ranks', '?')}")
    w(f"  stall warnings (registry): {r['stall_warnings']:g}")
    w("")
    w("-- overlap --")
    w(f"  comm_hidden_fraction: {r['comm_hidden_fraction']:.4f} "
      f"({r['streamed_buckets']:g} streamed buckets)")
    w("")
    w("-- wire budget (per step, per device) --")
    wb = r["wire_budget"]
    w(f"  ICI {wb['ici_bytes_per_step_device'] / 1e6:.3f} MB, "
      f"DCN {wb['dcn_bytes_per_step_device'] / 1e6:.3f} MB"
      + (f" (fp-equiv {wb['dcn_bytes_fp_equiv'] / 1e6:.3f} MB, "
         f"{wb['dcn_reduction']:.2f}x reduction)"
         if wb["dcn_reduction"] else ""))
    w(f"  modeled transfer: {wb['modeled_wire_ms']} ms at "
      f"ICI {wb['model']['ici_gbps']} GB/s / DCN {wb['model']['dcn_gbps']} GB/s")
    if r["serve"]:
        w("")
        w("-- serve --")
        for k, v in sorted(r["serve"].items()):
            w(f"  {k:<40} {v:g}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeline", required=True)
    ap.add_argument("--metrics", required=True)
    ap.add_argument("--json", help="also write the report dict here")
    args = ap.parse_args()
    for p in (args.timeline, args.metrics):
        if not os.path.exists(p):
            ap.error(f"no such file: {p}")
    report = build_report(args.timeline, args.metrics)
    print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
